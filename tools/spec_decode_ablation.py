"""Speculative-decoding ablation: same workload with spec on vs off.

Mirrors ``prefix_cache_ablation.py``: runs an identical
repetitive-suffix workload (prompts ending in a repeated pattern — the
extraction/code/quoting shape prompt-lookup targets) through two
engines — one with ``--speculative-ngram-k``, one without — and
reports decode tokens/s, the measured acceptance rate, and a
greedy-equivalence check that the speculative engine's outputs are
bit-identical to the baseline's.  Prints ONE JSON line, like bench.py.

Two rounds per engine: round 1 compiles (prefill buckets + the fused
decode scan / verify program), round 2 is the measured round.  The
headline is ``decode_speedup`` (spec tokens/s over baseline tokens/s on
the measured round) next to ``acceptance_rate`` — speculative decoding
is a bet that acceptance is high enough to beat the fused-decode scan,
and this tool prints both sides of the bet.

Invocation (CPU, synthetic weights — no checkpoint needed):

    JAX_PLATFORMS=cpu python tools/spec_decode_ablation.py

or against a real model / the TPU:

    python tools/spec_decode_ablation.py --model meta-llama/Llama-2-7b-hf
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def build_prompts(
    n: int, prompt_len: int, pattern_len: int
) -> list[list[int]]:
    """Prompts with a distinct preamble and a repeated-pattern suffix:
    the proposer can look the continuation up, the preamble keeps the
    requests (and their KV) distinct."""
    prompts = []
    for i in range(n):
        pattern = [(17 * i + 3 * j) % 700 + 1 for j in range(pattern_len)]
        preamble_len = max(prompt_len - 2 * pattern_len, 0)
        preamble = [(11 * i + 7 * j) % 900 + 1 for j in range(preamble_len)]
        p = (preamble + pattern + pattern)[:prompt_len]
        prompts.append(p)
    return prompts


def _run_round(engine, prompts, tag: str, max_tokens: int):
    from vllm_distributed_tpu.sampling_params import SamplingParams

    sp = SamplingParams(
        temperature=0.0, max_tokens=max_tokens, ignore_eos=True
    )
    for i, p in enumerate(prompts):
        engine.add_request(
            f"{tag}{i}", prompt_token_ids=p, sampling_params=sp
        )
    done: dict[str, object] = {}
    first_token_at = None
    t0 = time.perf_counter()
    while engine.has_unfinished_requests():
        for out in engine.step():
            if first_token_at is None and out.outputs[0].token_ids:
                first_token_at = time.perf_counter()
            if out.finished:
                done[out.request_id] = out
    elapsed = time.perf_counter() - t0
    outs = [done[f"{tag}{i}"] for i in range(len(prompts))]
    tokens = sum(len(o.outputs[0].token_ids) for o in outs)
    # Decode throughput excludes prefill: measure from the first token.
    decode_s = (
        time.perf_counter() - first_token_at
        if first_token_at is not None
        else elapsed
    )
    return (
        [list(o.outputs[0].token_ids) for o in outs],
        tokens,
        elapsed,
        max(decode_s, 1e-9),
    )


def _measure_mode(model: str, spec_k: int, args) -> dict:
    from vllm_distributed_tpu.config import EngineArgs
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine

    engine = LLMEngine.from_engine_args(
        EngineArgs(
            model=model,
            skip_tokenizer_init=True,
            load_format=args.load_format,
            num_kv_pages=args.num_kv_pages,
            page_size=args.page_size,
            max_num_seqs=args.num_prompts,
            max_model_len=args.prompt_len + args.max_tokens + 8,
            num_decode_steps=args.num_decode_steps,
            speculative_ngram_k=spec_k,
        )
    )
    prompts = build_prompts(
        args.num_prompts, args.prompt_len, args.pattern_len
    )
    try:
        outputs, _, _, _ = _run_round(
            engine, prompts, "c", args.max_tokens
        )  # compile round
        sched = engine.scheduler
        drafted0, accepted0 = (
            sched.spec_drafted_tokens,
            sched.spec_accepted_tokens,
        )
        warm_outputs, tokens, elapsed, decode_s = _run_round(
            engine, prompts, "w", args.max_tokens
        )
        assert warm_outputs == outputs, "warm round diverged"
        drafted = sched.spec_drafted_tokens - drafted0
        accepted = sched.spec_accepted_tokens - accepted0
        return {
            "spec_ngram_k": spec_k,
            "output_tokens": tokens,
            "round_s": round(elapsed, 3),
            "decode_s": round(decode_s, 3),
            "tokens_per_sec": round(tokens / elapsed, 1),
            "decode_tokens_per_sec": round(tokens / decode_s, 1),
            "drafted_tokens": drafted,
            "accepted_tokens": accepted,
            "acceptance_rate": (
                round(accepted / drafted, 4) if drafted else 0.0
            ),
            "outputs": outputs,
        }
    finally:
        engine.shutdown()


def run_ablation(model: str, args) -> dict:
    """On/off comparison; importable by bench.py.  The returned dict's
    ``gate_pass`` field asserts the bet: with acceptance >= the
    ``--gate-acceptance`` floor the speculative engine must deliver
    >= ``--gate-speedup`` x decode tokens/s (below the floor the gate
    abstains — drafts that never match cannot win, and the fused-decode
    fallback keeps the loss bounded)."""
    off = _measure_mode(model, 0, args)
    on = _measure_mode(model, args.spec_k, args)
    identical = on.pop("outputs") == off.pop("outputs")
    speedup = round(
        on["decode_tokens_per_sec"]
        / max(off["decode_tokens_per_sec"], 1e-9),
        3,
    )
    gated = on["acceptance_rate"] >= args.gate_acceptance
    result = {
        "bench": "spec_decode_ablation",
        "model": model,
        "num_prompts": args.num_prompts,
        "prompt_len": args.prompt_len,
        "pattern_len": args.pattern_len,
        "max_tokens": args.max_tokens,
        "off": off,
        "on": on,
        "acceptance_rate": on["acceptance_rate"],
        "decode_speedup": speedup,
        "outputs_bit_identical": identical,
        "gate_applicable": gated,
        "gate_pass": bool(
            identical and (not gated or speedup >= args.gate_speedup)
        ),
    }
    return result


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--model", default=None, help="default: tiny synthetic llama"
    )
    ap.add_argument(
        "--load-format", default=None, choices=["auto", "dummy"]
    )
    ap.add_argument("--num-prompts", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=96)
    ap.add_argument(
        "--pattern-len",
        type=int,
        default=24,
        help="repeated-suffix pattern length (the draftable tail)",
    )
    ap.add_argument("--max-tokens", type=int, default=48)
    ap.add_argument("--spec-k", type=int, default=4)
    ap.add_argument("--num-decode-steps", type=int, default=8)
    ap.add_argument("--num-kv-pages", type=int, default=1024)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument(
        "--gate-acceptance",
        type=float,
        default=0.5,
        help="acceptance floor below which the speedup gate abstains",
    )
    ap.add_argument(
        "--gate-speedup",
        type=float,
        default=1.3,
        help="required decode tokens/s multiple when the gate applies",
    )
    ap.add_argument(
        "--strict",
        action="store_true",
        help="exit 1 when the gate fails (bit-identity always fatal)",
    )
    args = ap.parse_args()

    model = args.model
    if model is None:
        from vllm_distributed_tpu.testing import write_llama_config

        model = write_llama_config()
        args.load_format = args.load_format or "dummy"
    args.load_format = args.load_format or "auto"

    result = run_ablation(model, args)
    print(json.dumps(result))
    if not result["outputs_bit_identical"]:
        sys.exit(2)
    if args.strict and not result["gate_pass"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
