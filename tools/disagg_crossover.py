"""Transfer-vs-recompute crossover sweep for disaggregated prefill
(ISSUE 15) — the bench that picks VDT_DISAGG_MIN_PROMPT_TOKENS.

For each prompt length L the harness measures, on a 2-replica mock (or
real-CPU) pair over real loopback HTTP:

- **recompute**: time-to-first-frame of an ``/internal/resume`` on the
  decode replica with NO transferred pages — the decode side re-prefills
  all L tokens (the PR 8 fallback path).
- **transfer**: the full hand-off — prefill-only request on the prefill
  replica, per-layer KV export→import streaming, commit, then
  time-to-first-frame of the resume that attaches the imported pages as
  computed.  Reported as the transfer wall plus the resume TTFT.

The crossover is the smallest L where the hand-off beats recompute;
below it the router should serve the prompt on the decode pool like
today.  ``VDT_MOCK_TOKEN_SECONDS`` makes mock prefill cost proportional
to L so the sweep has a real slope without chips (the default here);
on hardware, run against real replicas with ``--no-mock-env``.

Usage::

    python -m tools.disagg_crossover [--lengths 64,128,...] [--json]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import sys
import tempfile
import time

_MOCK_ENV = {
    "VDT_MOCK_TOKEN_SEQ": "1",
    # Prefill cost proportional to scheduled tokens: the recompute arm
    # scales with L, the transfer arm with page bytes.
    "VDT_MOCK_TOKEN_SECONDS": "0.002",
}


async def _sweep(args, model_dir: str) -> dict:
    import aiohttp

    from tests.mock_worker import MockUniProcExecutor
    from vllm_distributed_tpu.config import EngineArgs
    from vllm_distributed_tpu.engine.async_llm import AsyncLLM
    from vllm_distributed_tpu.entrypoints.openai.api_server import (
        build_app,
        init_app_state,
        serve_http,
    )
    from vllm_distributed_tpu.utils import get_open_port

    page_size = 16
    max_len = 2 * (max(args.lengths) + 8)

    def mk_engine() -> AsyncLLM:
        return AsyncLLM.from_engine_args(
            EngineArgs(
                model=model_dir,
                skip_tokenizer_init=True,
                load_format="dummy",
                num_kv_pages=4 * (max_len // page_size),
                page_size=page_size,
                max_model_len=max_len,
                num_decode_steps=1,
                enable_prefix_caching=True,
                distributed_executor_backend=MockUniProcExecutor,
            )
        )

    engines = [mk_engine(), mk_engine()]
    runners = []
    urls = []
    for i, role in enumerate(("prefill", "decode")):
        state = init_app_state(
            engines[i],
            served_model_name="crossover",
            replica_id=f"xo-{role}",
            role=role,
        )
        port = get_open_port()
        runners.append(
            await serve_http(build_app(state), host="127.0.0.1", port=port)
        )
        urls.append(f"http://127.0.0.1:{port}")
    prefill_url, decode_url = urls

    timeout = aiohttp.ClientTimeout(total=120)
    rows = []
    try:
        async with aiohttp.ClientSession(timeout=timeout) as session:

            async def post(url, payload):
                async with session.post(url, json=payload) as resp:
                    body = await resp.json()
                    if resp.status != 200:
                        raise RuntimeError(f"{url}: HTTP {resp.status} {body}")
                    return body

            async def resume_ttft(
                rid: str, prompt: list[int], emitted: list[int]
            ) -> float:
                """Time to the first token frame of an /internal/resume
                on the decode replica."""
                t0 = time.perf_counter()
                first = None
                async with session.post(
                    f"{decode_url}/internal/resume",
                    json={
                        "request_id": rid,
                        "kind": "completions",
                        "body": {
                            "prompt": prompt,
                            "max_tokens": 4,
                            "temperature": 0.0,
                            "ignore_eos": True,
                            "stream": True,
                        },
                        "prompt_token_ids": prompt,
                        "emitted_token_ids": emitted,
                    },
                ) as resp:
                    resp.raise_for_status()
                    # Drain fully (clean server-side close); the stamp
                    # is the FIRST token frame.
                    async for raw in resp.content:
                        line = raw.decode().strip()
                        if line.startswith("data:") and line[5:].strip() not in (
                            "",
                            "[DONE]",
                        ):
                            obj = json.loads(line[5:].strip())
                            if first is None and obj.get("token_ids"):
                                first = time.perf_counter() - t0
                return first if first is not None else time.perf_counter() - t0

            async def prefill_only(
                prompt: list[int], tag: str
            ) -> tuple[str, list[int]]:
                """Run the prefill-only hop directly; returns
                (kv_handle, emitted_token_ids)."""
                handle = None
                emitted: list[int] = []
                async with session.post(
                    f"{prefill_url}/v1/completions",
                    json={
                        "prompt": prompt,
                        "max_tokens": 8,
                        "temperature": 0.0,
                        "ignore_eos": True,
                        "stream": True,
                    },
                    headers={
                        "X-VDT-Router": "1",
                        "X-VDT-Disagg": "prefill",
                    },
                ) as resp:
                    resp.raise_for_status()
                    async for raw in resp.content:
                        line = raw.decode().strip()
                        if not line.startswith("data:"):
                            continue
                        payload = line[5:].strip()
                        if payload == "[DONE]":
                            break
                        obj = json.loads(payload)
                        for ch in obj.get("choices") or ():
                            emitted += ch.get("vdt_token_ids") or []
                            if ch.get("vdt_kv_handle"):
                                handle = ch["vdt_kv_handle"]
                if handle is None:
                    raise RuntimeError(f"{tag}: no kv handle")
                return handle, emitted

            for length in args.lengths:
                # Distinct token alphabets per arm so the decode
                # replica's prefix cache can't cross-contaminate arms.
                p_rec = [(3 * length + j) % 700 + 1 for j in range(length)]
                p_xfer = [(5 * length + j) % 700 + 100 for j in range(length)]

                # Arm 1: recompute-resume (prefill happened elsewhere;
                # decode re-prefills everything).
                t_rec = await resume_ttft(f"rec-{length}", p_rec, [])

                # Arm 2: the real hand-off.
                handle, emitted = await prefill_only(p_xfer, f"x-{length}")
                t0 = time.perf_counter()
                begin = await post(
                    f"{decode_url}/internal/kv",
                    {"op": "begin", "prompt_token_ids": p_xfer},
                )
                tid = begin.get("transfer_id")
                layer = 0
                num_layers = None
                while tid and (num_layers is None or layer < num_layers):
                    chunk = await post(
                        f"{prefill_url}/internal/kv/export",
                        {
                            "handle": handle,
                            "layer_start": layer,
                            "layer_count": args.chunk_layers,
                        },
                    )
                    num_layers = chunk["num_layers"]
                    await post(
                        f"{decode_url}/internal/kv",
                        {
                            "op": "chunk",
                            "transfer_id": tid,
                            "layers": chunk["layers"],
                        },
                    )
                    layer += len(chunk["layers"])
                adopted = 0
                if tid:
                    commit = await post(
                        f"{decode_url}/internal/kv",
                        {"op": "commit", "transfer_id": tid},
                    )
                    adopted = commit.get("adopted_tokens", 0)
                await post(
                    f"{prefill_url}/internal/kv/release",
                    {"handle": handle},
                )
                transfer_s = time.perf_counter() - t0
                t_resume = await resume_ttft(
                    f"xfer-{length}", p_xfer, emitted[:1]
                )
                rows.append(
                    {
                        "prompt_tokens": length,
                        "recompute_ttft_s": round(t_rec, 4),
                        "transfer_s": round(transfer_s, 4),
                        "handoff_ttft_s": round(transfer_s + t_resume, 4),
                        "adopted_tokens": adopted,
                    }
                )
    finally:
        for runner in runners:
            await runner.cleanup()
        for engine in engines:
            engine.shutdown()

    crossover = next(
        (
            r["prompt_tokens"]
            for r in rows
            if r["handoff_ttft_s"] < r["recompute_ttft_s"]
        ),
        None,
    )
    return {
        "mode": "disagg_crossover",
        "rows": rows,
        "recommended_min_prompt_tokens": crossover,
    }


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--lengths",
        type=lambda s: [int(x) for x in s.split(",")],
        default=[64, 128, 256, 512, 1024],
        help="comma-separated prompt lengths to sweep",
    )
    parser.add_argument("--chunk-layers", type=int, default=4)
    parser.add_argument(
        "--no-mock-env",
        action="store_true",
        help="do not install the deterministic mock cost model env "
        "(real-hardware runs)",
    )
    parser.add_argument("--json", action="store_true", dest="as_json")
    args = parser.parse_args()

    saved = {k: os.environ.get(k) for k in _MOCK_ENV}
    if not args.no_mock_env:
        os.environ.update(_MOCK_ENV)
    tmpdir = tempfile.mkdtemp(prefix="vdt_disagg_xo_")
    try:
        from vllm_distributed_tpu.testing import write_llama_config

        model_dir = write_llama_config(os.path.join(tmpdir, "m"))
        report = asyncio.new_event_loop().run_until_complete(
            _sweep(args, model_dir)
        )
    finally:
        if not args.no_mock_env:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        shutil.rmtree(tmpdir, ignore_errors=True)
    if args.as_json:
        print(json.dumps(report))
        return
    print(f"{'tokens':>8} {'recompute_s':>12} {'handoff_s':>10} {'adopted':>8}")
    for r in report["rows"]:
        print(
            f"{r['prompt_tokens']:>8} {r['recompute_ttft_s']:>12.4f} "
            f"{r['handoff_ttft_s']:>10.4f} {r['adopted_tokens']:>8}"
        )
    rec = report["recommended_min_prompt_tokens"]
    print(
        f"recommended VDT_DISAGG_MIN_PROMPT_TOKENS: "
        f"{rec if rec is not None else 'no crossover in sweep'}"
    )
    sys.exit(0)


if __name__ == "__main__":
    main()
