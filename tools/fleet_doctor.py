"""Ranked fleet diagnosis from the sentinel surfaces (ISSUE 20).

The command-line companion to ``/router/timeline`` and
``/router/alerts``: point it at a live router (or at saved JSON dumps
of both endpoints) and it prints a ranked diagnosis — which replica
looks wrong, on which signal, how hard, which alerts named it, and the
timeline events that surround each alert so the probable cause is on
the same screen as the symptom.

    python tools/fleet_doctor.py http://localhost:9000
    python tools/fleet_doctor.py --alerts alerts.json --timeline timeline.json

Exit status: 0 when nothing is flagged, 1 when at least one replica is
degraded (anomaly score past threshold or named by an alert) or an SLO
class is burning past threshold — so the tool doubles as a scriptable
health check.

Pure stdlib; the inputs are exactly the shapes served by the router:
``/router/alerts`` -> {"alerts": [...], "burn": {...}, "burn_peak": x,
"anomaly_scores": {rid: {signal: z}}} and ``/router/timeline`` ->
{"events": [...]}.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request

#: Timeline events within this many wall-clock seconds of an alert are
#: shown as correlated context under it.
CONTEXT_WINDOW_S = 30.0

#: Alert kinds that name a replica as the problem.
_REPLICA_ALERT_KINDS = ("replica_degraded", "replica_unreachable")


def load_json(source: str) -> dict:
    """Read one endpoint dump from a file path, URL, or ``-`` (stdin)."""
    if source.startswith(("http://", "https://")):
        with urllib.request.urlopen(source, timeout=30) as resp:
            return json.load(resp)
    if source == "-":
        return json.load(sys.stdin)
    with open(source) as f:
        return json.load(f)


def worst_signal(per_signal: dict) -> tuple[str, float]:
    """(signal, z) with the largest magnitude; ("", 0.0) when empty."""
    if not per_signal:
        return "", 0.0
    signal = max(per_signal, key=lambda s: abs(per_signal[s]))
    return signal, per_signal[signal]


def rank_replicas(
    scores: dict, alerts: list[dict], threshold: float
) -> list[dict]:
    """Rank replicas most-suspect first.

    The rank key is (named by an alert, worst |z|): an alert is a
    confirmed edge-triggered detection, a score is the live reading —
    a replica that recovered keeps its alert history but its score
    decays, and it should still outrank a mildly-noisy healthy one.
    """
    alert_counts: dict[str, int] = {}
    for alert in alerts:
        rid = alert.get("replica_id")
        if rid and alert.get("kind") in _REPLICA_ALERT_KINDS:
            alert_counts[rid] = alert_counts.get(rid, 0) + 1

    rows = []
    for rid in sorted(set(scores) | set(alert_counts)):
        signal, z = worst_signal(scores.get(rid) or {})
        rows.append({
            "replica_id": rid,
            "worst_signal": signal,
            "worst_z": round(z, 2),
            "alerts": alert_counts.get(rid, 0),
            "flagged": abs(z) >= threshold or alert_counts.get(rid, 0) > 0,
        })
    rows.sort(key=lambda r: (r["alerts"] > 0, abs(r["worst_z"])), reverse=True)
    return rows


def burning_classes(burn: dict, threshold: float) -> list[tuple[str, dict]]:
    """SLO classes whose burn exceeds threshold on EVERY window —
    the same all-windows conjunction the alerting rule uses."""
    out = []
    for cls, windows in sorted((burn or {}).items()):
        if windows and all(r >= threshold for r in windows.values()):
            out.append((cls, windows))
    return out


def correlate(alert: dict, events: list[dict], window: float = CONTEXT_WINDOW_S) -> list[dict]:
    """Timeline events within ``window`` seconds of the alert, the
    alert's own ``alert_*`` mirror excluded."""
    ts = alert.get("ts_wall")
    if ts is None:
        return []
    out = []
    for ev in events:
        ev_ts = ev.get("ts_wall")
        if ev_ts is None or abs(ev_ts - ts) > window:
            continue
        if ev.get("kind", "").startswith("alert_"):
            continue
        out.append(ev)
    return out


def diagnose(
    alerts_payload: dict,
    timeline_payload: dict,
    threshold: float = 4.0,
    burn_threshold: float = 10.0,
) -> dict:
    """Pure core: turn the two endpoint payloads into a diagnosis dict
    (rendered by :func:`format_report`, asserted by tests)."""
    alerts = alerts_payload.get("alerts") or []
    scores = alerts_payload.get("anomaly_scores") or {}
    events = timeline_payload.get("events") or []

    replicas = rank_replicas(scores, alerts, threshold)
    burning = burning_classes(alerts_payload.get("burn") or {}, burn_threshold)
    findings = []
    for alert in alerts:
        findings.append({
            "alert": alert,
            "context": correlate(alert, events),
        })
    return {
        "replicas": replicas,
        "flagged": [r["replica_id"] for r in replicas if r["flagged"]],
        "burning_classes": burning,
        "burn_peak": alerts_payload.get("burn_peak", 0.0),
        "findings": findings,
        "n_events": len(events),
    }


def _fmt_event(ev: dict) -> str:
    bits = [f"{ev.get('ts_wall', 0):.3f}", ev.get("origin") or ev.get("source", "?"), ev.get("kind", "?")]
    if ev.get("replica_id"):
        bits.append(f"replica={ev['replica_id']}")
    attrs = ev.get("attrs") or {}
    for key in sorted(attrs)[:4]:
        bits.append(f"{key}={attrs[key]}")
    return "  ".join(str(b) for b in bits)


def format_report(diag: dict) -> str:
    lines = ["fleet doctor", "============", ""]

    if diag["burning_classes"]:
        lines.append("SLO burn (all windows past threshold):")
        for cls, windows in diag["burning_classes"]:
            burns = "  ".join(f"{w}={r:.1f}x" for w, r in sorted(windows.items()))
            lines.append(f"  class {cls}: {burns}")
    else:
        lines.append(f"SLO burn: no class past threshold (peak {diag['burn_peak']:.1f}x)")
    lines.append("")

    if diag["replicas"]:
        lines.append("replica ranking (most suspect first):")
        lines.append(f"  {'replica':<24} {'worst signal':<20} {'z':>8} {'alerts':>7}  verdict")
        for row in diag["replicas"]:
            verdict = "DEGRADED" if row["flagged"] else "ok"
            lines.append(
                f"  {row['replica_id']:<24} {row['worst_signal'] or '-':<20}"
                f" {row['worst_z']:>8.2f} {row['alerts']:>7}  {verdict}"
            )
    else:
        lines.append("replica ranking: no anomaly scores (pool too small or sentinel off)")
    lines.append("")

    if diag["findings"]:
        lines.append(f"alerts ({len(diag['findings'])}), each with timeline context (±{CONTEXT_WINDOW_S:.0f}s):")
        for finding in diag["findings"]:
            alert = finding["alert"]
            who = alert.get("replica_id") or alert.get("slo_class") or "-"
            lines.append(f"  [{alert.get('ts_wall', 0):.3f}] {alert.get('kind', '?')} -> {who}")
            for ev in finding["context"][-8:]:
                lines.append(f"      {_fmt_event(ev)}")
    else:
        lines.append("alerts: none")
    lines.append("")

    if diag["flagged"]:
        lines.append("diagnosis: DEGRADED -> " + ", ".join(diag["flagged"]))
    else:
        lines.append(f"diagnosis: healthy ({diag['n_events']} timeline events scanned)")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "router", nargs="?",
        help="router base URL (fetches /router/alerts and /router/timeline)",
    )
    parser.add_argument("--alerts", help="saved /router/alerts JSON (file, URL, or -)")
    parser.add_argument("--timeline", help="saved /router/timeline JSON (file, URL, or -)")
    parser.add_argument(
        "--threshold", type=float, default=4.0,
        help="|z| past this flags a replica (default 4, matches VDT_SENTINEL_ANOMALY_THRESHOLD)",
    )
    parser.add_argument(
        "--burn-threshold", type=float, default=10.0,
        help="burn rate past this on every window flags a class (default 10)",
    )
    parser.add_argument("--json", action="store_true", help="emit the diagnosis as JSON")
    args = parser.parse_args(argv)

    if args.router:
        base = args.router.rstrip("/")
        alerts_payload = load_json(f"{base}/router/alerts")
        timeline_payload = load_json(f"{base}/router/timeline")
    elif args.alerts or args.timeline:
        alerts_payload = load_json(args.alerts) if args.alerts else {}
        timeline_payload = load_json(args.timeline) if args.timeline else {}
    else:
        parser.error("need a router URL or --alerts/--timeline dumps")

    diag = diagnose(
        alerts_payload, timeline_payload,
        threshold=args.threshold, burn_threshold=args.burn_threshold,
    )
    if args.json:
        print(json.dumps(diag, indent=2, sort_keys=True))
    else:
        print(format_report(diag))
    return 1 if (diag["flagged"] or diag["burning_classes"]) else 0


if __name__ == "__main__":
    sys.exit(main())
