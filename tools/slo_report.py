"""Per-class SLO attainment / goodput table from an /slo scrape.

The terminal companion to the ISSUE 12 accounting layer: point it at a
replica's ``/slo``, a router's ``/router/slo``, or a saved JSON dump of
either, and it prints the per-class attainment table the autoscaler
(ROADMAP item 5) will eventually consume programmatically.

    python tools/slo_report.py http://localhost:8000/slo
    python tools/slo_report.py http://localhost:8080/router/slo
    python tools/slo_report.py slo_dump.json

Pure stdlib.  Both input shapes carry a ``classes`` map; the replica
form holds raw counters + histograms (percentiles are computed here via
engine/slo.py), the router form arrives pre-merged with percentiles.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request

from vllm_distributed_tpu.engine.slo import LogBucketHistogram


def load_view(source: str) -> dict:
    """Read a view from a file path, '-' (stdin), or an http(s) URL."""
    if source.startswith(("http://", "https://")):
        with urllib.request.urlopen(source, timeout=30) as resp:
            return json.load(resp)
    if source == "-":
        return json.load(sys.stdin)
    with open(source) as f:
        return json.load(f)


def _pct(hist_dict: dict | None, q: float) -> float | None:
    if not hist_dict:
        return None
    return LogBucketHistogram.from_dict(hist_dict).percentile_ms(q)


def class_rows(view: dict) -> list[dict]:
    """Normalize either shape into rows, one per SLO class."""
    rows = []
    for cls, d in sorted((view.get("classes") or {}).items()):
        requests = int(d.get("requests", 0))

        def ratio(key):
            return int(d.get(key, 0)) / requests if requests else None

        rows.append(
            {
                "class": cls,
                "requests": requests,
                "goodput": int(d.get("goodput", 0)),
                "goodput_ratio": d.get("goodput_ratio", ratio("goodput")),
                "ttft_attain": ratio("ttft_attained"),
                "itl_attain": ratio("itl_attained"),
                "ttft_target_ms": d.get("ttft_target_ms"),
                "itl_target_ms": d.get("itl_target_ms"),
                "ttft_p50_ms": d.get(
                    "ttft_p50_ms", _pct(d.get("ttft_hist"), 0.5)
                ),
                "ttft_p99_ms": d.get(
                    "ttft_p99_ms", _pct(d.get("ttft_hist"), 0.99)
                ),
                "itl_p50_ms": d.get(
                    "itl_p50_ms", _pct(d.get("itl_hist"), 0.5)
                ),
                "itl_p99_ms": d.get(
                    "itl_p99_ms", _pct(d.get("itl_hist"), 0.99)
                ),
            }
        )
    return rows


def _fmt(value, pct=False) -> str:
    if value is None:
        return "-"
    if pct:
        return f"{value * 100:.1f}%"
    return f"{value:.1f}"


def _fmt_delta(value, pct=False) -> str:
    if value is None:
        return "-"
    sign = "+" if value >= 0 else ""
    if pct:
        return f"{sign}{value * 100:.1f}pp"
    return f"{sign}{value:.1f}"


def diff_rows(base: list[dict], cur: list[dict]) -> list[dict]:
    """Per-class deltas, current minus baseline — the review artifact
    for a QoS/scheduler change (ISSUE 16 satellite).

    Two shapes of comparison fall out of one computation:

    - two scrapes of the SAME server (before/after a run): the counter
      deltas are the run window, so ``window_goodput_ratio`` is the
      goodput of exactly the traffic in between;
    - two INDEPENDENT runs (A/B dumps): cumulative counters "reset"
      between scrapes, detected per class, and the window becomes the
      whole current run.

    Ratio/percentile columns are current-minus-baseline either way.
    """
    base_by = {r["class"]: r for r in base}
    out = []

    def delta(cur_v, base_v):
        if cur_v is None or base_v is None:
            return None
        return cur_v - base_v

    for r in cur:
        b = base_by.get(r["class"])
        d_requests = r["requests"] - (b["requests"] if b else 0)
        d_goodput = r["goodput"] - (b["goodput"] if b else 0)
        if d_requests < 0:
            # Counters went backwards: not the same accumulation
            # (restart or an independent A/B dump) — the current
            # scrape IS the window.
            d_requests, d_goodput = r["requests"], r["goodput"]
        out.append(
            {
                "class": r["class"],
                "d_requests": d_requests,
                "d_goodput": d_goodput,
                "window_goodput_ratio": (
                    d_goodput / d_requests if d_requests > 0 else None
                ),
                "d_goodput_ratio": delta(
                    r["goodput_ratio"],
                    b["goodput_ratio"] if b else None,
                ),
                "d_ttft_attain": delta(
                    r["ttft_attain"], b["ttft_attain"] if b else None
                ),
                "d_itl_attain": delta(
                    r["itl_attain"], b["itl_attain"] if b else None
                ),
                "d_ttft_p99_ms": delta(
                    r["ttft_p99_ms"], b["ttft_p99_ms"] if b else None
                ),
                "d_itl_p99_ms": delta(
                    r["itl_p99_ms"], b["itl_p99_ms"] if b else None
                ),
            }
        )
    seen = {r["class"] for r in cur}
    for r in base:
        if r["class"] not in seen:
            # Present at baseline, absent now: surface it rather than
            # silently dropping a class from the review artifact.
            out.append(
                {
                    "class": r["class"],
                    "d_requests": 0,
                    "d_goodput": 0,
                    "window_goodput_ratio": None,
                    "d_goodput_ratio": None,
                    "d_ttft_attain": None,
                    "d_itl_attain": None,
                    "d_ttft_p99_ms": None,
                    "d_itl_p99_ms": None,
                }
            )
    out.sort(key=lambda r: r["class"])
    return out


def render_diff_table(rows: list[dict]) -> str:
    headers = (
        "class", "d_reqs", "d_goodput", "window_gp",
        "d_gp_ratio", "d_ttft_ok", "d_itl_ok",
        "d_ttft_p99", "d_itl_p99",
    )
    table = [headers]
    for r in rows:
        table.append(
            (
                r["class"],
                str(r["d_requests"]),
                str(r["d_goodput"]),
                _fmt(r["window_goodput_ratio"], pct=True),
                _fmt_delta(r["d_goodput_ratio"], pct=True),
                _fmt_delta(r["d_ttft_attain"], pct=True),
                _fmt_delta(r["d_itl_attain"], pct=True),
                _fmt_delta(r["d_ttft_p99_ms"]),
                _fmt_delta(r["d_itl_p99_ms"]),
            )
        )
    widths = [
        max(len(row[i]) for row in table) for i in range(len(headers))
    ]
    lines = []
    for i, row in enumerate(table):
        lines.append(
            "  ".join(cell.ljust(w) for cell, w in zip(row, widths))
        )
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def render_table(rows: list[dict]) -> str:
    headers = (
        "class", "reqs", "goodput", "ttft_ok", "itl_ok",
        "ttft_p50", "ttft_p99", "tgt", "itl_p50", "itl_p99", "tgt",
    )
    table = [headers]
    for r in rows:
        table.append(
            (
                r["class"],
                str(r["requests"]),
                _fmt(r["goodput_ratio"], pct=True),
                _fmt(r["ttft_attain"], pct=True),
                _fmt(r["itl_attain"], pct=True),
                _fmt(r["ttft_p50_ms"]),
                _fmt(r["ttft_p99_ms"]),
                _fmt(r["ttft_target_ms"]),
                _fmt(r["itl_p50_ms"]),
                _fmt(r["itl_p99_ms"]),
                _fmt(r["itl_target_ms"]),
            )
        )
    widths = [
        max(len(row[i]) for row in table) for i in range(len(headers))
    ]
    lines = []
    for i, row in enumerate(table):
        lines.append(
            "  ".join(cell.ljust(w) for cell, w in zip(row, widths))
        )
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="per-SLO-class attainment/goodput table from an "
        "/slo or /router/slo scrape (latency columns in ms)"
    )
    parser.add_argument(
        "source", help="URL, JSON file path, or '-' for stdin"
    )
    parser.add_argument(
        "--json", action="store_true", help="emit rows as JSON"
    )
    parser.add_argument(
        "--diff",
        metavar="BASELINE",
        default=None,
        help="baseline scrape (URL, JSON file, or '-'): render "
        "per-class goodput/attainment DELTAS, source minus baseline — "
        "pp columns are percentage-point changes, window_gp is the "
        "goodput of just the traffic between the two scrapes",
    )
    args = parser.parse_args(argv)
    rows = class_rows(load_view(args.source))
    if args.diff is not None:
        rows = diff_rows(class_rows(load_view(args.diff)), rows)
        if args.json:
            print(json.dumps(rows, indent=2))
        elif not rows:
            print("no SLO classes observed yet")
        else:
            print(render_diff_table(rows))
        return 0
    if args.json:
        print(json.dumps(rows, indent=2))
    elif not rows:
        print("no SLO classes observed yet")
    else:
        print(render_table(rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
