# TPU-native serving image (the analog of the reference's
# vllm/vllm-openai base + flashinfer pip layer, /root/reference/Dockerfile:1-6
# — there the CUDA engine comes from the base image; here the engine IS this
# repo, so the image is just python + jax[tpu] + the package).
#
# Build on a TPU VM (libtpu comes from the jax[tpu] extra):
#   docker build -t vllm-distributed-tpu .
FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
        curl ca-certificates \
    && rm -rf /var/lib/apt/lists/*

# jax[tpu] pins jaxlib+libtpu to matching versions; -f pulls libtpu wheels.
RUN pip install --no-cache-dir "jax[tpu]" \
        -f https://storage.googleapis.com/jax-releases/libtpu_releases.html

WORKDIR /srv/vllm-distributed-tpu
COPY pyproject.toml ./
COPY vllm_distributed_tpu ./vllm_distributed_tpu
RUN pip install --no-cache-dir .

# XLA persistent compile cache lives on the cache volume
# (docker-compose.yml mounts ${ROOT_CACHE_PATH} -> /root/.cache, the same
# contract as the reference's compiled-model volume, docker-compose.yml:24-25).
ENV VDT_COMPILE_CACHE_DIR=/root/.cache/vdt-xla

ENTRYPOINT ["python3", "-m", "vllm_distributed_tpu"]
