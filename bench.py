"""Throughput bench — prints ONE JSON line for the driver.

Measures steady-state decode throughput (tokens/sec/chip) of the engine's
fused step on a Llama-1B-shaped model with dummy bf16 weights, batch 32,
on whatever backend is live (the real TPU chip under the driver).  The
reference publishes no numbers (BASELINE.md: "published": {}), so
vs_baseline is reported as 1.0 by convention.

Env knobs: VDT_BENCH_MODEL=1b|7b|tiny, VDT_BENCH_BATCH, VDT_BENCH_STEPS.
"""

from __future__ import annotations

import json
import os
import sys
import time


def main() -> None:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import jax

    from vllm_distributed_tpu.config import EngineArgs
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.sampling_params import SamplingParams
    from vllm_distributed_tpu.testing import (
        LLAMA_1B,
        LLAMA_7B,
        write_llama_config,
    )

    which = os.environ.get("VDT_BENCH_MODEL", "1b")
    shapes = {"1b": LLAMA_1B, "7b": LLAMA_7B}.get(which)
    if shapes is None:
        shapes = dict(
            vocab_size=1024, hidden=256, intermediate=512, layers=4,
            heads=8, kv_heads=4, dtype="float32",
        )
    if jax.default_backend() == "cpu" and which in ("1b", "7b"):
        # CPU smoke fallback: the big shapes would take minutes to compile.
        shapes = dict(
            vocab_size=1024, hidden=256, intermediate=512, layers=4,
            heads=8, kv_heads=4, dtype="float32",
        )
    batch = int(os.environ.get("VDT_BENCH_BATCH", "32"))
    decode_steps = int(os.environ.get("VDT_BENCH_STEPS", "64"))
    prompt_len = 32

    model_dir = write_llama_config(**shapes)
    engine = LLMEngine.from_engine_args(
        EngineArgs(
            model=model_dir,
            skip_tokenizer_init=True,
            load_format="dummy",
            max_num_seqs=batch,
            max_num_batched_tokens=max(2048, batch * prompt_len),
            max_model_len=prompt_len + decode_steps + 8,
        )
    )
    sp = SamplingParams(
        temperature=0.0, max_tokens=decode_steps, ignore_eos=True
    )
    for i in range(batch):
        prompt = [(7 * i + j) % 1000 + 1 for j in range(prompt_len)]
        engine.add_request(f"b{i}", prompt_token_ids=prompt, sampling_params=sp)

    # Prefill + warmup decode steps (compile happens here).
    engine.step()
    for _ in range(3):
        engine.step()

    t0 = time.perf_counter()
    steps = 0
    while engine.has_unfinished_requests():
        engine.step()
        steps += 1
    elapsed = time.perf_counter() - t0
    # Tokens generated during the timed window: batch per decode step.
    timed_tokens = steps * batch  # upper bound; all finish together here
    tps = timed_tokens / elapsed
    n_chips = jax.local_device_count()
    result = {
        "metric": f"decode_tokens_per_sec_per_chip_llama_{which}",
        "value": round(tps / n_chips, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": 1.0,
        "detail": {
            "backend": jax.default_backend(),
            "batch": batch,
            "decode_steps": steps,
            "elapsed_s": round(elapsed, 3),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
