"""Throughput bench — prints ONE JSON line for the driver.

Measures steady-state decode throughput (tokens/sec/chip) of the engine
on dummy-weight family-member shapes on whatever backend is live (the
real TPU chip under the driver).  The reference publishes no numbers
(BASELINE.md: "published": {}), so vs_baseline is reported as 1.0 by
convention; the `detail` block carries the honest engineering numbers
per config: dispatch percentiles, inter-token latency, an UNCLAMPED
roofline against weight + actually-scheduled-KV traffic, cold/warm
TTFT, prefill tokens/sec, and on-chip kernel checks (Pallas attention
incl. int8 pools, KV writer, int8/int4 weight streamers, grouped
ragged_dot lowering) run before any timing.

Default configs: Llama-1B bf16 b32 and int8 b64 (continuity shapes),
Llama-1B int4+int8KV b64 (streamer), Llama-7B int8 b16 (r4 continuity)
and int8+int8KV b48 (headline 7B), and a Mixtral-shape 8x1B MoE b32
(auto dispatch + a forced-ragged comparison).  The serve probe drives
the OpenAI server over HTTP/SSE at c16 with a c1/c4 sweep and a
matched engine-direct fraction.  The headline value is the best decode
tok/s/chip across configs.

Env knobs: VDT_BENCH_MODEL=1b|7b|tiny + VDT_BENCH_BATCH/VDT_BENCH_STEPS/
VDT_BENCH_QUANT/VDT_BENCH_KV run one explicit config instead;
VDT_BENCH_DISPATCHES sizes the timed window; VDT_BENCH_FAST=1 skips the
7B and MoE configs; VDT_BENCH_SERVE=0 skips the serve probe;
VDT_BENCH_SPEC=0 skips the speculative-decoding on/off gate;
VDT_BENCH_PREFIX_CACHE=1 builds the engines with --enable-prefix-caching
(details then report prefix_cache_hit_rate; `tools/ablation` is the
dedicated on/off warm-TTFT comparison).
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time


def _check_kernels() -> str:
    """Compare the Pallas kernels against pure-JAX oracles on the live
    backend (VERDICT r1 weak #4: interpret-only testing is not enough —
    aliasing/DMA behavior is exactly where real Mosaic can diverge)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    if jax.default_backend() != "tpu":
        return "skipped (cpu backend)"

    from vllm_distributed_tpu.ops.attention import (
        AttentionMetadata,
        merge_kv_pages,
        paged_attention_reference,
        write_kv_pages,
    )
    from vllm_distributed_tpu.ops.pallas.paged_attention import paged_attention

    rng = np.random.default_rng(0)
    hq, hkv, d, page, pages = 8, 4, 128, 16, 8
    s_pad, t = 4, 8
    q = jnp.asarray(rng.normal(size=(t, hq, d)), jnp.float32)
    k_pages = jnp.asarray(
        rng.normal(size=(pages, page, hkv, d)), jnp.float32
    )
    v_pages = jnp.asarray(
        rng.normal(size=(pages, page, hkv, d)), jnp.float32
    )
    # 2 seqs: one decoding at ctx 37, one mid-prefill chunk of 7 at ctx 20.
    seq_ids = np.full(t, s_pad, np.int32)
    positions = np.zeros(t, np.int32)
    seq_ids[0], positions[0] = 0, 36
    seq_ids[1:8], positions[1:8] = 1, np.arange(13, 20)
    meta = AttentionMetadata(
        q_seq_ids=jnp.asarray(seq_ids),
        q_positions=jnp.asarray(positions),
        slot_mapping=jnp.zeros(t, jnp.int32),
        # Page 0 is the engine's reserved dump page (garbage by
        # contract), so harness tables use pages 1..P-1 like the
        # allocator does.
        block_tables=jnp.asarray(
            np.arange(s_pad * pages, dtype=np.int32).reshape(s_pad, pages)
            % (pages - 1)
            + 1
        ),
        seq_lens=jnp.asarray([37, 20, 0, 0], jnp.int32),
        logits_indices=jnp.zeros(s_pad, jnp.int32),
        chunk_starts=jnp.asarray([36, 13, 0, 0], jnp.int32),
    )
    kv_pages = merge_kv_pages(k_pages, v_pages)
    got = np.asarray(
        paged_attention(
            q, kv_pages, meta, scale=0.125, num_kv_heads=hkv, max_q=8
        )
    )
    want = np.asarray(
        paged_attention_reference(
            q, kv_pages, meta, scale=0.125, num_kv_heads=hkv
        )
    )
    # TPU f32 dots truncate to bf16 on the MXU by default, and the two
    # paths round differently (flash online-softmax vs direct), so the
    # agreement bound is bf16-scale (eps ≈ 7.8e-3), not f32-scale.
    err = float(np.max(np.abs(got[:8] - want[:8])))
    if err > 2e-2:
        raise AssertionError(f"pallas kernel mismatch on chip: max err {err}")

    # Multi-kv-block decode (num_kvb >= 2 flips on the cross-sequence
    # block-0 prefetch) — real-Mosaic DMA ordering, not just interpret.
    pages2, s2 = 40, 4
    k2 = jnp.asarray(rng.normal(size=(pages2, page, hkv, d)), jnp.float32)
    v2 = jnp.asarray(rng.normal(size=(pages2, page, hkv, d)), jnp.float32)
    t2 = 4
    q2 = jnp.asarray(rng.normal(size=(t2, hq, d)), jnp.float32)
    lens2 = np.array([500, 300, 0, 620], np.int32)
    sid2 = np.array([0, 1, s2, 3], np.int32)  # row 2 -> dropped padding
    pos2 = np.maximum(lens2 - 1, 0)[np.minimum(sid2, s2 - 1)]
    bt2 = (
        rng.permutation(np.arange(s2 * 40) % (pages2 - 1) + 1)
        .reshape(s2, 40)
        .astype(np.int32)
    )
    meta2 = AttentionMetadata(
        q_seq_ids=jnp.asarray(sid2),
        q_positions=jnp.asarray(pos2),
        slot_mapping=jnp.zeros(t2, jnp.int32),
        block_tables=jnp.asarray(bt2),
        seq_lens=jnp.asarray(lens2),
        logits_indices=jnp.zeros(s2, jnp.int32),
        chunk_starts=jnp.asarray(np.maximum(lens2 - 1, 0)),
    )
    kv2 = merge_kv_pages(k2, v2)
    got2 = np.asarray(
        paged_attention(
            q2, kv2, meta2, scale=0.125, num_kv_heads=hkv, max_q=1
        )
    )
    want2 = np.asarray(
        paged_attention_reference(
            q2, kv2, meta2, scale=0.125, num_kv_heads=hkv
        )
    )
    live = np.array([0, 1, 3])
    err2 = float(np.max(np.abs(got2[live] - want2[live])))
    if err2 > 2e-2:
        raise AssertionError(
            f"cross-seq-prefetch kernel mismatch on chip: max err {err2}"
        )

    # In-place KV writer vs the functional scatter, on the live chip.
    from vllm_distributed_tpu.ops.pallas.kv_update import kv_update

    kq = jnp.asarray(rng.normal(size=(t, hkv, d)), jnp.float32)
    vq = jnp.asarray(rng.normal(size=(t, hkv, d)), jnp.float32)
    slots = jnp.asarray(rng.permutation(pages * page)[:t], jnp.int32)
    # Oracle first: kv_update aliases (donates) the pool buffer.
    want_kv = write_kv_pages(kv_pages, kq, vq, slots)
    got_kv = kv_update(kv_pages, kq, vq, slots)
    kv_err = float(np.max(np.abs(np.asarray(got_kv) - np.asarray(want_kv))))
    if kv_err > 0:
        raise AssertionError(f"kv_update mismatch on chip: max err {kv_err}")

    # MoE ragged dispatch: the TPU ragged_dot lowering must be truly
    # grouped (flops == 2*M*H*I), not masked-dense like the CPU one.
    e_, h_, i_, m_ = 8, 256, 512, 64
    xs_ = jnp.zeros((m_, h_), jnp.bfloat16)
    wg_ = jnp.zeros((e_, h_, i_), jnp.bfloat16)
    gs_ = jnp.full((e_,), m_ // e_, jnp.int32)
    rd_flops = (
        jax.jit(lambda a, b, g: jax.lax.ragged_dot(a, b, g))
        .lower(xs_, wg_, gs_).compile().cost_analysis().get("flops", 0)
    )
    if rd_flops > 2 * m_ * h_ * i_ * 1.5:
        raise AssertionError(
            f"ragged_dot lowering is not sparse: {rd_flops} flops vs "
            f"ideal {2 * m_ * h_ * i_}"
        )

    # int8/int4 weight-streaming matmuls vs dequant-in-graph.
    from vllm_distributed_tpu.ops.pallas.quant_matmul import (
        int4_matmul,
        int8_matmul,
    )
    from vllm_distributed_tpu.ops.quant import dequantize, quantize

    x = jnp.asarray(rng.normal(size=(32, 1024)) * 0.5, jnp.float32)
    w = (rng.normal(size=(1024, 512)) * 0.1).astype(np.float32)
    qt = quantize(w, 8)
    mm_want = np.asarray(x @ dequantize(qt, jnp.float32))
    mm_got = np.asarray(
        int8_matmul(x, jnp.asarray(qt.q), jnp.asarray(qt.scale))
    )
    mm_err = float(
        np.max(np.abs(mm_got - mm_want)) / (np.abs(mm_want).max() + 1e-9)
    )
    if mm_err > 2e-2:
        raise AssertionError(f"int8_matmul mismatch on chip: {mm_err}")
    qt4 = quantize(w, 4, group=128)
    mm4_want = np.asarray(x @ dequantize(qt4, jnp.float32))
    mm4_got = np.asarray(
        int4_matmul(
            x, jnp.asarray(qt4.q), jnp.asarray(qt4.scale), group=128
        )
    )
    mm4_err = float(
        np.max(np.abs(mm4_got - mm4_want)) / (np.abs(mm4_want).max() + 1e-9)
    )
    if mm4_err > 2e-2:
        raise AssertionError(f"int4_matmul mismatch on chip: {mm4_err}")
    return (
        f"pass (attn {err:.1e}; kv_update exact; int8_matmul "
        f"{mm_err:.1e}; int4_matmul {mm4_err:.1e})"
    )


def _hbm_bw() -> tuple[str, float]:
    import jax

    table = (
        ("TPU v6", 1640e9),
        ("TPU v5p", 2765e9),
        ("TPU v5", 819e9),  # v5e / v5 lite
        ("TPU v4", 1228e9),
    )
    kind = jax.devices()[0].device_kind
    return kind, next(
        (bw for p, bw in table if kind.startswith(p)), 819e9
    )


def _run_config(shapes, *, batch, k_steps, quant, timed_dispatches,
                kv_dtype="auto", model_kind="llama",
                warm_engine_probe=False, prefill_probe=False,
                timed_dispatches_cap=None):
    """One engine, one decode measurement.  Returns a detail dict."""
    import jax

    from vllm_distributed_tpu.config import EngineArgs
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.sampling_params import SamplingParams
    from vllm_distributed_tpu.testing import (
        write_llama_config,
        write_mixtral_config,
    )

    if timed_dispatches_cap is not None:
        timed_dispatches = min(timed_dispatches, timed_dispatches_cap)
    warmup_dispatches = 2
    prompt_len = 32
    max_tokens = 1 + k_steps * (warmup_dispatches + timed_dispatches)
    writer = (
        write_mixtral_config if model_kind == "mixtral" else write_llama_config
    )
    model_dir = writer(**shapes)

    def build():
        return LLMEngine.from_engine_args(
            EngineArgs(
                model=model_dir,
                skip_tokenizer_init=True,
                load_format="dummy",
                max_num_seqs=batch,
                max_num_batched_tokens=max(2048, batch * prompt_len),
                max_model_len=prompt_len + max_tokens + 8,
                num_decode_steps=k_steps,
                max_concurrent_dispatches=int(
                    os.environ.get("VDT_BENCH_PIPELINE", "6")
                ),
                quantization=quant,
                kv_cache_dtype=kv_dtype,
                enable_prefix_caching=(
                    os.environ.get("VDT_BENCH_PREFIX_CACHE", "0") == "1"
                ),
            )
        )

    def free_engine(eng):
        """Release HBM: the jit cache keys on the runner (static self),
        pinning params/KV beyond the engine's lifetime — delete the
        device buffers explicitly."""
        eng.shutdown()
        r = getattr(getattr(eng, "executor", None), "worker", None)
        r = getattr(r, "runner", None)
        if r is not None and r.params is not None:
            for leaf in jax.tree.leaves((r.params, r.kv_caches)):
                leaf.delete()
            carry = getattr(r, "_decode_carry", None)
            if carry is not None:
                carry[2].delete()
            r.params, r.kv_caches, r._decode_carry = None, None, None

    engine = build()
    try:
        return _measure(
            engine, build, free_engine, batch=batch, k_steps=k_steps,
            quant=quant, prompt_len=prompt_len, max_tokens=max_tokens,
            warmup_dispatches=warmup_dispatches,
            warm_engine_probe=warm_engine_probe,
            prefill_probe=prefill_probe,
        )
    finally:
        # Always release HBM — a failed config must not leak its pool
        # into the next config's budget.
        free_engine(engine)


def _measure(engine, build, free_engine, *, batch, k_steps, quant,
             prompt_len, max_tokens, warmup_dispatches, warm_engine_probe,
             prefill_probe=False):
    import jax

    from vllm_distributed_tpu.sampling_params import SamplingParams

    sp = SamplingParams(temperature=0.0, max_tokens=max_tokens, ignore_eos=True)
    for i in range(batch):
        prompt = [(7 * i + j) % 1000 + 1 for j in range(prompt_len)]
        engine.add_request(f"b{i}", prompt_token_ids=prompt, sampling_params=sp)

    produced: dict[str, int] = {}

    def run_step() -> int:
        before = sum(produced.values())
        for out in engine.step():
            produced[out.request_id] = len(out.outputs[0].token_ids)
        return sum(produced.values()) - before

    t0 = time.perf_counter()
    run_step()  # prefill (compiles the prefill program)
    ttft_cold_s = time.perf_counter() - t0
    for _ in range(warmup_dispatches):
        run_step()

    step_ms: list[float] = []
    timed_tokens = 0
    # Snapshot so the reported count covers the timed window only —
    # warmup/prefill-boundary reconciliations are expected and would
    # otherwise mask a nonzero steady-state reading.
    breaks_before = getattr(engine, "pipeline_breaks", 0)
    t0 = time.perf_counter()
    while engine.has_unfinished_requests():
        t1 = time.perf_counter()
        timed_tokens += run_step()
        step_ms.append((time.perf_counter() - t1) * 1e3)
    elapsed = time.perf_counter() - t0
    tps = timed_tokens / elapsed

    # Roofline for one decode micro-step.  Byte model (VERDICT r4 #4 —
    # derived from ACTUALLY SCHEDULED context, not the pages bucket):
    #   weight_bytes: resident param bytes (quantized weights stream
    #     their compressed form; MoE counts every resident expert —
    #     the top-k dispatch reads less, making raw frac conservative).
    #   kv_read_bytes: batch × ceil(mean_ctx/page)×page rows × row
    #     bytes, where mean_ctx = prompt + half the generated tokens
    #     (the timed window's midpoint) and a row is 2 planes × HD ×
    #     itemsize (+ Hkv f32 scales when the pool is int8).  Page
    #     granularity matches the kernel's DMA; the kernel may overread
    #     up to one KV *block* per sequence, so the floor is a slight
    #     underestimate — the frac is reported RAW (can exceed 1 only
    #     if this model is wrong).
    runner = getattr(
        getattr(getattr(engine, "executor", None), "worker", None),
        "runner",
        None,
    )
    param_bytes = 0
    kv_read_bytes = 0
    if runner is not None:
        param_bytes = sum(
            x.nbytes for x in jax.tree.leaves(runner.params)
        )
        mean_ctx = prompt_len + max_tokens // 2
        page = runner.page_size
        rows = -(-mean_ctx // page) * page
        from vllm_distributed_tpu.ops.attention import kv_pool_width

        m = runner.model
        row_bytes = (
            kv_pool_width(m.num_kv_heads, m.head_dim)
            * jax.numpy.dtype(runner.kv_cache_dtype()).itemsize
        )
        if runner.kv_cache_quantized:
            row_bytes += m.num_kv_heads * 4  # f32 scale row
        kv_read_bytes = batch * rows * 2 * row_bytes * m.num_layers
    kind, bw = _hbm_bw()
    floor_ms = (param_bytes + kv_read_bytes) / bw * 1e3
    micro_ms = 1e3 / (tps / batch) if tps else float("inf")
    itl = sorted(ms / k_steps for ms in step_ms)

    def pct(p):
        return round(itl[min(int(len(itl) * p), len(itl) - 1)], 3)

    # Steady-state throughput from the MEDIAN dispatch: the tunneled
    # chip shows multi-hundred-ms environmental stalls (up to ±25%
    # between identical runs); the wall-clock number (tokens_per_sec)
    # includes them, the p50 number is the reproducible steady state.
    p50_ms = statistics.median(step_ms)
    tps_p50 = batch * k_steps / p50_ms * 1e3 if p50_ms else 0.0
    detail = {
        "batch": batch,
        "decode_steps_fused": k_steps,
        "quantization": quant,
        "timed_tokens": timed_tokens,
        "elapsed_s": round(elapsed, 3),
        "tokens_per_sec": round(tps, 1),
        "tokens_per_sec_p50": round(tps_p50, 1),
        # The dispatch tax (ISSUE 7): steady-state p50 throughput minus
        # what the wall clock actually delivered.  0 means the driver
        # never fell off the p50 pace; r03 measured a 2,338 tok/s gap
        # here before the overlapped dispatch pipeline.
        "wall_vs_p50_gap": round(tps_p50 - tps, 1),
        "dispatch_ms_p50": round(p50_ms, 2),
        "dispatch_ms_max": round(max(step_ms), 2),
        # Windows > 2x the median are classified as stalls (transport
        # hiccups or engine-side pauses; compiles are excluded by the
        # warmup dispatches).  The final window is excluded — it drains
        # the whole dispatch pipeline and is ~depth x p50 by design.
        "stall_windows": sum(1 for ms in step_ms[:-1] if ms > 2 * p50_ms),
        "stall_ms_total": round(
            sum(ms - p50_ms for ms in step_ms[:-1] if ms > 2 * p50_ms), 1
        ),
        "decode_microstep_ms": round(micro_ms, 3),
        "itl_ms_p50": pct(0.5),
        "itl_ms_p90": pct(0.9),
        "itl_ms_p99": pct(0.99),
        "roofline_microstep_ms": round(floor_ms, 3),
        # RAW (unclamped): >1 means the byte model is wrong, not that
        # the chip beat physics (VERDICT r4 weak #3).
        "roofline_frac": round(floor_ms / micro_ms, 3),
        "ttft_cold_s": round(ttft_cold_s, 2),
        "param_bytes": param_bytes,
        "kv_read_bytes_per_microstep": kv_read_bytes,
    }
    # Async-scheduling reconciliation drains over the timed window (0 at
    # steady-state decode; each one idles the device for a full drain).
    if hasattr(engine, "pipeline_breaks"):
        detail["pipeline_breaks"] = engine.pipeline_breaks - breaks_before
    sched = getattr(engine, "scheduler", None)
    if sched is not None and getattr(sched, "prefix_cache_queries", 0):
        detail["prefix_cache_hit_rate"] = round(
            sched.prefix_cache_hits / sched.prefix_cache_queries, 4
        )
        # Tiered KV (ISSUE 14): host-tier traffic over the run, when
        # the spill tier is armed (VDT_KV_SPILL_HOST_PAGES > 0).
        if getattr(sched, "kv_spill_pages", 0) or getattr(
            sched, "kv_restore_pages", 0
        ):
            detail["kv_spill_pages"] = sched.kv_spill_pages
            detail["kv_restore_pages"] = sched.kv_restore_pages
            detail["prefix_cache_host_hit_tokens"] = (
                sched.prefix_cache_hits_host
            )
    if warm_engine_probe or prefill_probe:
        # Warm TTFT: a FRESH engine on the same shapes hits the
        # persistent caches this run just wrote (XLA disk cache + AOT
        # export artifacts) — the restart-to-first-token story (§5.4):
        # no retrace, no relower, compile-cache-hit only.  Free the
        # first engine's HBM before the rebuild.  A probe failure must
        # not discard the config's measurement.
        free_engine(engine)
        try:
            engine2 = build()
            try:
                # Replay the SAME admission shape as the measured run
                # (batch x prompt_len) so the first step hits the
                # artifact the run just exported — a lone request would
                # land in a token bucket the first engine never
                # compiled and measure a fresh compile instead.
                sp2 = SamplingParams(
                    temperature=0.0, max_tokens=2, ignore_eos=True
                )
                for i in range(batch):
                    engine2.add_request(
                        f"warm{i}",
                        prompt_token_ids=[
                            (7 * i + j) % 1000 + 1
                            for j in range(prompt_len)
                        ],
                        sampling_params=sp2,
                    )
                t0 = time.perf_counter()
                engine2.step()
                detail["ttft_warm_s"] = round(time.perf_counter() - t0, 2)
                while engine2.has_unfinished_requests():
                    engine2.step()
                if prefill_probe:
                    detail["prefill"] = _prefill_probe(
                        engine2, prompt_len=256, n_prompts=8
                    )
            finally:
                free_engine(engine2)
        except Exception as e:  # noqa: BLE001
            detail["ttft_warm_error"] = f"{type(e).__name__}: {e}"
    return detail


def _prefill_probe(engine, *, prompt_len, n_prompts) -> dict:
    """Prefill tokens/sec (VERDICT r4 #3: 'no prefill tokens/sec number
    anywhere'): run one compile pass, then time a batch of fresh
    prompts through their prefill steps (max_tokens=1 — decode excluded
    by construction)."""
    from vllm_distributed_tpu.sampling_params import SamplingParams

    prompt_len = min(
        prompt_len, engine.config.model_config.max_model_len - 8
    )
    sp = SamplingParams(temperature=0.0, max_tokens=1, ignore_eos=True)

    def run(tag):
        for i in range(n_prompts):
            toks = [(11 * i + j) % 900 + 1 for j in range(prompt_len)]
            engine.add_request(f"{tag}{i}", prompt_token_ids=toks,
                               sampling_params=sp)
        t0 = time.perf_counter()
        while engine.has_unfinished_requests():
            engine.step()
        return time.perf_counter() - t0

    run("pfc")  # compile pass
    elapsed = run("pf")
    total = n_prompts * prompt_len
    return {
        "prompt_len": prompt_len,
        "n_prompts": n_prompts,
        "elapsed_s": round(elapsed, 3),
        "prefill_tokens_per_sec": round(total / elapsed, 1),
    }


def _spec_probe(on_cpu: bool) -> dict:
    """Speculative-decoding gate (ISSUE 11): tokens/s and acceptance
    rate with spec decode on vs off on a repetitive-suffix workload
    (tools/spec_decode_ablation.py).  The result carries `gate_pass`:
    at the measured acceptance rate the speculative engine must beat
    the fused-decode baseline by the configured multiple, and outputs
    must be bit-identical (always fatal if not)."""
    import argparse

    from tools.spec_decode_ablation import run_ablation
    from vllm_distributed_tpu.testing import LLAMA_1B, write_llama_config

    if on_cpu:
        shapes = dict(
            vocab_size=1024, hidden=256, intermediate=512, layers=4,
            heads=8, kv_heads=4, dtype="float32",
        )
        n_prompts, max_tokens = 4, 32
    else:
        shapes = LLAMA_1B
        n_prompts, max_tokens = 16, 96
    args = argparse.Namespace(
        load_format="dummy",
        num_prompts=n_prompts,
        prompt_len=96,
        pattern_len=24,
        max_tokens=max_tokens,
        spec_k=4,
        num_decode_steps=8,
        num_kv_pages=2048,
        page_size=16,
        gate_acceptance=0.5,
        gate_speedup=1.3,
    )
    result = run_ablation(write_llama_config(**shapes), args)
    if not result["outputs_bit_identical"]:
        raise AssertionError(
            "spec decode outputs diverged from the greedy baseline"
        )
    # The >=1.3x speedup gate only binds in the memory-bound regime the
    # optimization targets (weights+KV streamed per micro-step).  A CPU
    # run is compute-bound — verifying K+1 tokens costs ~K+1x the
    # FLOPs of one — so there the numbers are reported, not asserted
    # (the deterministic tier-1 gate in tests/test_spec_decode.py
    # asserts the roofline model via the mock's HBM-pass cost instead).
    result["gate_enforced"] = not on_cpu
    if (
        result["gate_enforced"]
        and result["gate_applicable"]
        and not result["gate_pass"]
    ):
        raise AssertionError(
            f"spec decode gate failed: {result['decode_speedup']}x < "
            f"{args.gate_speedup}x at acceptance "
            f"{result['acceptance_rate']}"
        )
    return result


def _serve_probe() -> dict:
    """HTTP-path serving metrics (BASELINE.md's TTFT/ITL are SERVING
    numbers): boot the OpenAI server on the 1B dummy model and drive it
    with concurrent SSE completions via `vdt bench serve`'s client."""
    import argparse
    import asyncio
    import socket

    from aiohttp.test_utils import TestServer

    from vllm_distributed_tpu.config import EngineArgs
    from vllm_distributed_tpu.engine.async_llm import AsyncLLM
    from vllm_distributed_tpu.entrypoints.cli import _bench_serve_async
    from vllm_distributed_tpu.entrypoints.openai.api_server import (
        build_app,
        init_app_state,
    )
    from vllm_distributed_tpu.testing import LLAMA_1B, write_llama_config

    model_dir = write_llama_config(**LLAMA_1B)
    engine = AsyncLLM.from_engine_args(
        EngineArgs(
            model=model_dir,
            skip_tokenizer_init=True,
            load_format="dummy",
            quantization="int8",
            max_num_seqs=16,
            max_model_len=512,
            num_decode_steps=16,
            max_concurrent_dispatches=6,
            # warmup_decode only: the probe's prefill shapes are
            # multi-request JOINS, which the single-request prefill
            # buckets of --warmup-prefill would not cover anyway — the
            # HTTP warmup passes below compile the real shapes.
            warmup_decode=True,
        )
    )
    state = init_app_state(engine, served_model_name="bench-1b")
    loop = asyncio.new_event_loop()
    try:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        server = TestServer(build_app(state), port=port)
        loop.run_until_complete(server.start_server())
        args = argparse.Namespace(
            url=f"http://127.0.0.1:{port}",
            model="bench-1b",
            num_prompts=48,
            concurrency=16,
            input_len=32,
            output_len=128,
            # Closed-loop probe: the ISSUE 13 --ramp rate sweep is the
            # fleet/autoscaler workload, not a single-replica number.
            ramp=None,
        )
        # Warmup passes at EVERY measured concurrency (each join batch
        # size is its own prefill program shape), then the measured
        # passes: headline at c16 plus a small sweep (r4 weak #6) for
        # per-stream latency at low load.
        sweep_concs = (1, 4)
        for conc in (args.concurrency, *sweep_concs):
            warm = argparse.Namespace(
                **{**vars(args), "output_len": 16, "concurrency": conc,
                   "num_prompts": max(2 * conc, 4)}
            )
            loop.run_until_complete(_bench_serve_async(warm))
        result = loop.run_until_complete(_bench_serve_async(args))
        result["sweep"] = {}
        for conc in sweep_concs:
            a = argparse.Namespace(
                **{**vars(args), "concurrency": conc,
                   "num_prompts": max(3 * conc, 4)}
            )
            r = loop.run_until_complete(_bench_serve_async(a))
            result["sweep"][f"c{conc}"] = {
                k: r[k]
                for k in ("output_tokens_per_s", "ttft_s", "itl_ms")
            }
        loop.run_until_complete(server.close())
        return result
    finally:
        engine.shutdown()
        loop.close()
        # Release HBM so the matched engine-direct run that follows can
        # boot (shutdown alone leaves params/pool pinned by jit caches).
        import jax

        r = getattr(
            getattr(engine.engine, "executor", None), "worker", None
        )
        r = getattr(r, "runner", None)
        if r is not None and r.params is not None:
            for leaf in jax.tree.leaves((r.params, r.kv_caches)):
                leaf.delete()
            carry = getattr(r, "_decode_carry", None)
            if carry is not None:
                carry[2].delete()
            r.params, r.kv_caches, r._decode_carry = None, None, None


def main() -> None:
    import tempfile

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    # RUN-SCOPED persistent cache dir: the warm-TTFT probe measures the
    # restart story (§5.4 — XLA disk cache + AOT export artifacts
    # written EARLIER IN THIS RUN), while ttft_cold stays honestly cold
    # (a shared /tmp dir would leak warmth across runs).
    if "VDT_COMPILE_CACHE_DIR" not in os.environ:
        import atexit
        import shutil

        cache = tempfile.mkdtemp(prefix="vdt_bench_cache_")
        os.environ["VDT_COMPILE_CACHE_DIR"] = cache
        atexit.register(shutil.rmtree, cache, ignore_errors=True)
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        # The env var alone can lose to an interpreter-startup jax import
        # (sitecustomize); the config update before first backend use wins.
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    from vllm_distributed_tpu.testing import LLAMA_1B, LLAMA_7B

    tiny = dict(
        vocab_size=1024, hidden=256, intermediate=512, layers=4,
        heads=8, kv_heads=4, dtype="float32",
    )
    kernel_check = _check_kernels()
    timed = int(os.environ.get("VDT_BENCH_DISPATCHES", "24"))
    on_cpu = jax.default_backend() == "cpu"

    explicit = os.environ.get("VDT_BENCH_MODEL")
    if explicit or on_cpu:
        shapes = {"1b": LLAMA_1B, "7b": LLAMA_7B}.get(explicit, tiny)
        if on_cpu:
            shapes = tiny  # big shapes would take minutes to compile
        cfg = dict(
            shapes=shapes,
            batch=int(os.environ.get("VDT_BENCH_BATCH", "32")),
            k_steps=int(os.environ.get("VDT_BENCH_STEPS", "16")),
            quant=os.environ.get("VDT_BENCH_QUANT") or None,
            kv_dtype=os.environ.get("VDT_BENCH_KV", "auto"),
        )
        configs = [(explicit or "tiny", cfg)]
    else:
        from vllm_distributed_tpu.testing import MIXTRAL_8X1B

        configs = [
            # Continuity shapes (pinned since r2/r4 — VERDICT r4 #4).
            ("llama_1b_bf16_b32", dict(
                shapes=LLAMA_1B, batch=32, k_steps=16, quant=None)),
            ("llama_1b_int8_b64", dict(
                shapes=LLAMA_1B, batch=64, k_steps=32, quant="int8",
                prefill_probe=True)),
            # int4 weight streaming (nibble-unpack in VMEM).
            ("llama_1b_int4_b64", dict(
                shapes=LLAMA_1B, batch=64, k_steps=32, quant="int4",
                kv_dtype="int8", timed_dispatches_cap=12)),
        ]
        if os.environ.get("VDT_BENCH_FAST") != "1":
            configs += [
                # 7B KV is ~1 MiB/token (MHA, 32 layers): the batch and
                # decode length must FIT the pool or the scheduler
                # preempts in a loop mid-bench (r3's "12 s stalls" were
                # exactly this thrash).  b16 is the r4 continuity shape;
                # the int8 KV cache (~0.5 MiB/token) doubles capacity,
                # so b48 is the headline config.
                ("llama_7b_int8_b16", dict(
                    shapes=LLAMA_7B, batch=16, k_steps=16, quant="int8",
                    timed_dispatches_cap=16)),
                # (no warm/prefill probes here: each one is a full 7B
                # rebuild — the restart story is measured once, at 1B)
                ("llama_7b_int8_kv8_b48", dict(
                    shapes=LLAMA_7B, batch=48, k_steps=16, quant="int8",
                    kv_dtype="int8", timed_dispatches_cap=16)),
                # MoE (the reference flagship family is MoE): ragged
                # sorted dispatch, single chip, int8 weights.
                ("moe_mixtral8x1b_int8_b32", dict(
                    shapes=MIXTRAL_8X1B, batch=32, k_steps=16,
                    quant="int8", model_kind="mixtral",
                    timed_dispatches_cap=16)),
            ]

    details = {}
    best_name, best = None, None
    warm_pending = True  # probe warm TTFT on the first SUCCESSFUL config
    for name, cfg in configs:
        try:
            det = _run_config(
                **cfg, timed_dispatches=timed,
                warm_engine_probe=warm_pending,
            )
        except Exception as e:  # noqa: BLE001 — one config must not
            # take down the whole bench (e.g. OOM on a busy chip)
            details[name] = {"error": f"{type(e).__name__}: {e}"}
            continue
        warm_pending = False
        details[name] = det
        if (
            best is None
            or det["tokens_per_sec_p50"] > best["tokens_per_sec_p50"]
        ):
            best_name, best = name, det
    if best is None:
        raise RuntimeError(f"every bench config failed: {details}")

    # MoE dispatch-path ratio (VERDICT r4 #5): the headline config runs
    # the "auto" policy (dense-fused at bandwidth-bound decode — see
    # models/mixtral.py _mlp); rerun briefly with the ragged path
    # forced so the tradeoff is measured on the record every round.
    moe = details.get("moe_mixtral8x1b_int8_b32")
    # Skip (and don't clobber) when the user forced an impl themselves:
    # the comparison is only meaningful against the auto headline.
    user_impl = os.environ.get("VDT_MOE_IMPL")
    if moe and "error" not in moe and user_impl in (None, "auto"):
        from vllm_distributed_tpu.testing import MIXTRAL_8X1B

        os.environ["VDT_MOE_IMPL"] = "ragged"
        try:
            ragged = _run_config(
                shapes=MIXTRAL_8X1B, batch=32, k_steps=16, quant="int8",
                model_kind="mixtral", timed_dispatches=8,
            )
            moe["ragged_tokens_per_sec_p50"] = ragged[
                "tokens_per_sec_p50"
            ]
            moe["auto_vs_ragged_speedup"] = round(
                moe["tokens_per_sec_p50"]
                / max(ragged["tokens_per_sec_p50"], 1e-9),
                2,
            )
        except Exception as e:  # noqa: BLE001
            moe["ragged_oracle_error"] = f"{type(e).__name__}: {e}"
        finally:
            if user_impl is None:
                os.environ.pop("VDT_MOE_IMPL", None)
            else:
                os.environ["VDT_MOE_IMPL"] = user_impl

    # Speculative-decoding gate (ISSUE 11): cheap on CPU (tiny shapes),
    # the honest 1B measurement on TPU.  A gate failure is reported in
    # the detail rather than sinking the whole bench.
    spec_detail = None
    if os.environ.get("VDT_BENCH_SPEC", "1") == "1":
        try:
            spec_detail = _spec_probe(on_cpu)
        except Exception as e:  # noqa: BLE001
            spec_detail = {"error": f"{type(e).__name__}: {e}"}

    serve_detail = None
    if not on_cpu and os.environ.get("VDT_BENCH_SERVE", "1") == "1":
        try:
            serve_detail = _serve_probe()
        except Exception as e:  # noqa: BLE001
            serve_detail = {"error": f"{type(e).__name__}: {e}"}
        if serve_detail and "error" not in serve_detail:
            # Matched engine-direct comparison (VERDICT r4 #1 bar:
            # serve >= 50% of engine-direct at the same batch/quant/K).
            try:
                direct = _run_config(
                    shapes=LLAMA_1B, batch=16, k_steps=16, quant="int8",
                    timed_dispatches=8,
                )
                serve_detail["engine_direct_matched_tps"] = direct[
                    "tokens_per_sec"
                ]
                serve_detail["serve_frac_of_engine_direct"] = round(
                    serve_detail["output_tokens_per_s"]
                    / max(direct["tokens_per_sec"], 1e-9),
                    3,
                )
            except Exception as e:  # noqa: BLE001
                serve_detail["engine_direct_error"] = (
                    f"{type(e).__name__}: {e}"
                )

    n_chips = jax.local_device_count()
    result = {
        # p50-dispatch-derived steady state (see tokens_per_sec_p50 note
        # in _measure); the wall-clock number is in the config detail.
        "metric": "decode_tokens_per_sec_per_chip_p50",
        "value": round(best["tokens_per_sec_p50"] / n_chips, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": 1.0,
        "detail": {
            "backend": jax.default_backend(),
            "device_kind": _hbm_bw()[0],
            "best_config": best_name,
            # r1/r2 reported wall-clock tokens_per_sec of the 1B bf16
            # b32 config; that continuity number is preserved here.
            "continuity_r2_equivalent_tps": details.get(
                "llama_1b_bf16_b32", {}
            ).get("tokens_per_sec"),
            "pallas_kernel_check": kernel_check,
            "spec_decode": spec_detail,
            "serve_http": serve_detail,
            "configs": details,
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
