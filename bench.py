"""Throughput bench — prints ONE JSON line for the driver.

Measures steady-state decode throughput (tokens/sec/chip) of the engine on
a Llama-1B-shaped model with dummy bf16 weights on whatever backend is
live (the real TPU chip under the driver).  The reference publishes no
numbers (BASELINE.md: "published": {}), so vs_baseline is reported as 1.0
by convention; the `detail` block carries the honest engineering numbers:
per-dispatch latency percentiles, HBM-roofline fraction for the decode
micro-step, TTFT, and a Pallas-vs-reference kernel check run on the live
backend before any timing.

Env knobs: VDT_BENCH_MODEL=1b|7b|tiny, VDT_BENCH_BATCH, VDT_BENCH_STEPS
(decode steps fused per dispatch), VDT_BENCH_DISPATCHES (timed window).
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time


def _check_pallas_kernel() -> str:
    """Compare the Pallas kernel against the pure-JAX oracle on the live
    backend (VERDICT r1 weak #4: the kernel had only ever been
    correctness-tested in interpreter mode on CPU)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    if jax.default_backend() != "tpu":
        return "skipped (cpu backend)"

    from vllm_distributed_tpu.ops.attention import (
        AttentionMetadata,
        paged_attention_reference,
    )
    from vllm_distributed_tpu.ops.pallas.paged_attention import paged_attention

    rng = np.random.default_rng(0)
    hq, hkv, d, page, pages = 8, 4, 128, 16, 8
    s_pad, t = 4, 8
    q = jnp.asarray(rng.normal(size=(t, hq, d)), jnp.float32)
    k_pages = jnp.asarray(
        rng.normal(size=(pages, page, hkv, d)), jnp.float32
    )
    v_pages = jnp.asarray(
        rng.normal(size=(pages, page, hkv, d)), jnp.float32
    )
    # 2 seqs: one decoding at ctx 37, one mid-prefill chunk of 7 at ctx 20.
    seq_ids = np.full(t, s_pad, np.int32)
    positions = np.zeros(t, np.int32)
    seq_ids[0], positions[0] = 0, 36
    seq_ids[1:8], positions[1:8] = 1, np.arange(13, 20)
    meta = AttentionMetadata(
        q_seq_ids=jnp.asarray(seq_ids),
        q_positions=jnp.asarray(positions),
        slot_mapping=jnp.zeros(t, jnp.int32),
        block_tables=jnp.asarray(
            np.arange(s_pad * pages, dtype=np.int32).reshape(s_pad, pages)
            % pages
        ),
        seq_lens=jnp.asarray([37, 20, 0, 0], jnp.int32),
        logits_indices=jnp.zeros(s_pad, jnp.int32),
        chunk_starts=jnp.asarray([36, 13, 0, 0], jnp.int32),
    )
    got = np.asarray(
        paged_attention(q, k_pages, v_pages, meta, scale=0.125, max_q=8)
    )
    want = np.asarray(
        paged_attention_reference(q, k_pages, v_pages, meta, scale=0.125)
    )
    # TPU f32 dots truncate to bf16 on the MXU by default, and the two
    # paths round differently (flash online-softmax vs direct), so the
    # agreement bound is bf16-scale (eps ≈ 7.8e-3), not f32-scale.
    err = float(np.max(np.abs(got[:8] - want[:8])))
    if err > 2e-2:
        raise AssertionError(f"pallas kernel mismatch on chip: max err {err}")

    # In-place KV writer vs the functional scatter, on the live chip
    # (ADVICE r2: interpret mode can diverge from real Mosaic exactly
    # where input_output_aliases/DMA semantics are involved).
    from vllm_distributed_tpu.ops.attention import write_kv_pages
    from vllm_distributed_tpu.ops.pallas.kv_update import kv_update

    kq = jnp.asarray(rng.normal(size=(t, hkv, d)), jnp.float32)
    vq = jnp.asarray(rng.normal(size=(t, hkv, d)), jnp.float32)
    slots = jnp.asarray(rng.permutation(pages * page)[:t], jnp.int32)
    # Oracle first: kv_update aliases (donates) the pool buffers.
    want_k, want_v = write_kv_pages(k_pages, v_pages, kq, vq, slots)
    got_k, got_v = kv_update(k_pages, v_pages, kq, vq, slots)
    kv_err = max(
        float(np.max(np.abs(np.asarray(got_k) - np.asarray(want_k)))),
        float(np.max(np.abs(np.asarray(got_v) - np.asarray(want_v)))),
    )
    if kv_err > 0:
        raise AssertionError(f"kv_update mismatch on chip: max err {kv_err}")
    return f"pass (attn max err {err:.1e}; kv_update exact)"


def main() -> None:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        # The env var alone can lose to an interpreter-startup jax import
        # (sitecustomize); the config update before first backend use wins.
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    from vllm_distributed_tpu.config import EngineArgs
    from vllm_distributed_tpu.engine.llm_engine import LLMEngine
    from vllm_distributed_tpu.sampling_params import SamplingParams
    from vllm_distributed_tpu.testing import (
        LLAMA_1B,
        LLAMA_7B,
        write_llama_config,
    )

    which = os.environ.get("VDT_BENCH_MODEL", "1b")
    shapes = {"1b": LLAMA_1B, "7b": LLAMA_7B}.get(which)
    if shapes is None:
        shapes = dict(
            vocab_size=1024, hidden=256, intermediate=512, layers=4,
            heads=8, kv_heads=4, dtype="float32",
        )
    if jax.default_backend() == "cpu" and which in ("1b", "7b"):
        # CPU smoke fallback: the big shapes would take minutes to compile.
        shapes = dict(
            vocab_size=1024, hidden=256, intermediate=512, layers=4,
            heads=8, kv_heads=4, dtype="float32",
        )
    batch = int(os.environ.get("VDT_BENCH_BATCH", "32"))
    k_steps = int(os.environ.get("VDT_BENCH_STEPS", "16"))
    timed_dispatches = int(os.environ.get("VDT_BENCH_DISPATCHES", "6"))
    warmup_dispatches = 2
    prompt_len = 32
    # 1 token sampled at prefill + a whole number of fused-K dispatches.
    max_tokens = 1 + k_steps * (warmup_dispatches + timed_dispatches)

    kernel_check = _check_pallas_kernel()

    model_dir = write_llama_config(**shapes)
    engine = LLMEngine.from_engine_args(
        EngineArgs(
            model=model_dir,
            skip_tokenizer_init=True,
            load_format="dummy",
            max_num_seqs=batch,
            max_num_batched_tokens=max(2048, batch * prompt_len),
            max_model_len=prompt_len + max_tokens + 8,
            num_decode_steps=k_steps,
        )
    )
    sp = SamplingParams(
        temperature=0.0, max_tokens=max_tokens, ignore_eos=True
    )
    for i in range(batch):
        prompt = [(7 * i + j) % 1000 + 1 for j in range(prompt_len)]
        engine.add_request(f"b{i}", prompt_token_ids=prompt, sampling_params=sp)

    produced: dict[str, int] = {}

    def run_step() -> int:
        before = sum(produced.values())
        for out in engine.step():
            produced[out.request_id] = len(out.outputs[0].token_ids)
        return sum(produced.values()) - before

    # Prefill (compiles the prefill program) — time it for TTFT.
    t0 = time.perf_counter()
    run_step()
    ttft_cold_s = time.perf_counter() - t0

    # Warmup decode dispatches (compiles the fused-K scan).
    for _ in range(warmup_dispatches):
        run_step()

    step_ms: list[float] = []
    timed_tokens = 0
    t0 = time.perf_counter()
    while engine.has_unfinished_requests():
        t1 = time.perf_counter()
        timed_tokens += run_step()
        step_ms.append((time.perf_counter() - t1) * 1e3)
    elapsed = time.perf_counter() - t0

    tps = timed_tokens / elapsed
    n_chips = jax.local_device_count()

    # HBM roofline for one decode micro-step: every parameter byte must be
    # read once per token batch (weights dominate; KV traffic at this
    # context length is <1%).  Bandwidth picked by device kind; the
    # params attribute chain is uniproc-only, so guard it (under the
    # multihost executor the roofline block is skipped, not crashed).
    hbm_bw_by_kind = (
        ("TPU v6", 1640e9),
        ("TPU v5p", 2765e9),
        ("TPU v5", 819e9),  # v5e / v5 lite
        ("TPU v4", 1228e9),
    )
    device_kind = jax.devices()[0].device_kind
    hbm_bw = next(
        (bw for prefix, bw in hbm_bw_by_kind if device_kind.startswith(prefix)),
        819e9,
    )
    runner = getattr(
        getattr(getattr(engine, "executor", None), "worker", None),
        "runner",
        None,
    )
    params = getattr(runner, "params", None)
    param_bytes = (
        sum(x.nbytes for x in jax.tree.leaves(params)) if params else 0
    )
    floor_ms = param_bytes / hbm_bw * 1e3
    micro_ms = 1e3 / (tps / batch) if tps else float("inf")
    result = {
        "metric": f"decode_tokens_per_sec_per_chip_llama_{which}",
        "value": round(tps / n_chips, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": 1.0,
        "detail": {
            "backend": jax.default_backend(),
            "device_kind": device_kind,
            "hbm_bw_gbps": round(hbm_bw / 1e9),
            "batch": batch,
            "decode_steps_fused": k_steps,
            "timed_tokens": timed_tokens,
            "elapsed_s": round(elapsed, 3),
            "dispatch_ms_p50": round(statistics.median(step_ms), 2),
            "dispatch_ms_max": round(max(step_ms), 2),
            "decode_microstep_ms": round(micro_ms, 3),
            "hbm_roofline_microstep_ms": round(floor_ms, 3),
            "roofline_frac": round(min(floor_ms / micro_ms, 1.0), 3),
            "ttft_cold_s": round(ttft_cold_s, 2),
            "param_bytes": param_bytes,
            "pallas_kernel_check": kernel_check,
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
