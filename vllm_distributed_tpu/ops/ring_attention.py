"""Ring attention: causal attention with the SEQUENCE sharded over a
mesh axis (context parallelism for long prefill).

The reference serves 262k-token contexts from a single worker's paged
KV (SURVEY.md §5.7 — no sequence parallelism exists there); on TPU the
mesh makes the stronger design natural: shard the sequence over an
axis, keep each device's Q resident, and rotate K/V shards around the ring
with `ppermute` while accumulating flash-style online softmax — the
blockwise ring attention of Liu et al., expressed as a shard_map over
the same Mesh the rest of the engine uses.  Compute overlaps the
neighbor exchange because XLA pipelines the permute with the per-step
einsums; the collective rides ICI.

This is the long-context building block (prefill attention for
sequences larger than one device's HBM/compute appetite).  Decode stays
on the paged kernel — a decode step touches one token per sequence, so
sequence-sharding it has nothing to win.

Known inefficiency (future work): with contiguous sequence placement,
causal masking discards ~half the block computations across the ring
(device 0 masks out every remote block).  The standard fix is zigzag /
striped placement so each device holds an early and a late slice and
per-step work balances.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

_NEG_INF = float("-inf")


def _block_attention(q, k, v, *, scale, q_start, kv_start, causal):
    """Partial flash-attention of one (q-block, kv-block) pair.

    Returns (unnormalized out [B, H, D], row max m [B, H], row sum l
    [B, H]) for online-softmax accumulation.
    """
    bq, hq, d = q.shape
    bk, hkv = k.shape[0], k.shape[1]
    # GQA via grouped einsum (no materialized K/V repeat in the ring's
    # hot loop — same formulation as paged_attention_reference).
    g = hq // hkv
    qg = q.reshape(bq, hkv, g, d).astype(jnp.float32)
    logits = jnp.einsum(
        "qhgd,khd->hgqk", qg, k.astype(jnp.float32)
    ).reshape(hq, bq, bk) * scale
    if causal:
        q_pos = q_start + jnp.arange(bq)
        kv_pos = kv_start + jnp.arange(bk)
        mask = q_pos[:, None] >= kv_pos[None, :]
        logits = jnp.where(mask[None, :, :], logits, _NEG_INF)
    m = jnp.max(logits, axis=-1)  # [H, Q]
    # Fully-masked rows (this kv block is entirely in the future) must
    # not poison the accumulator: exp(-inf - -inf) -> nan.
    safe_m = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(logits - safe_m[..., None])
    p = jnp.where(jnp.isfinite(logits), p, 0.0)
    l = jnp.sum(p, axis=-1)  # [H, Q]
    pg = p.reshape(hkv, g, bq, bk)
    out = jnp.einsum(
        "hgqk,khd->qhgd", pg, v.astype(jnp.float32)
    ).reshape(bq, hq, d)
    return out, jnp.swapaxes(m, 0, 1), jnp.swapaxes(l, 0, 1)


def _merge(acc, new, m_acc, m_new, l_acc, l_new):
    """Online-softmax merge of two partial results."""
    m = jnp.maximum(m_acc, m_new)
    safe = lambda x: jnp.where(jnp.isfinite(x), x, 0.0)  # noqa: E731
    a_scale = jnp.exp(safe(m_acc) - safe(m)) * jnp.isfinite(m_acc)
    n_scale = jnp.exp(safe(m_new) - safe(m)) * jnp.isfinite(m_new)
    acc = acc * a_scale[:, :, None] + new * n_scale[:, :, None]
    l = l_acc * a_scale + l_new * n_scale
    return acc, m, l


def ring_attention(
    q: jax.Array,  # [T, Hq, D] sequence-sharded over `axis`
    k: jax.Array,  # [T, Hkv, D] likewise
    v: jax.Array,
    mesh,
    *,
    axis: str = "sp",
    scale: float,
    causal: bool = True,
) -> jax.Array:
    """Causal attention over a sequence sharded across `axis`.

    Each device keeps its Q shard and sends its K/V shard around the
    ring; after `sp` steps every Q block has attended to every K/V
    block at or before it.  Output is sequence-sharded like q.
    """
    sp = mesh.shape[axis]

    def per_device(q_blk, k_blk, v_blk):
        idx = jax.lax.axis_index(axis)
        bq = q_blk.shape[0]
        q_start = idx * bq
        perm = [(j, (j + 1) % sp) for j in range(sp)]

        # Peel the local block (no exchange needed), then scan sp-1
        # rotate-then-compute steps — no dead final permute shipping
        # shards nobody reads.
        acc, m_acc, l_acc = _block_attention(
            q_blk, k_blk, v_blk,
            scale=scale, q_start=q_start, kv_start=idx * k_blk.shape[0],
            causal=causal,
        )

        def body(carry, r):
            acc, m_acc, l_acc, k_cur, v_cur, kv_owner = carry
            # Rotate: device i's block moves to device i+1, so after
            # r rotations device i holds the block originally on i - r.
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)
            kv_owner = (kv_owner - 1) % sp
            out, m_new, l_new = _block_attention(
                q_blk, k_cur, v_cur,
                scale=scale, q_start=q_start,
                kv_start=kv_owner * k_cur.shape[0],
                causal=causal,
            )
            acc, m_acc, l_acc = _merge(acc, out, m_acc, m_new, l_acc, l_new)
            return (acc, m_acc, l_acc, k_cur, v_cur, kv_owner), None

        if sp > 1:
            (acc, m_acc, l_acc, _, _, _), _ = jax.lax.scan(
                body,
                (acc, m_acc, l_acc, k_blk, v_blk, idx),
                jnp.arange(sp - 1),
            )
        denom = jnp.where(l_acc > 0, l_acc, 1.0)
        return (acc / denom[:, :, None]).astype(q_blk.dtype)

    spec = P(axis, None, None)
    return jax.shard_map(
        per_device,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)
