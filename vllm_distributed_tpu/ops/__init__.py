"""TPU-native compute ops: attention (reference + Pallas), sampling, rotary."""
