"""Weight-only quantization (int8 per-channel, int4 group-wise).

The route to 70B-class models on v5e HBM (the reference's flagship is an
AWQ 4-bit MoE, /root/reference/.env.server:11 and Dockerfile:5-6 — its
quantized kernels come from flashinfer/vLLM; here the TPU-native design
is: store weights compressed in HBM, dequantize on the fly inside the
jitted step).  XLA fuses the convert+scale into the consuming matmul's
operand read, so the win is exactly what decode needs — HBM traffic
halves (int8) or quarters (int4) while the MXU still sees bf16.

Schemes (both symmetric, no zero points):
- int8: per-output-channel scale.  q = round(w / s), s = max|w_col| / 127.
- int4: group-wise scales along the contraction (input) dim, two nibbles
  packed per uint8 byte.  Group size must divide the *per-shard* input
  dim so group boundaries never straddle a tensor-parallel shard.

``QuantizedTensor`` is a pytree node, so quantized params flow through
jit/device_put/tree.map like plain arrays; partition specs mirror the
structure via ``quant_spec``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

METHODS = ("int8", "int4")


@jax.tree_util.register_pytree_node_class
@dataclass
class QuantizedTensor:
    """Compressed weight + scales; dequantizes to ``shape``/``dtype``.

    int8: q [..., in, out] int8, scale [..., out].
    int4: q [..., in/2, out] uint8 (low nibble = even input row),
          scale [..., in/group, out].

    ``matmul`` is the execution backend stamped at LOAD time per tensor
    ("dequant" | "pallas" | "pallas_interpret") — carried on the tensor,
    not in module state, so multiple engines in one process can't flip
    each other's path on a retrace.
    """

    q: Any
    scale: Any
    bits: int
    group: int  # 0 for per-channel (int8)
    shape: tuple  # logical (dequantized) shape
    dtype: Any  # logical dtype
    matmul: str = "dequant"
    # Stamped at device placement under a mesh: the weight's logical
    # partition entries for (input, output) dims plus the mesh itself,
    # so quant_matmul can shard_map the streaming kernel per tp shard.
    spec: Any = None
    mesh: Any = None

    def tree_flatten(self):
        return (self.q, self.scale), (
            self.bits,
            self.group,
            self.shape,
            self.dtype,
            self.matmul,
            self.spec,
            self.mesh,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, *aux)

    @property
    def nbytes(self) -> int:
        return self.q.nbytes + self.scale.nbytes


def _pow2_divisor(n: int, cap: int) -> int:
    g = 1
    while g * 2 <= cap and n % (g * 2) == 0:
        g *= 2
    return g


def pick_group_size(in_dim: int, shards: int = 1, cap: int = 128) -> int:
    """Largest power-of-2 group size <= cap dividing the per-shard input
    dim (so int4 group boundaries align with tp shard boundaries)."""
    per_shard = in_dim // shards if shards and in_dim % shards == 0 else in_dim
    return _pow2_divisor(per_shard, cap)


def quantize(
    w, bits: int, group: int = 0, dtype=None, matmul: str = "dequant"
) -> QuantizedTensor:
    """Quantize [..., in, out] weights.  Host (numpy) or device arrays.
    `dtype` records the logical dtype dequantization restores."""
    is_jax = isinstance(w, jax.Array)
    xp = jnp if is_jax else np
    wf = w.astype(xp.float32) if is_jax else np.asarray(w, np.float32)
    shape, in_dim = wf.shape, wf.shape[-2]
    if bits == 8:
        s = xp.max(xp.abs(wf), axis=-2) / 127.0  # [..., out]
        s = xp.maximum(s, 1e-8)
        q = xp.clip(xp.round(wf / s[..., None, :]), -127, 127).astype(xp.int8)
        return QuantizedTensor(
            q, s.astype(xp.float32), 8, 0, shape, dtype, matmul
        )
    if bits == 4:
        if group <= 0:
            group = pick_group_size(in_dim)
        if in_dim % group or in_dim % 2:
            raise ValueError(
                f"int4 needs input dim ({in_dim}) divisible by the group "
                f"size ({group}) and by 2"
            )
        g = wf.reshape(*shape[:-2], in_dim // group, group, shape[-1])
        s = xp.max(xp.abs(g), axis=-2) / 7.0  # [..., in/group, out]
        s = xp.maximum(s, 1e-8)
        q = xp.clip(xp.round(g / s[..., None, :]), -8, 7) + 8
        q = q.reshape(*shape[:-1], shape[-1]).astype(xp.uint8)
        packed = (q[..., 0::2, :] | (q[..., 1::2, :] << 4)).astype(xp.uint8)
        return QuantizedTensor(
            packed, s.astype(xp.float32), 4, group, shape, dtype, matmul
        )
    raise ValueError(f"unsupported bits {bits} (use 8 or 4)")


def dequantize(qt: QuantizedTensor, dtype=jnp.bfloat16) -> jax.Array:
    """In-graph dequantize; XLA fuses this into the consuming matmul."""
    dtype = qt.dtype or dtype
    if qt.bits == 8:
        return (
            qt.q.astype(jnp.float32) * qt.scale[..., None, :]
        ).astype(dtype)
    low = (qt.q & 0xF).astype(jnp.int32)
    high = (qt.q >> 4).astype(jnp.int32)
    in_dim = qt.shape[-2]
    # Inverse of packed[i] = (row 2i | row 2i+1 << 4): interleave pairs
    # back onto the input dim.
    q = jnp.stack([low, high], axis=-2).reshape(
        *qt.shape[:-2], in_dim, qt.shape[-1]
    )
    grouped = q.reshape(
        *qt.shape[:-2], in_dim // qt.group, qt.group, qt.shape[-1]
    )
    w = (grouped.astype(jnp.float32) - 8.0) * qt.scale[..., None, :]
    return w.reshape(*qt.shape).astype(dtype)


def maybe_dequantize(w, dtype) -> jax.Array:
    if isinstance(w, QuantizedTensor):
        return dequantize(w, dtype)
    return w.astype(dtype)


def pick_matmul_mode(quant_method: str | None) -> str:
    """Execution backend for quantized matmuls, decided at load time:
    "pallas" streams compressed tiles through the Pallas kernels —
    int8 single-chip and per-tp-shard under shard_map; int4 single-chip
    (tp>1 int4 falls back to dequant-in-graph at call time).
    Non-quantized stays "dequant"."""
    if quant_method not in ("int8", "int4"):
        return "dequant"
    from vllm_distributed_tpu import envs

    backend = envs.VDT_USE_PALLAS
    if backend == "auto":
        backend = "pallas" if jax.default_backend() == "tpu" else "reference"
    if backend in ("pallas", "pallas_interpret"):
        return backend
    return "dequant"


def _pick_block(
    out_dim: int, in_dim: int, x_nbytes: int, bits: int = 8
) -> int | None:
    """Largest out-block that divides out_dim and fits the VMEM budget.
    Bigger tiles stream faster ([2048x8192] with blk 2048: 1084 GB/s vs
    723 at blk 512 on v5e) — but the budget only admits them for small
    in_dims (2048-class); 4096/8192-in matmuls cap at 1024/512.  int4
    uses its own (larger-temporaries) budget model."""
    from vllm_distributed_tpu.ops.pallas.quant_matmul import (
        fits_vmem_budget,
        fits_vmem_budget4,
    )

    fits = fits_vmem_budget4 if bits == 4 else fits_vmem_budget
    for blk in (2048, 1024, 512, 256, 128):
        if out_dim % blk == 0 and fits(in_dim, blk, x_nbytes):
            return blk
    return None


def _sharded_int8_matmul(x, w: QuantizedTensor, interpret: bool):
    """Per-tp-shard streaming matmul under shard_map (GSPMD cannot
    partition the Pallas custom call).  Column-parallel weights
    (out dim sharded) keep the output sharded with no collective;
    row-parallel weights (in dim sharded) psum the partial products
    inside the region.  Returns None when the layout is unsupported
    (caller falls back to dequant-in-graph)."""
    from vllm_distributed_tpu.ops.pallas.quant_matmul import int8_matmul

    in_ax, out_ax = w.spec
    mesh = w.mesh
    if in_ax is not None and out_ax is not None:
        return None
    axis = out_ax if out_ax is not None else in_ax
    shards = axis_shards(axis, mesh) if axis is not None else 1
    # Keep the batch dim data-parallel inside the region (dp=1 makes
    # this a no-op; dp>1 must not all-gather the activations).
    dp_ax = "dp" if mesh.shape.get("dp", 1) > 1 else None
    dp = mesh.shape.get("dp", 1) if dp_ax else 1
    if x.shape[0] % dp:
        return None
    if out_ax is not None:
        out_local = w.q.shape[-1] // shards
        blk = _pick_block(out_local, w.q.shape[0], x.nbytes // dp)
        if blk is None:
            return None

        def body(x_, q_, s_):
            return int8_matmul(
                x_, q_, s_, block_out=blk, interpret=interpret
            )

        in_specs = (P(dp_ax), P(None, out_ax), P(out_ax))
        out_specs = P(dp_ax, out_ax)
    else:
        in_local = w.q.shape[0] // shards
        # Each shard's kernel sees x already split over the in dim.
        blk = _pick_block(
            w.q.shape[-1], in_local, x.nbytes // (shards * dp)
        )
        if blk is None:
            return None

        def body(x_, q_, s_):
            part = int8_matmul(
                x_, q_, s_, block_out=blk, interpret=interpret
            )
            if in_ax is not None:
                part = jax.lax.psum(part, in_ax)
            return part

        in_specs = (P(dp_ax, in_ax), P(in_ax, None), P())
        out_specs = P(dp_ax)
    f = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False,
    )
    return f(x, w.q, w.scale)


def quant_matmul(x: jax.Array, w, bias=None) -> jax.Array:
    """x @ w for plain or QuantizedTensor weights.  On the Pallas path
    eligible int8 2D weights stream through ops/pallas/quant_matmul (the
    only HBM traffic is the int8 bytes) — per tp shard under shard_map
    when the weight was placed on a mesh; everything else dequantizes
    in-graph."""
    if isinstance(w, QuantizedTensor):
        from vllm_distributed_tpu.ops.pallas.quant_matmul import (
            int4_matmul,
            int8_matmul,
        )

        interpret = w.matmul == "pallas_interpret"
        eligible = (
            w.matmul != "dequant"
            and w.q.ndim == 2
            and x.ndim == 2
            and x.shape[0] <= 256
        )
        out = None
        if w.bits == 8:
            if eligible and w.mesh is not None and w.spec is not None:
                out = _sharded_int8_matmul(x, w, interpret)
            elif eligible and w.mesh is None:
                blk = _pick_block(w.q.shape[-1], w.q.shape[0], x.nbytes)
                if blk is not None:
                    out = int8_matmul(
                        x, w.q, w.scale, block_out=blk,
                        interpret=interpret,
                    )
        elif (
            w.bits == 4
            and eligible
            and w.mesh is None  # tp>1 int4: dequant-in-graph for now
            and w.group >= 2
            and w.group % 2 == 0
        ):
            blk = _pick_block(w.q.shape[-1], w.shape[-2], x.nbytes, bits=4)
            if blk is not None:
                out = int4_matmul(
                    x, w.q, w.scale, group=w.group, block_out=blk,
                    interpret=interpret,
                )
        if out is None:
            out = x @ dequantize(w, x.dtype)
    else:
        out = x @ w.astype(x.dtype)
    if bias is not None:
        out = out + bias.astype(out.dtype)
    return out


def axis_shards(entry, mesh) -> int:
    names = entry if isinstance(entry, tuple) else (entry,)
    n = 1
    for name in names:
        if name is not None:
            n *= mesh.shape.get(name, 1)
    return n


def aligned_spec(spec: P, shape: tuple, mesh) -> P:
    """Drop sharding on any dim the mesh doesn't divide evenly (scales /
    packed nibbles can misalign with shard boundaries; replicating a
    small dim is correct and cheap, and keeps quantization semantics
    independent of the mesh)."""
    out = []
    for pos, entry in enumerate(tuple(spec)):
        if entry is not None and shape[pos] % axis_shards(entry, mesh):
            entry = None
        out.append(entry)
    return P(*out)


def place_quantized(qt: QuantizedTensor, wspec: P, mesh) -> QuantizedTensor:
    """Shard a QuantizedTensor's q/scale parts per the weight's spec."""
    from jax.sharding import NamedSharding

    qs = quant_spec(wspec, qt.bits)
    q_spec = aligned_spec(qs.q, qt.q.shape, mesh)
    return QuantizedTensor(
        jax.device_put(qt.q, NamedSharding(mesh, q_spec)),
        jax.device_put(
            qt.scale,
            NamedSharding(mesh, aligned_spec(qs.scale, qt.scale.shape, mesh)),
        ),
        qt.bits,
        qt.group,
        qt.shape,
        qt.dtype,
        qt.matmul,
        spec=tuple(q_spec) if qt.q.ndim == 2 else None,
        mesh=mesh,
    )


def quant_spec(wspec: P, bits: int) -> QuantizedTensor:
    """PartitionSpec structure mirroring a quantized leaf.

    q shards exactly like the weight (int4 packs along the input dim,
    which preserves divisibility for even per-shard sizes).  Scales drop
    the input dim (int8) or keep a shrunken one (int4)."""
    t = tuple(wspec)
    if len(t) < 2:  # fully/mostly replicated spec: scales replicate too
        return QuantizedTensor(
            q=wspec, scale=P(), bits=bits, group=0, shape=(), dtype=None
        )
    lead, in_ax, out_ax = t[:-2], t[-2], t[-1]
    scale = P(*lead, out_ax) if bits == 8 else P(*lead, in_ax, out_ax)
    return QuantizedTensor(
        q=wspec, scale=scale, bits=bits, group=0, shape=(), dtype=None
    )
