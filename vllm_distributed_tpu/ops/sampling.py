"""Vectorized, jit-compiled token sampling.

The TPU-native equivalent of the FlashInfer/CUDA sampling path the
reference inherits (SURVEY.md §2.2: "Pallas/XLA top-k/top-p sampling").
Every per-request knob in SamplingParams lowers to a row of a dense array,
so one compiled program samples the whole step batch — no per-request
Python in the hot loop.

Static specialization flags (`do_penalties`, `do_top_k_p`, `return_logprobs`)
keep the common greedy/temperature-only path free of the [S, V] sort.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

_NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class SamplingMetadata:
    """Per-sequence sampling state, padded to the step's [S] bucket.

    temperature == 0 selects greedy for that row.  `top_k` uses vocab_size
    to mean "disabled"; `output_tokens`/`prompt_tokens` are only populated
    (non-empty second dim) when penalties are active — they are [S, L]
    token-id arrays padded with -1, used to build count matrices in-jit.
    """

    temperature: jax.Array  # [S] f32
    top_k: jax.Array  # [S] i32
    top_p: jax.Array  # [S] f32
    min_p: jax.Array  # [S] f32
    repetition_penalty: jax.Array  # [S] f32
    presence_penalty: jax.Array  # [S] f32
    frequency_penalty: jax.Array  # [S] f32
    keys: jax.Array  # [S, 2] uint32 per-row PRNG keys
    prompt_tokens: jax.Array  # [S, Lp] i32, -1 padded
    output_tokens: jax.Array  # [S, Lo] i32, -1 padded


def _token_counts(tokens: jax.Array, vocab_size: int) -> jax.Array:
    """[S, L] padded token ids (-1 pad) -> [S, V] counts via scatter-add."""
    s = tokens.shape[0]
    # Route padding to an extra trash column, then drop it.
    idx = jnp.where(tokens < 0, vocab_size, tokens)
    counts = jnp.zeros((s, vocab_size + 1), dtype=jnp.float32)
    rows = jnp.broadcast_to(jnp.arange(s)[:, None], tokens.shape)
    counts = counts.at[rows, idx].add(1.0)
    return counts[:, :vocab_size]


def _apply_penalties(logits: jax.Array, meta: SamplingMetadata) -> jax.Array:
    vocab = logits.shape[-1]
    prompt_counts = _token_counts(meta.prompt_tokens, vocab)
    output_counts = _token_counts(meta.output_tokens, vocab)
    # Repetition penalty applies to every token seen (prompt + output).
    seen = (prompt_counts + output_counts) > 0
    rp = meta.repetition_penalty[:, None]
    logits = jnp.where(
        seen, jnp.where(logits > 0, logits / rp, logits * rp), logits
    )
    # Presence/frequency apply to generated tokens only (OpenAI semantics).
    logits = logits - meta.frequency_penalty[:, None] * output_counts
    logits = logits - meta.presence_penalty[:, None] * (output_counts > 0)
    return logits


def _apply_top_k_p(logits: jax.Array, meta: SamplingMetadata) -> jax.Array:
    sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
    sort_idx = jnp.argsort(logits, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    ranks = jnp.arange(logits.shape[-1], dtype=jnp.int32)[None, :]
    keep = ranks < meta.top_k[:, None]
    # Keep tokens until cumulative prob crosses top_p (first always kept).
    keep &= (cum - probs) < meta.top_p[:, None]
    keep &= probs >= meta.min_p[:, None] * probs[:, :1]
    # Scatter the sorted-order mask back to vocab order.
    rows = jnp.broadcast_to(
        jnp.arange(logits.shape[0])[:, None], sort_idx.shape
    )
    keep_orig = jnp.zeros_like(keep).at[rows, sort_idx].set(keep)
    return jnp.where(keep_orig, logits, _NEG_INF)


@partial(
    jax.jit,
    static_argnames=("do_penalties", "do_top_k_p", "return_logprobs"),
)
def sample(
    logits: jax.Array,  # [S, V] f32
    meta: SamplingMetadata,
    *,
    do_penalties: bool = False,
    do_top_k_p: bool = False,
    return_logprobs: bool = False,
) -> tuple[jax.Array, jax.Array | None]:
    """Returns (token_ids [S], logprobs [S, V] or None).

    Logprobs are of the penalized pre-truncation distribution at
    temperature 1 — the distribution the model "meant" — matching what the
    OpenAI API reports.
    """
    logits = logits.astype(jnp.float32)
    if do_penalties:
        logits = _apply_penalties(logits, meta)

    logprobs = jax.nn.log_softmax(logits, axis=-1) if return_logprobs else None

    greedy = jnp.argmax(logits, axis=-1)

    temp = jnp.maximum(meta.temperature, 1e-6)[:, None]
    scaled = logits / temp
    if do_top_k_p:
        scaled = _apply_top_k_p(scaled, meta)

    def _one(key_pair, row):
        key = jax.random.fold_in(jax.random.PRNGKey(key_pair[0]), key_pair[1])
        return jax.random.categorical(key, row)

    sampled = jax.vmap(_one)(meta.keys.astype(jnp.uint32), scaled)

    tokens = jnp.where(meta.temperature > 0, sampled, greedy)
    return tokens.astype(jnp.int32), logprobs


@jax.jit
def spec_greedy_accept(
    logits: jax.Array,  # [S, K+1, V] f32 — verify-pass logits
    draft_tokens: jax.Array,  # [S, K] i32, -1 padded
    num_drafts: jax.Array,  # [S] i32 — real drafts per row
) -> tuple[jax.Array, jax.Array]:
    """Greedy accept/reject for draftless speculative decoding.

    Row layout: ``logits[s, 0]`` is the distribution after the step's
    input token (the non-speculative next-token logits); ``logits[s,
    j]`` for ``j >= 1`` is the distribution after draft ``j-1``.  A
    draft is accepted while it equals the greedy argmax chain, so the
    emitted tokens — ``tokens[s, :num_emitted[s]]`` — are exactly the
    tokens sequential greedy decode would have produced: the longest
    matching draft prefix plus one bonus token from the first
    disagreeing (or final) distribution.  ``num_emitted`` is therefore
    in ``[1, num_drafts + 1]``; shapes stay static, the variable part
    is values only.
    """
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [S, K+1]
    k = draft_tokens.shape[1]
    pos = jnp.arange(k, dtype=jnp.int32)[None, :]
    matches = (greedy[:, :k] == draft_tokens) & (pos < num_drafts[:, None])
    # Leading-run length: cumprod zeroes everything after the first miss.
    accepted = jnp.cumprod(matches.astype(jnp.int32), axis=1).sum(axis=1)
    return greedy, (accepted + 1).astype(jnp.int32)
