"""In-place paged KV-cache row writer for the fused decode scan.

The functional scatter (`ops.attention.write_kv_pages`) is correct but
XLA does not keep it in place inside the fused decode scan — at large
pool sizes it materializes a full pool copy per layer per micro-step,
which dominates step time (measured: 5× end-to-end at r2; re-measured
this round: ~1.3 ms/layer on a 390 MB pool).  This writer updates the
pool with one `dynamic_update_slice` per token row, which XLA DOES
alias on the donated scan carry (measured in place at serving pool
sizes) — the TPU analog of vLLM's CUDA `reshape_and_cache`
(SURVEY.md §2.2).

A Pallas DMA writer is NOT possible on this pool layout: Mosaic only
allows slicing single rows of dims above the tiled minor-two pair, and
the combined pool ``[2, P, page, HD]`` (ops/attention.py) keeps
``(page, HD)`` as the tiled pair so the attention kernel's page DMAs
land contiguously.  Aligned whole-page slabs CAN be DMA'd — that is
the shape of the flush path planned for staged decode writes — but a
single token row cannot, hence dynamic_update_slice here.

STATUS: bench/test oracle only.  The production decode path stages
micro-step rows in dense side buffers and flushes them once per
dispatch through ops/pallas/kv_flush (the runner's _pick_kv_flush_fn);
nothing in the serving path selects this writer anymore.  It remains
the per-row in-place reference the flush path is tested against, and
the record of WHY a per-row Pallas writer is impossible (above).

Cost: ~1.8 µs per row update (measured) — the number that motivated
the staged-flush design.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def kv_update(
    kv_pages: jax.Array,  # [2, P, page, HD]
    k: jax.Array,  # [T, Hkv, D]
    v: jax.Array,
    slot_mapping: jax.Array,  # [T] int32
    *,
    interpret: bool = False,  # kept for backend-selection compatibility
) -> jax.Array:
    """Drop-in for write_kv_pages, writing in place via per-row DUS."""
    del interpret
    _, _, page_size, hd = kv_pages.shape
    t, hkv, d = k.shape
    rows_k = k.reshape(t, hkv * d).astype(kv_pages.dtype)
    rows_v = v.reshape(t, hkv * d).astype(kv_pages.dtype)
    if hkv * d < hd:
        pad = [(0, 0), (0, hd - hkv * d)]
        rows_k = jnp.pad(rows_k, pad)
        rows_v = jnp.pad(rows_v, pad)
    for i in range(t):
        page = slot_mapping[i] // page_size
        row = slot_mapping[i] % page_size
        kv_pages = jax.lax.dynamic_update_slice(
            kv_pages, rows_k[None, i : i + 1, None], (0, page, row, 0)
        )
        kv_pages = jax.lax.dynamic_update_slice(
            kv_pages, rows_v[None, i : i + 1, None], (1, page, row, 0)
        )
    return kv_pages


def kv_update_cpu(*args, **kwargs):
    """CPU-test entry (same implementation — pure XLA)."""
    return kv_update(*args, **kwargs)
