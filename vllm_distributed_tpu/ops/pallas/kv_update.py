"""Pallas in-place paged KV-cache writer.

The functional scatter (`ops.attention.write_kv_pages`) is correct but
XLA does not reliably alias it inside the fused decode scan — at large
pool sizes it materializes a full pool copy per layer per micro-step,
which dominates step time (measured: 5× end-to-end).  This kernel writes
the step's K/V rows straight into the paged HBM pool with
``input_output_aliases``, so the update is in place by construction —
the TPU analog of vLLM's CUDA `reshape_and_cache` (SURVEY.md §2.2).

Layout contract (shared with ops/attention.py): pool is slot-major
``[num_pages, page_size, Hkv, D]``, so one token's K/V row ``[Hkv, D]``
is a single DMA whose sliced dims are major (Mosaic allows arbitrary
slicing there; the minor two dims ride whole).  Token t of a request
lands at flat slot ``page_ids[t // page_size] * page_size +
t % page_size``; padding tokens carry slots inside reserved page 0.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(
    slots_ref,  # [T] int32 (SMEM, scalar prefetch)
    k_new_ref,  # [1, Hkv, D] VMEM block (token t's heads)
    v_new_ref,
    k_pages_in,  # [P, page, Hkv, D] ANY (aliased with k_pages_out)
    v_pages_in,
    k_pages_out,
    v_pages_out,
    sems,  # DMA sems [2]
    *,
    page_size: int,
):
    t = pl.program_id(0)
    slot = slots_ref[t]
    page = slot // page_size
    row = slot % page_size
    k_cp = pltpu.make_async_copy(
        k_new_ref.at[0], k_pages_out.at[page, row], sems.at[0]
    )
    v_cp = pltpu.make_async_copy(
        v_new_ref.at[0], v_pages_out.at[page, row], sems.at[1]
    )
    k_cp.start()
    v_cp.start()
    k_cp.wait()
    v_cp.wait()


def kv_update(
    k_pages: jax.Array,  # [P, page, Hkv, D]
    v_pages: jax.Array,
    k: jax.Array,  # [T, Hkv, Dq]  (Dq <= D; lane-padded here)
    v: jax.Array,
    slot_mapping: jax.Array,  # [T] int32
    *,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Drop-in for write_kv_pages, writing in place via aliasing."""
    p_total, page_size, hkv, d = k_pages.shape
    t = k.shape[0]
    if k.shape[-1] < d:
        pad = [(0, 0), (0, 0), (0, d - k.shape[-1])]
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    k = k.astype(k_pages.dtype)
    v = v.astype(v_pages.dtype)

    kernel = functools.partial(_kernel, page_size=page_size)
    out_shape = (
        jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
        jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
    )
    k_pages, v_pages = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(t,),
            in_specs=[
                pl.BlockSpec((1, hkv, d), lambda t_, *refs: (t_, 0, 0)),
                pl.BlockSpec((1, hkv, d), lambda t_, *refs: (t_, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            scratch_shapes=[pltpu.SemaphoreType.DMA((2,))],
        ),
        out_shape=out_shape,
        # Inputs count scalar-prefetch first: 0=slots, 1=k, 2=v,
        # 3=k_pages, 4=v_pages → outputs (0=k_pages, 1=v_pages).
        input_output_aliases={3: 0, 4: 1},
        interpret=interpret,
    )(slot_mapping, k, v, k_pages, v_pages)
    return k_pages, v_pages


def kv_update_cpu(*args, **kwargs):
    """Interpret-mode entry for CPU tests."""
    return kv_update(*args, interpret=True, **kwargs)
