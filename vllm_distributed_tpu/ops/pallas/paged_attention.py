"""Pallas ragged paged-attention kernel — the TPU hot path.

The TPU-native replacement for the CUDA PagedAttention/FlashAttention
kernels the reference inherits from the vLLM image (SURVEY.md §2.2 and
BASELINE.json north_star: "PagedAttention is a Pallas kernel").  One
kernel serves both decode (1 query token/seq) and chunked prefill
(many): queries are grouped per sequence and attention runs flash-style
(online softmax) over the sequence's paged KV.

Design (v2 — rebuilt after the round-3 on-chip ablation, PERF.md):
the round-3 kernel was COMPUTE-bound, not DMA-bound (DMA-only ablation
ran at 779 GB/s while the full kernel ran at ~250 GB/s): decode issued
8 separate per-head op chains on 8-row tiles, so the VPU (softmax, mask,
state updates) and tiny 6%-utilized MXU calls dominated while the DMA
queues idled.  v2 changes, in order of impact:

- **Folded-head block-diagonal compute.**  KV heads are processed in
  fold groups of F heads per matmul: queries are laid out
  block-diagonally as [rows = F*G*mq, F*D] so ONE dot per kv block
  computes F heads' scores ([rows, BLK]), and the whole softmax/state
  chain runs on one wide tile instead of per-head slivers.  For decode
  (mq=1) F grows to put all heads in a single chain (1B: 32 rows, 8
  heads, one chain vs 8); for prefill rows are already plentiful and F
  stays at the 128-lane alignment minimum.  The off-diagonal lanes are
  zeros, so scores are exact; outputs are extracted by diagonal einsum
  outside the kernel.
- **Combined flat KV pool** ``[2, P, page, HD]`` (ops/attention.py):
  two descriptors per page (K plane + V plane) cover all heads'
  contiguous lanes, and a 64-wide head dim is stored unpadded inside
  HD (the r3 layout padded each head to 128 lanes — 2× wasted bytes on
  Llama-1B-class models).
- **Globally rotating triple buffer.**  Buffer index = (number of
  active blocks completed so far) % 3, tracked in SMEM — never resets
  per sequence, so the cross-sequence block-0 prefetch can never target
  the buffer the current (or previous) step reads.  This replaces the
  r3 order-dependent safety argument (ADVICE r3 medium) with a
  structural invariant.
- Causal skip: kv blocks entirely above the q block's last position are
  skipped (no DMA, no compute) — half the work on prefill.

Numerics: scores/softmax/accumulation in float32 regardless of cache
dtype; output cast back to q.dtype.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from vllm_distributed_tpu.ops.attention import AttentionMetadata
from vllm_distributed_tpu.utils import cdiv, next_power_of_2

_MASK = -0.7 * float(jnp.finfo(jnp.float32).max)
_LANES = 128
# Per-buffer-slot VMEM budget for the combined K+V block (bytes).
_KV_BUF_BYTES = 1024 * 1024
_NBUF = 3
# Budget for the f32 flash state (acc + m + l) across all fold groups:
# acc is hkv*g*mq*f*d*4 bytes and m+l add hkv*g*mq*256*4 (f-independent).
_STATE_BYTES = 6 * 1024 * 1024
# Decode-shape fold target: grow F until a block's softmax chain has at
# least this many rows (amortizes VPU op issue over more elements).
_ROWS_TARGET = 32


def _kernel(
    # scalar prefetch (+ side_len_ref when has_side)
    block_tables_ref,  # [S, max_pages] int32 (SMEM)
    seq_lens_ref,  # [S] int32
    chunk_starts_ref,  # [S] int32
    # then inputs (q_ref [1,1,NF,ROWS,FD]; side_ref [1,2,K,HD] when
    # has_side; kv_pages_ref [2,P,page,HD] ANY; scale_blk_ref
    # [1,2,Hkv,BLK] f32 when has_quant — a regular pipelined block of
    # the per-sequence TRANSPOSED scale matrix the wrapper gathers in
    # XLA, so the kernel never DMAs sub-128-lane scale slabs), the out
    # block [1,1,NF,ROWS,FD], and scratch (kv_vmem [NBUF,2,BLK,HD],
    # m/l [NF,ROWS,LANES] f32, acc [NF,ROWS,FD] f32, DMA sems [NBUF],
    # cnt SMEM [2] = [completed active blocks (the buffer-rotation
    # cursor), prefetch-pending flag]).
    *rest,
    scale: float,
    soft_cap: float | None,
    page_size: int,
    pages_per_blk: int,
    group_size: int,
    num_fold: int,
    fold_width: int,
    mq_blk: int,
    has_side: bool,
    has_quant: bool,
):
    rest = list(rest)
    side_len_ref = rest.pop(0) if has_side else None
    q_ref = rest.pop(0)
    side_ref = rest.pop(0) if has_side else None
    kv_pages_ref = rest.pop(0)
    scale_blk_ref = rest.pop(0) if has_quant else None
    out_ref, kv_vmem = rest.pop(0), rest.pop(0)
    m_scr, l_scr, acc_scr, sems, cnt = rest
    s = pl.program_id(0)
    qb = pl.program_id(1)
    kvb = pl.program_id(2)
    num_seqs = pl.num_programs(0)
    num_qb = pl.num_programs(1)
    num_kvb = pl.num_programs(2)
    blk = pages_per_blk * page_size
    seq_len = seq_lens_ref[s]
    chunk_start = chunk_starts_ref[s]
    # Number of active kv blocks for (s, qb): the causal skip bound.
    q_pos_max = chunk_start + (qb + 1) * mq_blk - 1
    span = jnp.minimum(seq_len, q_pos_max + 1)
    nb = jnp.where(seq_len > 0, (span + blk - 1) // blk, 0)
    active = kvb < nb

    @pl.when((s == 0) & (qb == 0) & (kvb == 0))
    def _boot():
        cnt[0] = 0
        cnt[1] = 0

    def block_dma(seq, block_idx, buf):
        """Two descriptors per page (K plane, V plane), each covering
        every head's lanes contiguously.  (Quantized pools: the scale
        block arrives through the regular BlockSpec pipeline, not here.)
        """
        copies = []
        for i in range(pages_per_blk):
            page = block_tables_ref[seq, block_idx * pages_per_blk + i]
            for kvi in range(2):
                copies.append(
                    pltpu.make_async_copy(
                        kv_pages_ref.at[kvi, page],
                        kv_vmem.at[
                            buf, kvi, pl.ds(i * page_size, page_size)
                        ],
                        sems.at[buf],
                    )
                )
        return copies

    @pl.when(kvb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _MASK)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Bootstrap / gap-recovery: if no predecessor prefetched this block
    # (first active step, or the one-step lookahead hit an empty
    # sequence), issue the DMA here and eat the stall.
    @pl.when(active & (cnt[1] == 0))
    def _bootstrap_dma():
        for cp in block_dma(s, kvb, cnt[0] % _NBUF):
            cp.start()

    # Prefetch the NEXT active block (same q block, next q block, or the
    # next sequence) into the next rotation slot while this one computes.
    next_in_qb = kvb + 1 < nb
    # (s, qb+1) restarts from kv block 0; (s+1) likewise.  An empty
    # sequence between live ones defeats the one-step lookahead; the
    # bootstrap above recovers (correctness never depends on lookahead).
    have_next_qb = (qb + 1 < num_qb) & (seq_len > 0)
    next_seq_ok = (s + 1 < num_seqs) & (
        seq_lens_ref[jnp.minimum(s + 1, num_seqs - 1)] > 0
    )
    has_next = next_in_qb | have_next_qb | next_seq_ok
    next_s = jnp.where(next_in_qb | have_next_qb, s, s + 1)
    next_kvb = jnp.where(next_in_qb, kvb + 1, 0)

    @pl.when(active & has_next)
    def _prefetch():
        for cp in block_dma(next_s, next_kvb, (cnt[0] + 1) % _NBUF):
            cp.start()

    block_start = kvb * blk

    def row_positions(ncols):
        """Per-row query position / per-col iota for masking.  Row
        layout: r = (hl*G + g)*mq + m → token index m = r % mq."""
        rows = acc_scr.shape[1]
        row_ids = jax.lax.broadcasted_iota(jnp.int32, (rows, ncols), 0)
        col_ids = jax.lax.broadcasted_iota(jnp.int32, (rows, ncols), 1)
        q_pos = chunk_start + qb * mq_blk + row_ids % mq_blk
        return q_pos, col_ids

    def scale_mat(st, nf, ncols):
        """[ROWS, ncols] dequant factors for fold nf from transposed
        per-head scales st [Hkv, ncols]: row r of the fold covers head
        nf*F + r // (G*mq) (block-diagonal row layout), so each head's
        scale row broadcasts over its G*mq query rows.  Off-diagonal
        lanes get the ROW's head scale (not the lane's) — harmless,
        they are discarded by the diagonal extraction outside."""
        f = acc_scr.shape[1] // (group_size * mq_blk)
        sub = st[nf * f : nf * f + f]  # [F, ncols] (static slice)
        return jnp.broadcast_to(
            sub[:, None, :], (f, group_size * mq_blk, ncols)
        ).reshape(f * group_size * mq_blk, ncols)

    def flash_update(nf, k, v, mask, sk=None, sv=None):
        """One online-softmax accumulation step for fold group nf.
        ``sk``/``sv`` are [ROWS, ncols] dequant factors (int8 pool);
        the K factor folds into the scores, the V factor into p before
        the PV matmul — both cheaper than lane-expanding the scale to
        dequantize the [ncols, FD] tiles themselves."""
        qn = q_ref[0, 0, nf].astype(jnp.float32)  # [ROWS, FD]
        scores = jax.lax.dot_general(
            qn, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [ROWS, ncols]
        if sk is not None:
            scores = scores * sk
        scores = scores * scale
        if soft_cap is not None:
            scores = jnp.tanh(scores / soft_cap) * soft_cap
        scores = jnp.where(mask, scores, _MASK)

        m_prev = m_scr[nf, :, 0:1]
        m_cur = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(scores - m_new)
        p = jnp.where(mask, p, 0.0)
        l_new = l_scr[nf, :, 0:1] * alpha + jnp.sum(
            p, axis=-1, keepdims=True
        )
        if sv is not None:
            p = p * sv
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[nf] = acc_scr[nf] * alpha + pv
        m_scr[nf] = jnp.broadcast_to(m_new, m_scr[nf].shape)
        l_scr[nf] = jnp.broadcast_to(l_new, l_scr[nf].shape)

    @pl.when(active)
    def _compute():
        buf = cnt[0] % _NBUF
        for cp in block_dma(s, kvb, buf):
            cp.wait()
        q_pos, col_ids = row_positions(blk)
        c_pos = block_start + col_ids
        mask = (c_pos <= q_pos) & (c_pos < seq_len)
        if has_quant:
            # Pre-transposed by the wrapper: [Hkv, BLK] — fold slices
            # are contiguous sublane rows.
            st_k = scale_blk_ref[0, 0]
            st_v = scale_blk_ref[0, 1]
        for nf in range(num_fold):
            lo = nf * fold_width
            k = kv_vmem[buf, 0, :, lo : lo + fold_width].astype(jnp.float32)
            v = kv_vmem[buf, 1, :, lo : lo + fold_width].astype(jnp.float32)
            if has_quant:
                flash_update(
                    nf, k, v, mask,
                    sk=scale_mat(st_k, nf, blk),
                    sv=scale_mat(st_v, nf, blk),
                )
            else:
                flash_update(nf, k, v, mask)
        cnt[0] = cnt[0] + 1
        cnt[1] = has_next.astype(jnp.int32)

    @pl.when(kvb == num_kvb - 1)
    def _finalize():
        if has_side:
            # Staged decode writes: this dispatch's K/V rows live in the
            # dense side buffer (positions seq_len + j), not the pool.
            # Fold them into the same online-softmax state before the
            # division.  seq_len here is the POOL length (the runner
            # passes base lengths when staging).
            n_side = side_len_ref[0]
            kblk = side_ref.shape[2]
            q_pos, col_ids = row_positions(kblk)
            side_pos = seq_len + col_ids
            smask = (
                (col_ids < n_side)
                & (side_pos <= q_pos)
                & (seq_len > 0)
            )
            for nf in range(num_fold):
                lo = nf * fold_width
                k = side_ref[0, 0, :, lo : lo + fold_width].astype(
                    jnp.float32
                )
                v = side_ref[0, 1, :, lo : lo + fold_width].astype(
                    jnp.float32
                )
                flash_update(nf, k, v, smask)
        for nf in range(num_fold):
            denom = jnp.maximum(l_scr[nf, :, 0:1], 1e-30)
            out_ref[0, 0, nf] = (acc_scr[nf] / denom).astype(out_ref.dtype)


def _pow2_floor(x: int) -> int:
    return 1 << (max(x, 1).bit_length() - 1)


def _state_bytes(hkv: int, g: int, mq: int, f: int, d: int) -> int:
    """f32 flash state for one grid step: acc [NF, ROWS, F*D] plus
    m/l [NF, ROWS, 128] each — NF*ROWS = hkv*g*mq regardless of F."""
    return hkv * g * mq * (f * d + 2 * _LANES) * 4


def _fold_align(hkv: int, d: int, hd_pad: int) -> int:
    """Smallest legal fold factor: F*D must be a 128-lane multiple (so
    the in-kernel lane slice is tile-aligned).  Returns hkv (single
    group over the whole padded width) when alignment inside the head
    count is impossible."""
    if (hkv * d) % _LANES or hd_pad != hkv * d:
        return hkv
    f = 1
    while (f * d) % _LANES:
        f *= 2
    return f if hkv % f == 0 else hkv


def _pick_fold(hkv: int, d: int, hd_pad: int, g: int, mq_blk: int):
    """Fold factor F (heads per matmul), fold width (lanes), NF groups.

    Constraints: F divides hkv; F*D is a multiple of 128 lanes; the
    whole f32 flash state stays under _STATE_BYTES.  When hkv*D itself
    is not 128-aligned the whole (padded) width is one fold group.
    """
    f = _fold_align(hkv, d, hd_pad)
    if f == hkv:
        return hkv, hd_pad, 1
    while (
        f * g * mq_blk < _ROWS_TARGET
        and hkv % (2 * f) == 0
        and _state_bytes(hkv, g, mq_blk, 2 * f, d) <= _STATE_BYTES
    ):
        f *= 2
    return f, f * d, hkv // f


def paged_attention(
    q: jax.Array,  # [T, Hq, D] flat
    kv_pages: jax.Array,  # [2, P, page, HD]
    metadata: AttentionMetadata,
    *,
    scale: float,
    soft_cap: float | None = None,
    num_kv_heads: int | None = None,
    max_q: int = 1,
    side_kv: jax.Array | None = None,  # [S, 2, K, HD] staged decode rows
    side_len: jax.Array | None = None,  # [1] int32: valid side columns
    interpret: bool = False,
) -> jax.Array:
    """Drop-in for paged_attention_reference (same contract), running the
    flash kernel.  `max_q` is the static per-sequence query bound for this
    step (the runner's padded max chunk length).

    ``side_kv``/``side_len``: staged decode writes — the fused decode
    scan keeps each micro-step's K/V rows in a dense per-sequence side
    buffer instead of scattering them into the paged pool every step
    (the pool is flushed once per dispatch).  Row j of a sequence's side
    buffer holds position ``metadata.seq_lens[s] + j`` (seq_lens is the
    POOL-resident length when staging); columns ``>= side_len`` are not
    yet written and are masked.

    An int8 pool arrives as a ``(data, per-head scales)`` tuple
    (ops/attention.py kv_scales_shape); the kernel DMAs the tiny scale
    slabs alongside the data pages and folds the dequant factors into
    the score/probability matrices (the side buffer stays in model
    dtype — only pool history is quantized)."""
    t, hq, d = q.shape
    has_quant = isinstance(kv_pages, tuple)
    kv_scales = None
    if has_quant:
        kv_pages, kv_scales = kv_pages
    _, p_total, page_size, hd_pad = kv_pages.shape
    s, max_pages = metadata.block_tables.shape
    hkv = num_kv_heads if num_kv_heads is not None else hq
    g = hq // hkv

    # maxq padded so a q block always has >= 8 rows (f32 sublane tile).
    maxq = next_power_of_2(max_q)
    while maxq * g * hkv < 8:
        maxq *= 2

    # Split maxq into q blocks whose full f32 flash state (acc + m + l,
    # at the alignment-minimum fold factor) fits the budget, then pick
    # the head fold factor.
    f_min = _fold_align(hkv, d, hd_pad)
    mq_blk = maxq
    while (
        _state_bytes(hkv, g, mq_blk, f_min, d) > _STATE_BYTES and mq_blk > 1
    ):
        mq_blk //= 2
    f, fd, nf = _pick_fold(hkv, d, hd_pad, g, mq_blk)
    while f * g * mq_blk < 8:  # tiny-model corner: widen the q block
        mq_blk *= 2
        maxq = max(maxq, mq_blk)
    num_qb = maxq // mq_blk
    rows = f * g * mq_blk

    # ---- group flat queries per sequence ----
    # Padding tokens carry q_seq_ids == S (one past the end); route their
    # scatter to an out-of-bounds column so it is DROPPED instead of
    # clobbering a real row (scatter drops OOB updates under jit).
    valid = metadata.q_seq_ids < s
    seq_idx = jnp.minimum(metadata.q_seq_ids, s - 1)
    tok_in_chunk = metadata.q_positions - metadata.chunk_starts[seq_idx]
    col = jnp.where(valid, tok_in_chunk, maxq)
    q_grouped = jnp.zeros((s, maxq, hq, d), q.dtype)
    q_grouped = q_grouped.at[seq_idx, col].set(q, mode="drop")

    # ---- block-diagonal fold:  [S, NQB, NF, ROWS, FD] ----
    q7 = q_grouped.reshape(s, num_qb, mq_blk, nf, f, g, d)
    q7 = q7.transpose(0, 1, 3, 4, 5, 2, 6)  # [S,NQB,NF,F,G,mq,D]
    eye = jnp.eye(f, dtype=q.dtype)
    q_bd = (
        q7[:, :, :, :, :, :, None, :]
        * eye[None, None, None, :, None, None, :, None]
    ).reshape(s, num_qb, nf, rows, f * d)
    if fd > f * d:  # padded single-group case: zero lanes at the end
        q_bd = jnp.pad(
            q_bd, [(0, 0), (0, 0), (0, 0), (0, 0), (0, fd - f * d)]
        )

    # ---- kv blocking: size blocks to the VMEM budget ----
    kv_bytes_per_token = 2 * hd_pad * jnp.dtype(kv_pages.dtype).itemsize
    if has_quant:
        kv_bytes_per_token += 2 * hkv * 4  # f32 scale rows
    blk_tokens = max(_KV_BUF_BYTES // kv_bytes_per_token, page_size)
    blk_tokens = min(_pow2_floor(blk_tokens), max_pages * page_size)
    if has_quant and blk_tokens < 128 and blk_tokens < max_pages * page_size:
        # The scale block's lane dim is BLK: it must be a 128 multiple
        # (or cover the whole context) for Mosaic's tiling.
        blk_tokens = min(128, max_pages * page_size)
    pages_per_blk = max(blk_tokens // page_size, 1)
    num_kvb = cdiv(max_pages, pages_per_blk)
    blk = pages_per_blk * page_size
    if max_pages % pages_per_blk:
        # Pad the table so block_dma never reads a page id out of bounds
        # (padding pages are id 0 — a real page, masked out of scores).
        pad = pages_per_blk - max_pages % pages_per_blk
        block_tables = jnp.pad(metadata.block_tables, ((0, 0), (0, pad)))
    else:
        block_tables = metadata.block_tables

    grid = (s, num_qb, num_kvb)
    has_side = side_kv is not None
    kernel = functools.partial(
        _kernel,
        scale=scale,
        soft_cap=soft_cap,
        page_size=page_size,
        pages_per_blk=pages_per_blk,
        group_size=g,
        num_fold=nf,
        fold_width=fd,
        mq_blk=mq_blk,
        has_side=has_side,
        has_quant=has_quant,
    )
    in_specs = [
        pl.BlockSpec(
            (1, 1, nf, rows, fd),
            # Scalar-prefetch refs ride along after grid indices.
            lambda s_, qb_, b_, *refs: (s_, qb_, 0, 0, 0),
        ),
    ]
    scalars = [block_tables, metadata.seq_lens, metadata.chunk_starts]
    inputs = [q_bd]
    if has_side:
        scalars.append(side_len.astype(jnp.int32))
        k_side = side_kv.shape[2]
        in_specs.append(
            pl.BlockSpec(
                (1, 2, k_side, hd_pad),
                lambda s_, qb_, b_, *refs: (s_, 0, 0, 0),
            )
        )
        inputs.append(side_kv)
    in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
    inputs.append(kv_pages)
    if has_quant:
        # Per-sequence transposed scale matrix [S, 2, Hkv, CTX_PAD],
        # gathered in XLA (loop-invariant per fused dispatch, so XLA
        # hoists it out of the decode scan).  The kernel consumes
        # lane-aligned [1, 2, Hkv, BLK] blocks via the regular
        # pipeline — a manual [page, Hkv] DMA slab would violate
        # Mosaic's 128-lane slice alignment.  Known cost: prefill/mixed
        # steps pay the gather each step, sized by the pages_pad bucket
        # (Hkv/ (4*D) of the data bytes — ~3% at D=64/f32 scales);
        # acceptable next to the chunk's matmul work, and shrinkable
        # with bf16 scales if it ever shows up in a profile.
        ctx_pad = num_kvb * blk
        sc = kv_scales[:, block_tables]  # [2, S, PAD_PAGES, page, Hkv]
        sc = sc.transpose(1, 0, 4, 2, 3).reshape(s, 2, hkv, ctx_pad)
        in_specs.append(
            pl.BlockSpec(
                (1, 2, hkv, blk),
                lambda s_, qb_, b_, *refs: (s_, 0, 0, b_),
            )
        )
        inputs.append(sc)
    scratch = [pltpu.VMEM((_NBUF, 2, blk, hd_pad), kv_pages.dtype)]
    scratch += [
        pltpu.VMEM((nf, rows, _LANES), jnp.float32),
        pltpu.VMEM((nf, rows, _LANES), jnp.float32),
        pltpu.VMEM((nf, rows, fd), jnp.float32),
        pltpu.SemaphoreType.DMA((_NBUF,)),
        pltpu.SMEM((2,), jnp.int32),
    ]
    out_bd = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=len(scalars),
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, 1, nf, rows, fd),
                lambda s_, qb_, b_, *refs: (s_, qb_, 0, 0, 0),
            ),
            scratch_shapes=scratch,
        ),
        out_shape=jax.ShapeDtypeStruct((s, num_qb, nf, rows, fd), q.dtype),
        interpret=interpret,
    )(*scalars, *inputs)

    # ---- extract the diagonal blocks back to the flat layout ----
    ob = out_bd[..., : f * d].reshape(s, num_qb, nf, f, g, mq_blk, f, d)
    # diagonal over (F_row, F_lane): row block i holds head i's output
    # in lane block i; everything off-diagonal is cross-head garbage.
    out7 = jnp.einsum("abcfgmfd->abcfgmd", ob)  # [S,NQB,NF,F,G,mq,D]
    out = out7.transpose(0, 1, 5, 2, 3, 4, 6).reshape(s, maxq, hq, d)
    return out[seq_idx, jnp.clip(tok_in_chunk, 0, maxq - 1)]


paged_attention.needs_max_q = True


def paged_attention_cpu(*args, **kwargs):
    """Interpret-mode entry for CPU tests."""
    return paged_attention(*args, interpret=True, **kwargs)


paged_attention_cpu.needs_max_q = True
