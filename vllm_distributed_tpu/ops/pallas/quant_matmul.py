"""Pallas weight-streaming quantized matmuls: x @ dequant(Wq).

The decode hot path is HBM-bound on weight reads; weight-only int8/int4
cuts those bytes 2×/4× — but ONLY if the compressed weights are what
actually streams.  XLA either hoists the dequant out of the fused
decode scan (materializing the bf16 model; blocked by an
optimization_barrier in model_runner) or materializes a dequantized
copy per micro-step, which pays compressed-read + bf16-write +
bf16-read and erases the win.  These kernels do what the hardware
wants: DMA compressed tiles HBM→VMEM (Pallas pipelines/double-buffers
the grid blocks), dequantize in VMEM, feed the MXU — the only HBM
traffic is the compressed bytes.

int4 packing note: the host packs input rows 2i (low nibble) and 2i+1
(high nibble) into one byte (ops/quant.py).  Un-interleaving rows in
VMEM would be a sublane relayout Mosaic handles poorly, so the kernel
never interleaves: a matmul contraction is order-invariant, so the
CALLER permutes x's columns to [evens | odds] (cheap XLA op on the tiny
activation) and the kernel runs TWO dots — low nibbles against the
even columns, high nibbles against the odd columns.  Group scales along
the input dim stay aligned because rows 2i and 2i+1 always share a
group (group sizes are even): each half's row r maps to group
r // (group/2), a contiguous sublane broadcast.

Activations stay exact (weight-only quantization, same numerics as
``dequantize()`` + matmul).

Used for 2D weights on the single-chip path; under tp>1 the int8 path
shard_maps per shard (ops/quant.py), int4 falls back to
dequant-in-graph.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def fits_vmem_budget(in_dim: int, block_out: int, x_nbytes: int) -> bool:
    """VMEM model per grid step: two double-buffered int8 weight tiles
    (in*blk*2) plus the f32 dequantized tile the kernel materializes
    before the dot (in*blk*4) plus f32-promoted x (~2*x_nbytes).  The
    26 MiB cap is empirically anchored: [8192x512] and [2048x2048]
    tiles (both = 24 MiB by this model) compile and run on v5e across
    the whole bench suite; one step up ([4096x2048] = 48 MiB) must not
    be approved.  Single source of truth for the int8 caller's
    eligibility check and the kernel's own guard (int4 has its own
    model below — its temporaries are larger)."""
    return in_dim * block_out * 6 + 2 * x_nbytes <= 26 * 2**20


def fits_vmem_budget4(in_dim: int, block_out: int, x_nbytes: int) -> bool:
    """int4 kernel VMEM model: per LOGICAL input element the kernel
    holds ~half-height planes of int32 q (2B), two f32 nibble planes
    (4B), the expanded scales (2B) and the two scaled operands (4B),
    plus double-buffered packed tiles — ~16B/element against int8's
    6B.  Same empirically-anchored 26 MiB cap."""
    return in_dim * block_out * 16 + 2 * x_nbytes <= 26 * 2**20


def _kernel(x_ref, q_ref, s_ref, o_ref, *, out_dtype):
    # x [T, IN] bf16/f32; q [IN, BLK] int8; s [1, BLK] f32 -> o [T, BLK]
    w = q_ref[...].astype(jnp.float32) * s_ref[0, :][None, :]
    acc = jnp.dot(
        x_ref[...].astype(jnp.float32),
        w,
        preferred_element_type=jnp.float32,
    )
    o_ref[...] = acc.astype(out_dtype)


def _kernel4(x_ref, q_ref, s_ref, o_ref, *, group, out_dtype):
    # x [T, IN] (columns permuted to [evens | odds]); q [IN/2, BLK]
    # uint8 (low nibble = even row, high = odd); s [IN/group, BLK] f32.
    half = q_ref.shape[0]
    # Mosaic has no direct uint8->f32 cast; hop through int32.
    q = q_ref[...].astype(jnp.int32)
    low = (q & 0xF).astype(jnp.float32) - 8.0
    high = (q >> 4).astype(jnp.float32) - 8.0
    # Each half's row r belongs to group r // (group/2): expand the
    # scale rows by sublane broadcast (shared by both halves).
    g2 = group // 2
    s = s_ref[...]
    sexp = jnp.broadcast_to(
        s[:, None, :], (s.shape[0], g2, s.shape[1])
    ).reshape(half, s.shape[1])
    x = x_ref[...].astype(jnp.float32)
    acc = jnp.dot(
        x[:, :half], low * sexp, preferred_element_type=jnp.float32
    )
    acc += jnp.dot(
        x[:, half:], high * sexp, preferred_element_type=jnp.float32
    )
    o_ref[...] = acc.astype(out_dtype)


def int4_matmul(
    x: jax.Array,  # [T, IN]
    q: jax.Array,  # [IN/2, OUT] uint8 (packed nibbles)
    scale: jax.Array,  # [IN/group, OUT] f32 (group-wise along IN)
    *,
    group: int,
    block_out: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """x @ dequant4(q, scale) with packed int4 weights streamed
    tile-by-tile (see module docstring for the permuted-contraction
    trick)."""
    t, in_dim = x.shape
    half, out_dim = q.shape
    assert half * 2 == in_dim, (x.shape, q.shape)
    assert group % 2 == 0 and group >= 2
    block_out = min(block_out, out_dim)
    if out_dim % block_out:
        raise ValueError(f"out dim {out_dim} % block {block_out} != 0")
    if not fits_vmem_budget4(in_dim, block_out, x.nbytes):
        raise ValueError(
            f"int4_matmul tile budget exceeded (in={in_dim}, "
            f"block={block_out}, T={t})"
        )
    # Permute the contraction to [evens | odds] (cheap: x is the small
    # activation).  The kernel's two dots undo the nibble packing.
    x2 = jnp.concatenate([x[:, 0::2], x[:, 1::2]], axis=-1)
    kernel = functools.partial(
        _kernel4, group=group, out_dtype=x.dtype
    )
    return pl.pallas_call(
        kernel,
        grid=(out_dim // block_out,),
        in_specs=[
            pl.BlockSpec((t, in_dim), lambda j: (0, 0)),
            pl.BlockSpec((half, block_out), lambda j: (0, j)),
            pl.BlockSpec(
                (scale.shape[0], block_out), lambda j: (0, j)
            ),
        ],
        out_specs=pl.BlockSpec((t, block_out), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((t, out_dim), x.dtype),
        interpret=interpret,
    )(x2, q, scale.astype(jnp.float32))


def int8_matmul(
    x: jax.Array,  # [T, IN]
    q: jax.Array,  # [IN, OUT] int8
    scale: jax.Array,  # [OUT] f32 (per output channel)
    *,
    block_out: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """x @ (q * scale) with int8 weights streamed tile-by-tile."""
    t, in_dim = x.shape
    in_q, out_dim = q.shape
    assert in_q == in_dim, (x.shape, q.shape)
    block_out = min(block_out, out_dim)
    if out_dim % block_out:
        raise ValueError(f"out dim {out_dim} % block {block_out} != 0")
    # [8192, 512] int8 = 4 MB/tile; x [T<=256, 8192] bf16 = 4 MB.  Bigger
    # in_dims would need an inner K loop; serving shapes fit.
    if not fits_vmem_budget(in_dim, block_out, x.nbytes):
        raise ValueError(
            f"int8_matmul tile budget exceeded (in={in_dim}, "
            f"block={block_out}, T={t})"
        )
    kernel = functools.partial(_kernel, out_dtype=x.dtype)
    return pl.pallas_call(
        kernel,
        grid=(out_dim // block_out,),
        in_specs=[
            pl.BlockSpec((t, in_dim), lambda j: (0, 0)),
            pl.BlockSpec((in_dim, block_out), lambda j: (0, j)),
            pl.BlockSpec((1, block_out), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((t, block_out), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((t, out_dim), x.dtype),
        interpret=interpret,
    )(x, q, scale.reshape(1, -1).astype(jnp.float32))
