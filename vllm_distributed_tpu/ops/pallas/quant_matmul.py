"""Pallas weight-streaming int8 matmul: x @ dequant(Wq).

The decode hot path is HBM-bound on weight reads; weight-only int8
halves those bytes — but ONLY if the int8 weights are what actually
streams.  XLA either hoists the dequant out of the fused decode scan
(materializing the bf16 model; blocked by an optimization_barrier in
model_runner) or materializes a dequantized copy per micro-step, which
pays int8-read + bf16-write + bf16-read and erases the win.  This
kernel does what the hardware wants: DMA int8 tiles HBM→VMEM (Pallas
pipelines/double-buffers the grid blocks), dequantize in VMEM, feed the
MXU in bf16 — the only HBM traffic is the int8 bytes.

Activations stay exact (weight-only quantization, same numerics as
``dequantize()`` + matmul: q.astype(f32) * scale).

Used for 2D per-channel int8 weights on the single-chip path; under
tp>1 the matmuls belong to GSPMD (a custom call would break its
partitioning), so the dequant-in-graph fallback applies there.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def fits_vmem_budget(in_dim: int, block_out: int, x_nbytes: int) -> bool:
    """VMEM model per grid step: two double-buffered int8 weight tiles
    (in*blk*2) plus the f32 dequantized tile the kernel materializes
    before the dot (in*blk*4) plus f32-promoted x (~2*x_nbytes).  The
    26 MiB cap is empirically anchored: [8192x512] and [2048x2048]
    tiles (both = 24 MiB by this model) compile and run on v5e across
    the whole bench suite; one step up ([4096x2048] = 48 MiB) must not
    be approved.  Single source of truth for the caller's eligibility
    check and the kernel's own guard."""
    return in_dim * block_out * 6 + 2 * x_nbytes <= 26 * 2**20


def _kernel(x_ref, q_ref, s_ref, o_ref, *, out_dtype):
    # x [T, IN] bf16/f32; q [IN, BLK] int8; s [1, BLK] f32 -> o [T, BLK]
    w = q_ref[...].astype(jnp.float32) * s_ref[0, :][None, :]
    acc = jnp.dot(
        x_ref[...].astype(jnp.float32),
        w,
        preferred_element_type=jnp.float32,
    )
    o_ref[...] = acc.astype(out_dtype)


def int8_matmul(
    x: jax.Array,  # [T, IN]
    q: jax.Array,  # [IN, OUT] int8
    scale: jax.Array,  # [OUT] f32 (per output channel)
    *,
    block_out: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """x @ (q * scale) with int8 weights streamed tile-by-tile."""
    t, in_dim = x.shape
    in_q, out_dim = q.shape
    assert in_q == in_dim, (x.shape, q.shape)
    block_out = min(block_out, out_dim)
    if out_dim % block_out:
        raise ValueError(f"out dim {out_dim} % block {block_out} != 0")
    # [8192, 512] int8 = 4 MB/tile; x [T<=256, 8192] bf16 = 4 MB.  Bigger
    # in_dims would need an inner K loop; serving shapes fit.
    if not fits_vmem_budget(in_dim, block_out, x.nbytes):
        raise ValueError(
            f"int8_matmul tile budget exceeded (in={in_dim}, "
            f"block={block_out}, T={t})"
        )
    kernel = functools.partial(_kernel, out_dtype=x.dtype)
    return pl.pallas_call(
        kernel,
        grid=(out_dim // block_out,),
        in_specs=[
            pl.BlockSpec((t, in_dim), lambda j: (0, 0)),
            pl.BlockSpec((in_dim, block_out), lambda j: (0, j)),
            pl.BlockSpec((1, block_out), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((t, block_out), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((t, out_dim), x.dtype),
        interpret=interpret,
    )(x, q, scale.reshape(1, -1).astype(jnp.float32))
