"""Pallas flush of staged decode K/V rows into the paged pool.

The fused decode scan stages each micro-step's K/V in a dense side
buffer (one in-place dynamic_update_slice per layer per step) instead
of scattering rows into the paged pool; after the scan, this kernel
folds a dispatch's K rows per sequence back into the pool in one pass.

Why a read-modify-write: Mosaic can only DMA tile-aligned slabs of the
pool's (page_size, HD) minor pair — single token rows are not
addressable (see ops/attention.py layout notes).  A sequence's K
consecutive rows [base, base+K) touch at most ceil(K/page)+1 pages, so
the kernel reads those page slabs, overlays the side rows with a
vectorized roll + iota select, and writes the slabs back.  Per
dispatch this is ~2 pages × 2 planes × r+w per sequence per layer —
~1-2 % of the decode step's attention traffic — versus a per-row write
EVERY micro-step on the old path (measured ~1.8 µs/row: several ms per
micro-step at batch 64).

Pipelining: the next sequence's slab reads are started before this
sequence's modify/write-back, so the read latency is hidden; the
write-back is waited in the same grid step (cheap — the slabs are
tens of KB), which keeps semaphore accounting trivially balanced even
when trailing padding rows are skipped.  Sequences' touched pages are
disjoint by the allocator; padding rows (base 0) are skipped, and any
over-read of the reserved dump page 0 via clamped table padding only
rewrites garbage with garbage.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(
    base_lens_ref,  # [S] int32 (pool-resident length; <=0 = skip row)
    page0_ref,  # [S] int32: base // page_size (logical first page)
    page_ids_ref,  # [S, NPT] int32: touched page ids (clamped, padded)
    n_side_ref,  # [S] int32: rows to flush per sequence (<= K)
    side_ref,  # [1, 2, K, HD] VMEM block (this sequence's staged rows)
    pool_in,  # [2, P, page, HD] ANY (aliased with pool_out)
    pool_out,
    slab_vmem,  # [2(pipe), 2(kv), NPT*page, HD]
    read_sems,  # [2]
    write_sem,
    *,
    page_size: int,
    npt: int,
):
    s = pl.program_id(0)
    num_s = pl.num_programs(0)
    buf = s % 2
    live = (base_lens_ref[s] > 0) & (n_side_ref[s] > 0)

    # Page id 0 is the reserved dump page (slack slab columns route
    # there): skip its copies entirely so the dump-page contract stays
    # read-only for this kernel — an unskipped write-back would race the
    # next sequence's prefetched read of the same page.  Start and wait
    # predicates read the same page_ids entries, so DMA semaphore
    # accounting stays balanced.
    def _for_each(seq, buf, action, direction):
        for pt in range(npt):
            page = page_ids_ref[seq, pt]

            @pl.when(page != 0)
            def _go(page=page, pt=pt):
                for kvi in range(2):
                    slab = slab_vmem.at[
                        buf, kvi, pl.ds(pt * page_size, page_size)
                    ]
                    if direction == "read":
                        cp = pltpu.make_async_copy(
                            pool_in.at[kvi, page], slab, read_sems.at[buf]
                        )
                    else:
                        cp = pltpu.make_async_copy(
                            slab, pool_out.at[kvi, page], write_sem
                        )
                    getattr(cp, action)()

    def start_reads(seq, buf):
        _for_each(seq, buf, "start", "read")

    def wait_reads(seq, buf):
        _for_each(seq, buf, "wait", "read")

    def start_writes(seq, buf):
        _for_each(seq, buf, "start", "write")

    def wait_writes(seq, buf):
        _for_each(seq, buf, "wait", "write")

    # Prologue: nobody prefetched row 0's slabs.
    @pl.when((s == 0) & live)
    def _first_reads():
        start_reads(s, buf)

    # Prefetch the next sequence's slabs while this one modifies/writes.
    # The predicate must MATCH the next grid step's `live` exactly: a
    # started copy whose wait is skipped would leave its semaphore
    # signaled for a later sequence on the same buffer parity.
    nxt = jnp.minimum(s + 1, num_s - 1)

    @pl.when(
        (s + 1 < num_s)
        & (base_lens_ref[nxt] > 0)
        & (n_side_ref[nxt] > 0)
    )
    def _next_reads():
        start_reads(nxt, (s + 1) % 2)

    @pl.when(live)
    def _modify_and_write():
        wait_reads(s, buf)
        n_side = n_side_ref[s]
        rows = npt * page_size
        base = base_lens_ref[s]
        off = base - page0_ref[s] * page_size  # first row's slab offset
        row_ids = jax.lax.broadcasted_iota(
            jnp.int32, (rows, side_ref.shape[3]), 0
        )
        in_window = (row_ids >= off) & (row_ids < off + n_side)
        for kvi in range(2):
            # Side row j lands at slab row off + j: pad side to the slab
            # height and roll it down by `off`.  Mosaic only rotates
            # 32-bit lanes, so roll in f32 (exact for bf16/int8 values).
            side = side_ref[0, kvi].astype(jnp.float32)  # [K, HD]
            padded = jnp.pad(side, [(0, rows - side.shape[0]), (0, 0)])
            shifted = pltpu.roll(padded, off, 0).astype(slab_vmem.dtype)
            cur = slab_vmem[buf, kvi]
            slab_vmem[buf, kvi] = jnp.where(in_window, shifted, cur)
        start_writes(s, buf)
        wait_writes(s, buf)


def kv_flush(
    kv_pages,  # [2, P, page, HD] — or (int8 data, per-head scales)
    side_kv: jax.Array,  # [S, 2, K, HD] (model dtype)
    block_tables: jax.Array,  # [S, max_pages] int32
    base_lens: jax.Array,  # [S] int32 (0 = padding row, skipped)
    n_side: jax.Array,  # [S] (or [1], broadcast) int32: rows per sequence
    *,
    interpret: bool = False,
):
    """Write each live sequence's staged rows [base, base+n_side[s])
    into the pool, in place (aliased).  Per-sequence lengths let the
    fused decode scan mask under-K request tails (model_runner).

    For an int8 pool the staged rows are quantized per kv head here
    (plain XLA — a [S, 2, K] reduction, off the micro-step path); the
    int8 data planes go through the overlay kernel, while the f32
    scale planes — ~HD/(4·Hkv)× smaller, and too narrow for Mosaic's
    128-lane DMA slice alignment — are written by a functional XLA
    scatter on the donated buffer (in place; worst case one small copy
    per dispatch).  Under shard_map each shard quantizes its own
    heads' lanes, which is bit-identical to the global per-head
    computation."""
    if isinstance(kv_pages, tuple):
        from vllm_distributed_tpu.ops.attention import quantize_kv_heads

        data, scales = kv_pages
        hkv = scales.shape[-1]
        side_q, side_s = quantize_kv_heads(side_kv, hkv)
        data = kv_flush(
            data, side_q, block_tables, base_lens, n_side,
            interpret=interpret,
        )
        s, _, k_blk, _ = side_kv.shape
        page_size = data.shape[2]
        if n_side.shape[0] != s:
            n_side = jnp.broadcast_to(n_side, (s,))
        # Row j of sequence s lands at pool position base+j; rows past
        # n_side[s] (and dead sequences) scatter into dump page 0.
        j = jnp.arange(k_blk, dtype=jnp.int32)[None, :]
        pos = base_lens[:, None] + j  # [S, K]
        live = (base_lens[:, None] > 0) & (j < n_side[:, None])
        page_idx = jnp.where(live, pos // page_size, 0)
        pages = jnp.take_along_axis(
            block_tables, jnp.minimum(page_idx, block_tables.shape[1] - 1),
            axis=1,
        )
        pages = jnp.where(live, pages, 0)
        rows = jnp.where(live, pos % page_size, 0)
        scales = scales.at[0, pages, rows].set(side_s[:, 0])
        scales = scales.at[1, pages, rows].set(side_s[:, 1])
        return (data, scales)
    _, p_total, page_size, hd = kv_pages.shape
    s, _, k_blk, _ = side_kv.shape
    npt = (k_blk + page_size - 1) // page_size + 1
    if n_side.shape[0] != s:
        n_side = jnp.broadcast_to(n_side, (s,))

    page0 = base_lens // page_size
    pts = page0[:, None] + jnp.arange(npt, dtype=jnp.int32)[None, :]
    # The slab's slack column can step past the table: route it to the
    # reserved dump page 0, NOT a clamped real page — a clamped
    # duplicate would write a stale copy of the sequence's last page
    # over the freshly flushed rows.  (In-table entries past a
    # sequence's allocation are already 0 by table construction.)
    in_table = pts < block_tables.shape[1]
    gathered = jnp.take_along_axis(
        block_tables, jnp.minimum(pts, block_tables.shape[1] - 1), axis=1
    )
    page_ids = jnp.where(in_table, gathered, 0)

    kernel = functools.partial(_kernel, page_size=page_size, npt=npt)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(s,),
            in_specs=[
                pl.BlockSpec(
                    (1, 2, k_blk, hd),
                    lambda s_, *refs: (s_, 0, 0, 0),
                ),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[
                pltpu.VMEM(
                    (2, 2, npt * page_size, hd), kv_pages.dtype
                ),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA,
            ],
        ),
        out_shape=jax.ShapeDtypeStruct(kv_pages.shape, kv_pages.dtype),
        # Inputs: 0-3 scalar prefetch, 4 side, 5 pool → output 0.
        input_output_aliases={5: 0},
        interpret=interpret,
    )(
        base_lens.astype(jnp.int32),
        page0.astype(jnp.int32),
        page_ids.astype(jnp.int32),
        n_side.astype(jnp.int32),
        side_kv,
        kv_pages,
    )
    return out


def kv_flush_cpu(*args, **kwargs):
    """Interpret-mode entry for CPU tests."""
    return kv_flush(*args, interpret=True, **kwargs)
