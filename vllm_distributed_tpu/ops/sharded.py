"""shard_map partitioning of the Pallas paged-attention ops over "tp".

GSPMD cannot partition a Pallas custom call: under a tp>1 mesh it either
replicates the kernel (wrong memory/compute) or fails to lower.  The
runner therefore wraps the production kernels in ``jax.shard_map`` so
each device runs the kernel on its *local* head shard — q heads shard
over "tp", and the combined KV pool ``[2, P, page, HD]`` shards its
flat head×dim lanes (dim 3) over "tp", which is exactly per-kv-head
sharding because HD stores heads contiguously (``HD/tp = (Hkv/tp)*D``).
Per-head attention is embarrassingly parallel, so sharded outputs are
bit-identical to the unsharded kernel.  The matmuls around the kernels
stay GSPMD-partitioned; the row-parallel ``wo`` all-reduce is still
inserted by XLA outside the shard_map region.

This is the TPU-native analog of the reference's per-rank attention:
each NCCL rank runs CUDA attention on its head shard inside vLLM
workers (SURVEY.md §2.2, §2.4 TP row; TP-group discipline
launch.py:211-247).

dp>1 is not supported on this path: the KV pool is replicated over "dp",
and a manual per-shard write would diverge the replicas (each dp group
writes different tokens).  The runner keeps the XLA scatter/gather path
for dp>1, where GSPMD maintains replica consistency.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from vllm_distributed_tpu.ops.attention import AttentionMetadata

# Attention metadata is replicated: every device sees every sequence's
# block table / lengths; only heads are sharded.
_META_SPECS = AttentionMetadata(
    q_seq_ids=P(),
    q_positions=P(),
    slot_mapping=P(),
    block_tables=P(),
    seq_lens=P(),
    logits_indices=P(),
    chunk_starts=P(),
)

_Q_SPEC = P(None, "tp", None)  # [T, Hq, D] — heads sharded
# [2, P, page, HD] — flat head lanes sharded (== per-kv-head sharding).
_KV_SPEC = P(None, None, None, "tp")
# [S, 2, K, HD] staged decode side buffer — same lane sharding.
_SIDE_SPEC = P(None, None, None, "tp")


def _check_divisible(mesh: Mesh, num_heads: int, num_kv_heads: int) -> None:
    tp = mesh.shape.get("tp", 1)
    if num_heads % tp or num_kv_heads % tp:
        raise ValueError(
            f"tp={tp} must divide num_heads={num_heads} and "
            f"num_kv_heads={num_kv_heads} to shard the Pallas kernels"
        )


def _pool_spec(kv_pages):
    """Sharding spec(s) for a pool operand: an int8 pool is a (data,
    scales) tuple — the scale plane's lane axis is kv heads, which
    shards over tp exactly like the data plane's per-head HD lanes."""
    if isinstance(kv_pages, tuple):
        return (_KV_SPEC, _KV_SPEC)
    return _KV_SPEC


def shard_attention(attn_fn, mesh: Mesh):
    """Wrap a paged-attention kernel to run per-tp-shard under shard_map."""
    tp = mesh.shape.get("tp", 1)

    def wrapped(
        q, kv_pages, metadata, *,
        num_kv_heads=None, side_kv=None, side_len=None, **kw,
    ):
        hkv = num_kv_heads if num_kv_heads is not None else q.shape[1]
        has_side = side_kv is not None

        def body(q_, kv_, m_, *side_args):
            if side_args:
                kw.update(side_kv=side_args[0], side_len=side_args[1])
            return attn_fn(q_, kv_, m_, num_kv_heads=hkv // tp, **kw)

        in_specs = [_Q_SPEC, _pool_spec(kv_pages), _META_SPECS]
        operands = [q, kv_pages, metadata]
        if has_side:
            in_specs += [_SIDE_SPEC, P()]
            operands += [side_kv, side_len]
        f = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=tuple(in_specs),
            out_specs=_Q_SPEC,
            check_vma=False,
        )
        return f(*operands)

    wrapped.needs_max_q = getattr(attn_fn, "needs_max_q", False)
    return wrapped


def shard_kv_flush(flush_fn, mesh: Mesh):
    """Wrap the staged-decode flush kernel to run per-tp-shard: pool and
    side buffer shard their flat head lanes; tables/lengths replicate."""

    def wrapped(kv_pages, side_kv, block_tables, base_lens, n_side):
        spec = _pool_spec(kv_pages)
        f = jax.shard_map(
            flush_fn,
            mesh=mesh,
            in_specs=(spec, _SIDE_SPEC, P(), P(), P()),
            out_specs=spec,
            check_vma=False,
        )
        return f(kv_pages, side_kv, block_tables, base_lens, n_side)

    return wrapped
