"""Paged attention over a block-paged KV cache — pure-JAX reference path.

This is the TPU-native equivalent of the CUDA PagedAttention kernels the
reference inherits from the vLLM image (SURVEY.md §2.2).  The layout
contract shared by the allocator (engine/block_manager.py), the model
runner's KV scatter, and the kernels:

- KV pool: ONE combined array per layer of shape ``[2, num_pages,
  page_size, HD]`` — dim 0 is K/V, dims 1-2 address the token slot,
  and ``HD = num_kv_heads * head_dim`` is the flat head×dim lane axis.
  Rationale (measured on v5e, see PERF.md):
    * heads are stored unpadded and contiguous in HD, so the attention
      kernel computes on ``[BLK, F*D]`` tiles with ONE matmul + softmax
      chain per fold group instead of per-head slivers (the r3 kernel
      was compute-bound on those), and a 64-wide head model (Llama-1B
      class) no longer pays the 2× lane-padding tax of a per-head
      ``[..., Hkv, 128]`` layout;
    * a page is ``.at[kv, page]`` — a slice of the two MAJOR dims, so
      the kernel fetches it as one contiguous ``[page, HD]`` DMA per
      K/V plane.  (Mosaic cannot slice single rows of the tiled
      (page_size, HD) minor pair, which is why the decode-path writer
      uses XLA dynamic_update_slice instead of a DMA kernel —
      ops/pallas/kv_update.py);
    * token ``t`` of a request lives at page ``page_ids[t //
      page_size]``, row ``t % page_size``.
- A step's work is a flat token batch ``[T]`` spanning mixed prefill
  chunks and decodes; ``q_seq_ids``/``q_positions`` say which sequence
  and absolute position each query token has.

Everything is static-shape and jit-friendly: padding tokens carry
``q_seq_ids`` pointing at padded sequence rows whose ``seq_lens`` is 0,
so their attention rows are garbage that is never read.  The fast path
is the Pallas kernel in ops/pallas/; this reference is the correctness
oracle (tested against each other, SURVEY.md §4.2) and the CPU fallback.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def kv_pool_width(num_kv_heads: int, head_dim: int) -> int:
    """Flat lane width HD of the combined pool.

    No padding: every production shape (heads × 64/128-wide dims) is
    already a multiple of the 128-lane tile, and padding would both
    waste bytes and break per-head TP sharding of the flat lane axis
    for sub-128 test shapes (the pad would land in the last shard
    instead of spreading per head).  The Pallas kernel's single-fold
    fallback handles non-128-multiple widths.
    """
    return num_kv_heads * head_dim


def kv_pool_shape(
    num_pages: int, page_size: int, num_kv_heads: int, head_dim: int
) -> tuple[int, int, int, int]:
    return (
        2,
        num_pages,
        page_size,
        kv_pool_width(num_kv_heads, head_dim),
    )


def kv_scales_shape(
    num_pages: int, page_size: int, num_kv_heads: int
) -> tuple:
    """Scale plane of an int8 KV pool: one f32 scale PER (token, kv
    head), lane axis = kv heads.  Per-head (not per-token-row) scales
    are what make the quantized pool TP-shardable: the flat HD lane
    axis shards per head, so each shard's local absmax over its own
    heads' lanes IS the per-head scale — bit-identical to the
    unsharded computation, with the scale array sharding over the same
    lane axis (``tp`` must divide num_kv_heads, enforced at load)."""
    return (2, num_pages, page_size, num_kv_heads)


def quantize_kv_heads(
    k: jax.Array,  # [..., Hkv * D] flat rows (model dtype)
    num_kv_heads: int,
) -> tuple[jax.Array, jax.Array]:
    """Symmetric per-(row, kv-head) int8 quantization.

    Returns (q [..., Hkv*D] int8, s [..., Hkv] f32) with
    row ≈ q * s[..., head_of_lane]."""
    d = k.shape[-1] // num_kv_heads
    kf = k.astype(jnp.float32).reshape(*k.shape[:-1], num_kv_heads, d)
    s = jnp.maximum(jnp.max(jnp.abs(kf), axis=-1), 1e-8) / 127.0
    q = jnp.clip(jnp.round(kf / s[..., None]), -127, 127).astype(jnp.int8)
    return q.reshape(k.shape), s


def split_kv_pages(
    kv_pages, num_kv_heads: int, head_dim: int
) -> tuple[jax.Array, jax.Array]:
    """Views of the combined pool as per-head [P, page, Hkv, D] K and V.

    A quantized pool ((int8 data, per-head scales) tuple) dequantizes
    to f32."""
    if isinstance(kv_pages, tuple):
        data, scales = kv_pages
        _, p, page, hd = data.shape
        shape = (p, page, num_kv_heads, head_dim)
        deq = (
            data.astype(jnp.float32).reshape(2, *shape)
            * scales[..., None]
        )
        return deq[0], deq[1]
    _, p, page, hd = kv_pages.shape
    shape = (p, page, num_kv_heads, head_dim)
    return kv_pages[0].reshape(shape), kv_pages[1].reshape(shape)


def merge_kv_pages(k_pages: jax.Array, v_pages: jax.Array) -> jax.Array:
    """Inverse of split_kv_pages (test/bench helper)."""
    p, page, hkv, d = k_pages.shape
    return jnp.stack(
        [
            k_pages.reshape(p, page, hkv * d),
            v_pages.reshape(p, page, hkv * d),
        ],
        axis=0,
    )


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class AttentionMetadata:
    """Per-step attention inputs shared by every layer.

    Shapes (all padded to bucketed static sizes):
      q_seq_ids:      [T] int32  — row of each query token in the seq batch
      q_positions:    [T] int32  — absolute position of each query token
      slot_mapping:   [T] int32  — flat KV slot each token's K/V is written to
                                   (padding tokens point into reserved page 0)
      block_tables:   [S, max_pages] int32 — page ids per sequence (0-padded)
      seq_lens:       [S] int32  — total context length per sequence
                                   (computed + scheduled this step; 0 = pad row)
      logits_indices: [S] int32  — flat token index whose hidden state is
                                   sampled for each sequence
      chunk_starts:   [S] int32  — absolute position of each sequence's
                                   first query token this step (= its
                                   num_computed_tokens before the step)
    """

    q_seq_ids: jax.Array
    q_positions: jax.Array
    slot_mapping: jax.Array
    block_tables: jax.Array
    seq_lens: jax.Array
    logits_indices: jax.Array
    chunk_starts: jax.Array


def write_kv_pages(
    kv_pages,  # [2, P, page, HD] or (int8 pool, scales) tuple
    k: jax.Array,  # [T, Hkv, D]
    v: jax.Array,
    slot_mapping: jax.Array,
):
    """Scatter this step's K/V into the combined paged pool (quantizing
    on write when the pool is int8).

    Functional reference / CPU / prefill path.  The production decode
    path is the per-row dynamic_update_slice writer
    (ops/pallas/kv_update.py) — XLA does not keep this scatter in place
    inside the fused decode scan at large pool sizes.
    """
    if isinstance(kv_pages, tuple):
        data, scales = kv_pages
        _, _, page_size, hd = data.shape
        hkv = scales.shape[-1]
        t = k.shape[0]
        q_k, s_k = quantize_kv_heads(k.reshape(t, -1), hkv)
        q_v, s_v = quantize_kv_heads(v.reshape(t, -1), hkv)
        if q_k.shape[-1] < hd:  # sub-tile pools pad HD (like below)
            pad = [(0, 0), (0, hd - q_k.shape[-1])]
            q_k = jnp.pad(q_k, pad)
            q_v = jnp.pad(q_v, pad)
        pages = slot_mapping // page_size
        rows = slot_mapping % page_size
        data = data.at[0, pages, rows].set(q_k)
        data = data.at[1, pages, rows].set(q_v)
        scales = scales.at[0, pages, rows].set(s_k)
        scales = scales.at[1, pages, rows].set(s_v)
        return (data, scales)
    _, _, page_size, hd = kv_pages.shape
    t, hkv, d = k.shape
    k = k.reshape(t, hkv * d).astype(kv_pages.dtype)
    v = v.reshape(t, hkv * d).astype(kv_pages.dtype)
    if hkv * d < hd:  # sub-tile pools pad HD (kv_update does the same)
        pad = [(0, 0), (0, hd - hkv * d)]
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    pages = slot_mapping // page_size
    rows = slot_mapping % page_size
    kv_pages = kv_pages.at[0, pages, rows].set(k)
    kv_pages = kv_pages.at[1, pages, rows].set(v)
    return kv_pages


@partial(jax.jit, static_argnames=("scale", "soft_cap", "num_kv_heads"))
def paged_attention_reference(
    q: jax.Array,  # [T, Hq, D]
    kv_pages: jax.Array,  # [2, P, page, HD]
    metadata: AttentionMetadata,
    *,
    scale: float,
    soft_cap: float | None = None,
    num_kv_heads: int | None = None,
    side_kv: jax.Array | None = None,  # [S, 2, K, HD] staged decode rows
    side_len: jax.Array | None = None,  # [1] int32
) -> jax.Array:
    """Causal attention of flat query tokens against their sequences' paged
    KV history.  O(T × max_ctx) with full gathers — the oracle, not the
    fast path.

    ``side_kv``/``side_len``: staged decode rows holding positions
    ``seq_lens[s] + j`` (seq_lens is the pool-resident length when
    staging) — see the Pallas kernel's docstring.
    """
    t, hq, d = q.shape
    hkv = num_kv_heads if num_kv_heads is not None else hq
    k_pages, v_pages = split_kv_pages(kv_pages, hkv, d)
    _, page_size, _, _ = k_pages.shape
    s, max_pages = metadata.block_tables.shape
    groups = hq // hkv
    max_ctx = max_pages * page_size

    # Gather each sequence's KV: [S, max_ctx, Hkv, D].
    k_all = k_pages[metadata.block_tables].reshape(s, max_ctx, hkv, d)
    v_all = v_pages[metadata.block_tables].reshape(s, max_ctx, hkv, d)

    seq_lens_tok = metadata.seq_lens[metadata.q_seq_ids]  # [T]
    ctx_pos = jnp.arange(max_ctx, dtype=jnp.int32)
    valid = ctx_pos[None, :] <= metadata.q_positions[:, None]  # causal
    valid &= ctx_pos[None, :] < seq_lens_tok[:, None]

    if side_kv is not None:
        k_blk = side_kv.shape[2]
        side = side_kv[..., : hkv * d].reshape(s, 2, k_blk, hkv, d)
        k_all = jnp.concatenate([k_all, side[:, 0]], axis=1)
        v_all = jnp.concatenate([v_all, side[:, 1]], axis=1)
        j = jnp.arange(k_blk, dtype=jnp.int32)
        side_pos = seq_lens_tok[:, None] + j[None, :]  # [T, K]
        side_valid = (
            (j[None, :] < side_len[0])
            & (side_pos <= metadata.q_positions[:, None])
            & (seq_lens_tok[:, None] > 0)
        )
        valid = jnp.concatenate([valid, side_valid], axis=1)

    # Per query token, its sequence's KV: [T, C, Hkv, D].
    k_tok = k_all[metadata.q_seq_ids]
    v_tok = v_all[metadata.q_seq_ids]

    qg = q.reshape(t, hkv, groups, d).astype(jnp.float32)
    scores = jnp.einsum(
        "thgd,tchd->thgc", qg, k_tok.astype(jnp.float32)
    ) * scale  # [T, Hkv, G, C]
    if soft_cap is not None:
        scores = jnp.tanh(scores / soft_cap) * soft_cap

    scores = jnp.where(valid[:, None, None, :], scores, DEFAULT_MASK_VALUE)

    scores = scores - jnp.max(scores, axis=-1, keepdims=True)
    probs = jnp.exp(scores)
    denom = jnp.sum(probs, axis=-1, keepdims=True)
    probs = probs / jnp.maximum(denom, 1e-30)

    out = jnp.einsum("thgc,tchd->thgd", probs, v_tok.astype(jnp.float32))
    return out.reshape(t, hq, d).astype(q.dtype)
