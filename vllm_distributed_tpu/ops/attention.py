"""Paged attention over a block-paged KV cache — pure-JAX reference path.

This is the TPU-native equivalent of the CUDA PagedAttention kernels the
reference inherits from the vLLM image (SURVEY.md §2.2).  The layout
contract shared by the allocator (engine/block_manager.py), the model
runner's KV scatter, and the kernels:

- KV pool: ``k_pages``/``v_pages`` of shape ``[num_pages, page_size,
  num_kv_heads, head_dim]`` — slot-major so (a) one token's K/V row
  ``[Hkv, D]`` is a tile-aligned single DMA target (the in-place Pallas
  writer needs single-slot writes; Mosaic only allows full-tile slices
  of the minor-two dims), and (b) a page is one contiguous
  ``[page_size, Hkv, D]`` DMA for the attention kernel.  Token ``t`` of
  a request lives at flat slot ``page_ids[t // page_size] * page_size +
  t % page_size``.
- A step's work is a flat token batch ``[T]`` spanning mixed prefill
  chunks and decodes; ``q_seq_ids``/``q_positions`` say which sequence and
  absolute position each query token has.

Everything is static-shape and jit-friendly: padding tokens carry
``q_seq_ids`` pointing at padded sequence rows whose ``seq_lens`` is 0, so
their attention rows are garbage that is never read.  The fast path is the
Pallas kernel in ops/pallas/; this reference is the correctness oracle
(tested against each other, SURVEY.md §4.2) and the CPU fallback.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class AttentionMetadata:
    """Per-step attention inputs shared by every layer.

    Shapes (all padded to bucketed static sizes):
      q_seq_ids:      [T] int32  — row of each query token in the seq batch
      q_positions:    [T] int32  — absolute position of each query token
      slot_mapping:   [T] int32  — flat KV slot each token's K/V is written to
                                   (padding tokens point into reserved page 0)
      block_tables:   [S, max_pages] int32 — page ids per sequence (0-padded)
      seq_lens:       [S] int32  — total context length per sequence
                                   (computed + scheduled this step; 0 = pad row)
      logits_indices: [S] int32  — flat token index whose hidden state is
                                   sampled for each sequence
      chunk_starts:   [S] int32  — absolute position of each sequence's
                                   first query token this step (= its
                                   num_computed_tokens before the step)
    """

    q_seq_ids: jax.Array
    q_positions: jax.Array
    slot_mapping: jax.Array
    block_tables: jax.Array
    seq_lens: jax.Array
    logits_indices: jax.Array
    chunk_starts: jax.Array


def write_kv_pages(
    k_pages: jax.Array,
    v_pages: jax.Array,
    k: jax.Array,
    v: jax.Array,
    slot_mapping: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """Scatter this step's K/V ([T, Hkv, D]) into the paged pool.

    Functional reference / CPU path.  The production TPU path is the
    aliased Pallas writer (ops/pallas/kv_update.py) — XLA does not keep
    this scatter in place inside the fused decode scan at large pool
    sizes.
    """
    num_pages, page_size, hkv, d = k_pages.shape
    if k.shape[-1] < d:
        # Pool head dim is lane-padded (to 128) for the Pallas kernel's
        # DMA alignment; zero-pad the incoming heads to match.
        pad = [(0, 0), (0, 0), (0, d - k.shape[-1])]
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    flat_k = k_pages.reshape(num_pages * page_size, hkv, d)
    flat_v = v_pages.reshape(num_pages * page_size, hkv, d)
    flat_k = flat_k.at[slot_mapping].set(k.astype(flat_k.dtype))
    flat_v = flat_v.at[slot_mapping].set(v.astype(flat_v.dtype))
    return (
        flat_k.reshape(num_pages, page_size, hkv, d),
        flat_v.reshape(num_pages, page_size, hkv, d),
    )


@partial(jax.jit, static_argnames=("scale", "soft_cap"))
def paged_attention_reference(
    q: jax.Array,  # [T, Hq, D]
    k_pages: jax.Array,  # [P, page_size, Hkv, D]
    v_pages: jax.Array,  # [P, page_size, Hkv, D]
    metadata: AttentionMetadata,
    *,
    scale: float,
    soft_cap: float | None = None,
) -> jax.Array:
    """Causal attention of flat query tokens against their sequences' paged
    KV history.  O(T × max_ctx) with full gathers — the oracle, not the
    fast path."""
    t, hq, d = q.shape
    _, page_size, hkv, d_pool = k_pages.shape
    s, max_pages = metadata.block_tables.shape
    groups = hq // hkv
    max_ctx = max_pages * page_size
    if d_pool > d:  # lane-padded pool (see write_kv_pages)
        k_pages = k_pages[..., :d]
        v_pages = v_pages[..., :d]

    # Gather each sequence's KV: [S, max_ctx, Hkv, D].
    k_all = k_pages[metadata.block_tables].reshape(s, max_ctx, hkv, d)
    v_all = v_pages[metadata.block_tables].reshape(s, max_ctx, hkv, d)

    # Per query token, its sequence's KV: [T, max_ctx, Hkv, D].
    k_tok = k_all[metadata.q_seq_ids]
    v_tok = v_all[metadata.q_seq_ids]

    qg = q.reshape(t, hkv, groups, d).astype(jnp.float32)
    scores = jnp.einsum(
        "thgd,tchd->thgc", qg, k_tok.astype(jnp.float32)
    ) * scale  # [T, Hkv, G, C]
    if soft_cap is not None:
        scores = jnp.tanh(scores / soft_cap) * soft_cap

    ctx_pos = jnp.arange(max_ctx, dtype=jnp.int32)
    valid = ctx_pos[None, :] <= metadata.q_positions[:, None]  # causal
    valid &= ctx_pos[None, :] < metadata.seq_lens[metadata.q_seq_ids][:, None]
    scores = jnp.where(valid[:, None, None, :], scores, DEFAULT_MASK_VALUE)

    scores = scores - jnp.max(scores, axis=-1, keepdims=True)
    probs = jnp.exp(scores)
    denom = jnp.sum(probs, axis=-1, keepdims=True)
    probs = probs / jnp.maximum(denom, 1e-30)

    out = jnp.einsum("thgc,tchd->thgd", probs, v_tok.astype(jnp.float32))
    return out.reshape(t, hq, d).astype(q.dtype)
