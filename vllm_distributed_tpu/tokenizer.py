"""Tokenizer access + incremental detokenization.

The engine-side capability the reference delegates to vLLM's tokenizer
group (SURVEY.md §2.3: EngineClient surface).  ``IncrementalDetokenizer``
implements streaming-safe decoding: multi-byte/multi-token glyphs are held
back until complete, and stop strings are matched over the accumulated
text (stop-string truncation happens here, not in the scheduler).
"""

from __future__ import annotations

from typing import Any


def get_tokenizer(
    tokenizer_name: str, trust_remote_code: bool = False
) -> Any:
    from transformers import AutoTokenizer

    return AutoTokenizer.from_pretrained(
        tokenizer_name, trust_remote_code=trust_remote_code, use_fast=True
    )


class IncrementalDetokenizer:
    """Per-request streaming detokenizer.

    Decodes with a sliding window of already-emitted tokens (the standard
    prefix-offset scheme) so byte-level BPE pieces that straddle token
    boundaries render correctly, and replacement chars at the tail are
    withheld until resolved.
    """

    def __init__(
        self,
        tokenizer: Any,
        prompt_token_ids: list[int],
        *,
        stop: list[str] | None = None,
        include_stop_str_in_output: bool = False,
        skip_special_tokens: bool = True,
        min_tokens: int = 0,
    ) -> None:
        self.tokenizer = tokenizer
        self.token_ids: list[int] = list(prompt_token_ids)
        self.prompt_len = len(prompt_token_ids)
        self.stop = stop or []
        self.include_stop = include_stop_str_in_output
        self.skip_special = skip_special_tokens
        # Offsets into self.token_ids for the incremental window.
        self.prefix_offset = max(self.prompt_len - 6, 0)
        self.read_offset = self.prompt_len
        self.output_text = ""
        self.stopped_on: str | None = None
        # Stop strings are suppressed until min_tokens have been generated
        # (matching the token-level min_tokens gate in Request.check_stop).
        self.min_tokens = min_tokens
        self._tokens_seen = 0
        # Stop-string scan cursor: text before this offset was already
        # checked (keeps per-token matching O(new text), not O(total)).
        self._stop_scanned = 0
        self._max_stop_len = max((len(s) for s in self.stop), default=0)

    def append(self, token_ids: list[int]) -> str:
        """Feed newly sampled tokens; returns the newly finalized text.
        Sets ``stopped_on`` when a stop string is hit (output_text is then
        already truncated per include_stop_str_in_output)."""
        new_text = ""
        for tok in token_ids:
            self.token_ids.append(tok)
            self._tokens_seen += 1
            prefix = self.tokenizer.decode(
                self.token_ids[self.prefix_offset : self.read_offset],
                skip_special_tokens=self.skip_special,
            )
            full = self.tokenizer.decode(
                self.token_ids[self.prefix_offset :],
                skip_special_tokens=self.skip_special,
            )
            if len(full) > len(prefix) and not full.endswith("�"):
                delta = full[len(prefix) :]
                self.prefix_offset = self.read_offset
                self.read_offset = len(self.token_ids)
                self.output_text += delta
                new_text += delta
                hit = self._check_stop()
                if hit is not None:
                    self.stopped_on = hit
                    return new_text
        return new_text

    @property
    def stop_token_count(self) -> int:
        """Output tokens consumed up to and including the one that
        completed the stop string (valid once ``stopped_on`` is set) —
        used to truncate token_ids/logprobs/usage to match the text."""
        return self._tokens_seen

    def _check_stop(self) -> str | None:
        if not self.stop:
            return None
        if self._tokens_seen < self.min_tokens:
            # Stops occurring before min_tokens are IGNORED, not deferred:
            # advance the scan cursor past the suppressed text.
            self._stop_scanned = len(self.output_text)
            return None
        start = max(self._stop_scanned - (self._max_stop_len - 1), 0)
        for s in self.stop:
            idx = self.output_text.find(s, start)
            if idx != -1:
                end = idx + (len(s) if self.include_stop else 0)
                self.output_text = self.output_text[:end]
                return s
        self._stop_scanned = len(self.output_text)
        return None
