"""Replica pool: health tracking + load scraping for the router.

One ``Replica`` per backend api_server.  A background poll loop (every
``VDT_ROUTER_HEALTH_INTERVAL_SECONDS``, each probe deadline-bounded)
reads ``/health`` — which PR 2/3/8 made four-state: healthy, recovering,
draining/drained, dead — and scrapes the PR 7 admission gauges
(``vllm:num_requests_waiting``, ``vllm:admission_queued_tokens``) from
``/metrics`` so least-loaded placement ranks replicas by queue depth,
not round-robin luck.

The proxy path feeds back too: a transport error marks the replica
unreachable immediately (placement must not wait a poll tick to stop
picking a dead backend), and a 429 with ``Retry-After`` puts the replica
in backoff for that long (it is healthy but full — eject it from
placement briefly, don't mark it down).
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass, field

from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.router.resilience import ResilienceManager

logger = init_logger(__name__)

# /health interpretations that mean "will come back without operator
# action" — kept out of placement but not forgotten.
_TRANSIENT_STATES = {"recovering", "draining", "drained"}

_LOAD_GAUGES = (
    "vllm:num_requests_waiting",
    "vllm:admission_queued_tokens",
    "vllm:num_requests_running",
)


@dataclass
class Replica:
    url: str  # base URL, no trailing slash
    replica_id: str = ""  # learned from /health; url until then
    # healthy|recovering|draining|drained|dead|unreachable|unknown, plus
    # the router-local "verifying" (ISSUE 17): a re-adopted replica in
    # its post-recovery grace window — kept out of placement but immune
    # to transport-failure ejection until the window expires.
    state: str = "unknown"
    # Disaggregation role (ISSUE 15), learned from the /health body (or
    # pinned by the fleet manager at spawn): "prefill" replicas only
    # take the router's prefill-only hand-off hops; "decode"/"mixed"
    # serve normal traffic.  All-mixed pools behave exactly as before.
    role: str = "mixed"
    waiting: float = 0.0  # vllm:num_requests_waiting
    queued_tokens: float = 0.0  # vllm:admission_queued_tokens
    running: float = 0.0  # vllm:num_requests_running
    backoff_until: float = 0.0  # monotonic; 429 Retry-After ejection
    consecutive_failures: int = 0
    last_error: str = ""
    last_probe_mono: float = 0.0
    # Monotonic deadline of the "verifying" grace window; 0 = none.
    verify_deadline_mono: float = 0.0
    # Clock-offset estimate from /health round trips (ISSUE 20):
    # replica_wall - router_wall at the request midpoint, kept when its
    # RTT beats the stored best (same accept/decay rule as tracing.py's
    # heartbeat offsets).  clock_rtt < 0 = no sample yet.
    clock_offset: float = 0.0
    clock_rtt: float = -1.0

    def note_clock_sample(self, offset: float, rtt: float) -> None:
        if rtt < 0:
            return
        if self.clock_rtt < 0 or rtt <= self.clock_rtt * 1.25:
            self.clock_offset = offset
            self.clock_rtt = rtt
        else:
            # Slow decay so a temporarily-congested link can't pin a
            # stale offset forever.
            self.clock_rtt *= 1.05

    @property
    def verifying(self) -> bool:
        return (
            self.state == "verifying"
            and time.monotonic() < self.verify_deadline_mono
        )

    def __post_init__(self) -> None:
        self.url = self.url.rstrip("/")
        if not self.replica_id:
            self.replica_id = self.url

    @property
    def routable(self) -> bool:
        return (
            self.state == "healthy"
            and time.monotonic() >= self.backoff_until
        )

    @property
    def load_key(self) -> tuple[float, float, float]:
        """Least-loaded sort key: waiting depth first (the PR 7
        admission gauge that grows first under pressure), then queued
        prompt tokens, then running batch size."""
        return (self.waiting, self.queued_tokens, self.running)

    def snapshot(self) -> dict:
        return {
            "url": self.url,
            "replica_id": self.replica_id,
            "state": self.state,
            "role": self.role,
            "waiting": self.waiting,
            "queued_tokens": self.queued_tokens,
            "running": self.running,
            "backing_off": time.monotonic() < self.backoff_until,
            "last_error": self.last_error or None,
        }


def parse_load_gauges(metrics_text: str) -> dict[str, float]:
    """Sum the admission-gauge samples out of a Prometheus exposition
    (labels collapse: one engine per replica)."""
    out: dict[str, float] = {}
    for line in metrics_text.splitlines():
        if not line or line.startswith("#"):
            continue
        parts = line.split()
        if len(parts) != 2:
            continue
        family = parts[0].split("{")[0]
        if family in _LOAD_GAUGES:
            try:
                out[family] = out.get(family, 0.0) + float(parts[1])
            except ValueError:
                continue
    return out


class ReplicaPool:
    """Owns the replica set and the health-poll task.  All mutation
    happens on the router's event loop (the poll task and the request
    handlers share it), so no locking."""

    def __init__(
        self,
        urls: list[str],
        *,
        health_interval: float = 2.0,
        connect_timeout: float = 5.0,
        probe_timeout: float = 10.0,
        allow_empty: bool = False,
    ) -> None:
        self.replicas: list[Replica] = []
        for url in urls:
            self.add(url)
        if not self.replicas and not allow_empty:
            raise ValueError("router needs at least one replica URL")
        self.health_interval = health_interval
        self.connect_timeout = connect_timeout
        self.probe_timeout = probe_timeout
        self._task: asyncio.Task | None = None
        self._stopped = asyncio.Event()
        # Resilient data plane (ISSUE 19): RouterState installs its
        # manager here so probes share the breakers/budget/hedging with
        # the proxy path.  A standalone pool (unit tests) gets the
        # always-off passthrough.
        self.resilience: ResilienceManager | None = None
        # Fleet sentinel (ISSUE 20): RouterState installs its
        # RouterSentinel here; probes feed it state transitions, clock
        # offsets, and the scraped signal gauges.
        self.sentinel = None
        # Membership hooks (the fleet layer and the metrics exporter
        # subscribe): called with the Replica on every add/remove so
        # per-replica series can be created/forgotten in lockstep with
        # the pool — a scaled-down replica must not linger in the
        # merged exposition.
        self.on_remove: list = []

    # ---- membership (ISSUE 13: replica count is a runtime variable) ----
    def add(
        self,
        url: str,
        *,
        replica_id: str = "",
        state: str = "unknown",
        role: str = "mixed",
        verify_window: float = 0.0,
    ) -> Replica | None:
        """Add a replica URL (idempotent).  The fleet manager passes
        ``state="healthy"`` after its health-gated warmup so a fresh
        replica is routable immediately instead of waiting a poll tick,
        and pins the role it spawned the replica with.

        ``verify_window`` > 0 (recovery re-adoption, ISSUE 17) enters
        the replica in the ``verifying`` state instead: not routable
        until a probe confirms it, but transport-level probe failures
        inside the window keep it verifying (with faster re-probes)
        rather than declaring it unreachable — a router restart storm
        must not mass-eject a fleet that is briefly slow to answer.
        """
        url = url.rstrip("/")
        if not url:
            return None
        existing = self.by_url(url)
        if existing is not None:
            return existing
        if verify_window > 0:
            state = "verifying"
        replica = Replica(
            url=url, replica_id=replica_id, state=state, role=role
        )
        if verify_window > 0:
            replica.verify_deadline_mono = (
                time.monotonic() + verify_window
            )
        self.replicas.append(replica)
        return replica

    def remove(self, url: str) -> Replica | None:
        """Drop a replica from the pool.  After this returns, the
        merged /metrics exposition and the /router/slo merge (both
        iterate ``replicas``) no longer carry its rows; ``on_remove``
        hooks let the metrics layer drop its labeled series too."""
        replica = self.by_url(url)
        if replica is None:
            return None
        self.replicas.remove(replica)
        for hook in self.on_remove:
            try:
                hook(replica)
            except Exception:  # noqa: BLE001 — membership hooks are advisory
                logger.exception("pool on_remove hook failed")
        return replica

    # ---- lookup ----
    def by_url(self, url: str) -> Replica | None:
        url = url.rstrip("/")
        for r in self.replicas:
            if r.url == url:
                return r
        return None

    def by_id(self, replica_id: str) -> Replica | None:
        for r in self.replicas:
            if r.replica_id == replica_id:
                return r
        return None

    def candidates(self, exclude: set[str] | None = None) -> list[Replica]:
        """Routable replicas, excluding ``exclude`` (urls)."""
        exclude = exclude or set()
        return [
            r
            for r in self.replicas
            if r.routable and r.url not in exclude
        ]

    def snapshot(self) -> list[dict]:
        return [r.snapshot() for r in self.replicas]

    # ---- request-path feedback ----
    def note_unreachable(self, replica: Replica, error: str) -> None:
        old = replica.state
        replica.state = "unreachable"
        replica.consecutive_failures += 1
        replica.last_error = error
        self._note_transition(replica, old)
        logger.warning(
            "replica %s unreachable: %s", replica.replica_id, error
        )

    def _note_transition(self, replica: Replica, old: str) -> None:
        """Feed observed state changes into the sentinel timeline."""
        if self.sentinel is None or replica.state == old:
            return
        try:
            self.sentinel.note_replica_state(
                replica.replica_id, old, replica.state
            )
        except Exception:  # noqa: BLE001 — the timeline is observe-only
            logger.exception("sentinel state hook failed")

    def note_backoff(self, replica: Replica, retry_after: float) -> None:
        """429 from a healthy-but-full replica: eject from placement for
        Retry-After seconds, nothing more."""
        replica.backoff_until = time.monotonic() + max(retry_after, 0.5)

    # ---- health polling ----
    async def probe(self, session, replica: Replica) -> None:
        """One deadline-bounded /health + /metrics read."""
        import aiohttp

        rz = self.resilience or ResilienceManager.noop()
        timeout = aiohttp.ClientTimeout(
            total=self.probe_timeout, connect=self.connect_timeout
        )
        replica.last_probe_mono = time.monotonic()

        async def fetch_health() -> tuple[int, dict, float, float]:
            # Wall-clock stamps around the round trip: with the
            # replica's own "now" in the body this doubles as a clock
            # offset sample (ISSUE 20 timeline correction).
            t_send = time.time()
            async with await rz.request(
                session,
                "GET",
                f"{replica.url}/health",
                endpoint="health",
                replica_id=replica.replica_id,
                timeout=timeout,
            ) as resp:
                try:
                    body = await resp.json()
                except Exception:  # noqa: BLE001 — pre-ISSUE-10 replicas answer 200 with an empty body
                    body = {}
                return resp.status, body or {}, t_send, time.time()

        prev_state = replica.state
        try:
            # /health is the idempotent read par excellence: hedged
            # (ISSUE 19) so one straggling answer under a lossy DCN
            # doesn't read as a missed probe.  The half-open breaker
            # probe also rides this path.
            http_status, body, t_send, t_recv = await rz.hedged(
                "health", replica.replica_id, fetch_health
            )
            remote_now = body.get("now")
            if isinstance(remote_now, (int, float)):
                replica.note_clock_sample(
                    float(remote_now) - (t_send + t_recv) / 2.0,
                    t_recv - t_send,
                )
            if http_status == 200:
                replica.state = "healthy"
                replica.consecutive_failures = 0
                replica.last_error = ""
                replica.verify_deadline_mono = 0.0
                rid = body.get("replica_id")
                if rid:
                    replica.replica_id = str(rid)
                role = body.get("role")
                if role in ("prefill", "decode", "mixed"):
                    replica.role = role
            else:
                status = str(body.get("status", "dead"))
                replica.state = (
                    status
                    if status in _TRANSIENT_STATES or status == "dead"
                    else "dead"
                )
                replica.last_error = str(
                    body.get("error", f"HTTP {http_status}")
                )
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — any transport failure = unreachable
            if replica.verifying:
                # Grace window (ISSUE 17): a just-re-adopted replica
                # may be slow to answer while the whole fleet and the
                # restarted router come up together.  Remember the
                # error, stay in "verifying", and let the (faster)
                # re-probes decide; only a window expiry or an explicit
                # /health verdict can eject it.
                replica.consecutive_failures += 1
                replica.last_error = f"{type(e).__name__}: {e}"
                return
            self.note_unreachable(replica, f"{type(e).__name__}: {e}")
            return
        self._note_transition(replica, prev_state)
        if replica.state != "healthy":
            return

        async def fetch_metrics() -> str | None:
            async with await rz.request(
                session,
                "GET",
                f"{replica.url}/metrics",
                endpoint="metrics",
                replica_id=replica.replica_id,
                timeout=timeout,
            ) as resp:
                if resp.status != 200:
                    return None
                return await resp.text()

        try:
            text = await rz.hedged(
                "metrics", replica.replica_id, fetch_metrics
            )
            if text is not None:
                gauges = parse_load_gauges(text)
                replica.waiting = gauges.get(
                    "vllm:num_requests_waiting", replica.waiting
                )
                replica.queued_tokens = gauges.get(
                    "vllm:admission_queued_tokens",
                    replica.queued_tokens,
                )
                replica.running = gauges.get(
                    "vllm:num_requests_running", replica.running
                )
                if self.sentinel is not None:
                    self.sentinel.note_probe(replica.replica_id, text)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — load stats are advisory; /health already passed
            logger.debug(
                "metrics scrape of %s failed: %s", replica.replica_id, e
            )

    def _probe_jitter(self) -> float:
        """Max per-replica probe delay: spread N probes over a fraction
        of the poll interval so replicas aren't scraped in lockstep
        bursts (N simultaneous /metrics renders every tick)."""
        return min(self.health_interval * 0.25, 1.0)

    async def probe_all(self, session, *, jitter: bool = True) -> None:
        # Each probe is internally deadline-bounded; the outer bound
        # just guarantees one wedged probe can't stall the poll loop.
        span = self._probe_jitter() if jitter else 0.0

        async def jittered(replica: Replica) -> None:
            if span > 0:
                await asyncio.sleep(random.uniform(0, span))
            await self.probe(session, replica)

        await asyncio.wait_for(
            asyncio.gather(
                *(jittered(r) for r in list(self.replicas))
            ),
            timeout=(
                2 * (self.probe_timeout + self.connect_timeout) + 5 + span
            ),
        )

    def start(self, session) -> None:
        if self._task is not None:
            return
        self._stopped.clear()
        self._task = asyncio.get_running_loop().create_task(
            self._poll_loop(session)
        )

    def _next_interval(self) -> float:
        """Poll cadence: normally ``health_interval``, but while any
        replica is inside its ``verifying`` grace window, re-probe on a
        faster (still bounded — never below 0.2s) cadence so adoption
        confirms in a fraction of the window instead of one poll tick
        per attempt.  Per-probe jitter in ``probe_all`` spreads the
        storm."""
        if any(r.verifying for r in self.replicas):
            return max(self.health_interval / 4.0, 0.2)
        return self.health_interval

    async def _poll_loop(self, session) -> None:
        while not self._stopped.is_set():
            try:
                await self.probe_all(session)
            except asyncio.CancelledError:
                return
            except Exception:  # noqa: BLE001 — the poll loop must outlive one bad tick
                logger.exception("replica health poll failed")
            try:
                await asyncio.wait_for(
                    self._stopped.wait(), timeout=self._next_interval()
                )
            except asyncio.TimeoutError:
                continue

    async def stop(self) -> None:
        self._stopped.set()
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await asyncio.wait_for(task, timeout=5.0)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                pass
