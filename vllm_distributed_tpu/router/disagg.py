"""Disaggregated prefill/decode hand-off orchestration (ISSUE 15).

DistServe/Splitwise role separation at the router: long prompts are
prefetched on a **prefill-pool** replica (``X-VDT-Disagg: prefill`` hop
→ the replica runs prefill plus the first sampled token and HOLDS its
KV pages for export), then the router streams the pages in per-layer
chunks from the prefill replica's ``/internal/kv/export`` to a
decode-pool replica's ``/internal/kv`` and resumes the request there
over the PR 8 ``/internal/resume`` path — the imported pages attach as
computed, so decode continues bit-identically while the decode pool's
ITL never shares a mesh with the compute-bound prefill.

Failure semantics (the chaos_soak ``--disagg`` contract): any failure
on the prefill side — replica SIGKILLed mid-export, checksum mismatch,
transfer aborted — falls back to the PR 8 recompute-resume on the
decode pool with whatever the journal already holds.  Planned hand-offs
AND their fallbacks are the happy path of role separation: they count
in ``vdt_router:handoffs``, never in ``vdt_router:migrations``, and
never burn ``VDT_ROUTER_MAX_MIGRATIONS`` budget.  Only a failure of the
decode-side continuation itself enters the normal migration loop.

Below the ``VDT_DISAGG_MIN_PROMPT_TOKENS`` crossover (benched by
``tools/disagg_crossover.py``) the hand-off is not planned at all and
the request serves on the decode/mixed pool exactly as today; a fleet
with no prefill-role replica never takes this path.
"""

from __future__ import annotations

import asyncio
import json
from dataclasses import dataclass

from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.router.resilience import ResilienceManager
from vllm_distributed_tpu.tracing import get_tracer

logger = init_logger(__name__)

# Test seams for the chaos harness (tools/chaos_soak.py --disagg):
# `_test_before_transfer` is awaited after the prefill stream yields
# its first token but before any export chunk moves;
# `_test_after_chunk` after each export→import chunk round trip (chunk
# index passed); `_test_after_chunk_failure` after a chunk round trip
# FAILS and is about to be resumed (failure count passed) — the
# partition soak heals the link exactly there, so "one lost chunk,
# then resume" is deterministic regardless of event-loop contention.
# Together they make "SIGKILL the prefill replica mid-hand-off /
# mid-export" deterministic scenarios instead of races.
_test_before_transfer = None
_test_after_chunk = None
_test_after_chunk_failure = None


def _emit_handoff_event(state, outcome: str, **attrs) -> None:
    """Mirror a hand-off outcome into the sentinel timeline (ISSUE 20);
    best-effort — the data path never blocks on observability."""
    sentinel = getattr(state, "sentinel", None)
    if sentinel is None:
        return
    try:
        sentinel.emit("router_handoff", outcome=outcome, **attrs)
    except Exception:  # noqa: BLE001 — observability must not break the stream
        logger.exception("sentinel router_handoff event failed")


@dataclass
class HandoffPlan:
    est_prompt_tokens: int


def estimate_prompt_tokens(journal) -> int:
    """Crossover estimate from what the router can see pre-placement:
    exact for token-id prompts, ~4 chars/token for text/chat."""
    text, ids = journal.affinity_source()
    if ids:
        return len(ids)
    return len(text or "") // 4


def plan_handoff(state, journal, keys) -> HandoffPlan | None:
    """Decide whether this stream takes the prefill→decode hand-off
    path: single-choice streaming request whose (estimated) prompt is
    at/above the crossover, with BOTH pools routable.  Everything else
    places exactly as before."""
    if not journal.stream or len(journal.choices) != 1:
        return None
    mt = journal.body.get("max_tokens")
    try:
        if mt is not None and int(mt) <= 1:
            return None  # finishes at the first token either way
    except (TypeError, ValueError):
        return None
    have_prefill = any(
        r.routable and r.role == "prefill" for r in state.pool.replicas
    )
    have_decode = any(
        r.routable and r.role != "prefill" for r in state.pool.replicas
    )
    if not (have_prefill and have_decode):
        return None
    est = estimate_prompt_tokens(journal)
    if est < state.disagg_min_prompt_tokens:
        return None
    return HandoffPlan(est_prompt_tokens=est)


async def _post_json(
    state,
    url: str,
    payload: dict,
    *,
    endpoint: str = "kv",
    replica_id: str | None = None,
    hedge: bool = False,
) -> tuple[int, dict]:
    """One bounded router→replica control POST; returns (status, body).
    Routed through the resilience manager (ISSUE 19): breaker-gated,
    adaptive-deadline'd, and — for idempotent export pulls — hedged.
    With no resilience envs set the manager is a pure passthrough."""
    import aiohttp

    rz = getattr(state, "resilience", None) or ResilienceManager.noop()
    timeout = aiohttp.ClientTimeout(
        total=state.read_timeout, connect=state.connect_timeout
    )

    async def fetch() -> tuple[int, dict]:
        async with await rz.request(
            state.session,
            "POST",
            url,
            endpoint=endpoint,
            replica_id=replica_id,
            json=payload,
            timeout=timeout,
        ) as resp:
            try:
                body = await resp.json()
            except Exception:  # noqa: BLE001 — a non-JSON error body still carries the status
                body = {}
            return resp.status, body or {}

    if hedge:
        return await rz.hedged(endpoint, replica_id, fetch)
    return await fetch()


async def _transfer_pages(
    state, prefill_url: str, decode_url: str, kv_handle: str,
    prompt_token_ids: list[int],
    *,
    prefill_id: str | None = None,
    decode_id: str | None = None,
) -> int:
    """Stream the held pages prefill→decode in per-layer chunks.
    Returns the adopted token count (0 = nothing transferred, e.g. the
    decode pool declined).  Raises on any wire/checksum/commit failure
    — the caller aborts and falls back to recompute.

    With ``VDT_ROUTER_KV_CHUNK_RETRIES > 0`` the transfer is resumable
    (ISSUE 19): a lost chunk round-trip re-begins with ``resume_from``,
    learns which checksummed layers actually landed decode-side, and
    re-pulls only the missing ones.  Each resume draws one token from
    the retry budget; only an exhausted budget or chunk-retry cap falls
    back to recompute."""
    rz = getattr(state, "resilience", None) or ResilienceManager.noop()
    kv_url = f"{decode_url}/internal/kv"
    status, begin = await _post_json(
        state,
        kv_url,
        {"op": "begin", "prompt_token_ids": prompt_token_ids},
        endpoint="kv_import",
        replica_id=decode_id,
    )
    if status != 200:
        raise RuntimeError(f"kv import begin failed: HTTP {status}")
    transfer_id = begin.get("transfer_id")
    if not transfer_id:
        return 0  # nothing importable decode-side; recompute is correct
    chunk_layers = max(int(state.disagg_chunk_layers), 1)
    chunk_retries = max(int(rz.cfg.kv_chunk_retries), 0)
    failures = 0
    need_sync = False
    try:
        layer = 0
        num_layers = None
        chunk_idx = 0
        while num_layers is None or layer < num_layers:
            try:
                if need_sync:
                    status, rebegin = await _post_json(
                        state,
                        kv_url,
                        {
                            "op": "begin",
                            "prompt_token_ids": prompt_token_ids,
                            "resume_from": transfer_id,
                        },
                        endpoint="kv_import",
                        replica_id=decode_id,
                    )
                    if (
                        status != 200
                        or rebegin.get("transfer_id") != transfer_id
                    ):
                        # Reservation gone (TTL, scatter-failure
                        # abort): nothing to resume onto — recompute.
                        raise RuntimeError(
                            "kv transfer resume rejected: "
                            f"HTTP {status}"
                        )
                    received = {
                        int(i) for i in rebegin.get("received") or ()
                    }
                    nl = rebegin.get("num_layers")
                    if nl:
                        num_layers = int(nl)
                    # Re-pull from the first missing layer.  Layers
                    # land in order, so the missing set is a suffix in
                    # practice; the import-side set-add is idempotent
                    # if it is not.
                    layer = 0
                    while layer in received:
                        layer += 1
                    need_sync = False
                    metrics = getattr(state, "metrics", None)
                    if metrics is not None:
                        metrics.record_kv_resume()
                    continue  # loop guard re-checks completion
                status, chunk = await _post_json(
                    state,
                    f"{prefill_url}/internal/kv/export",
                    {
                        "handle": kv_handle,
                        "layer_start": layer,
                        "layer_count": chunk_layers,
                    },
                    endpoint="kv_export",
                    replica_id=prefill_id,
                    hedge=True,  # pure read: chunks are idempotent pulls
                )
                if status != 200:
                    raise RuntimeError(
                        f"kv export chunk failed: HTTP {status}"
                    )
                num_layers = int(chunk.get("num_layers") or 0)
                layers = chunk.get("layers") or []
                if not layers:
                    raise RuntimeError(
                        f"kv export returned no layers at "
                        f"{layer}/{num_layers}"
                    )
                status, _ = await _post_json(
                    state,
                    kv_url,
                    {
                        "op": "chunk",
                        "transfer_id": transfer_id,
                        "layers": layers,
                    },
                    endpoint="kv_import",
                    replica_id=decode_id,
                )
                if status != 200:
                    raise RuntimeError(
                        f"kv import chunk failed: HTTP {status}"
                    )
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — any lost round-trip (even a breaker rejection: the cooldown is shorter than a recompute) is resumable
                resume_rejected = isinstance(
                    e, RuntimeError
                ) and "resume rejected" in str(e)
                failures += 1
                if (
                    resume_rejected
                    or failures > chunk_retries
                    or not rz.try_spend_retry(decode_id)
                ):
                    raise
                logger.warning(
                    "kv transfer chunk failed (%s); resuming transfer "
                    "%s (attempt %d/%d)",
                    e,
                    transfer_id,
                    failures,
                    chunk_retries,
                )
                if _test_after_chunk_failure is not None:
                    await _test_after_chunk_failure(failures)
                # Linear backoff: a partitioned link fails in
                # microseconds — give the heal (or the breaker
                # cooldown) a beat before re-syncing.
                await asyncio.sleep(min(0.25 * failures, 2.0))
                need_sync = True
                continue
            layer += len(layers)
            chunk_idx += 1
            if _test_after_chunk is not None:
                await _test_after_chunk(chunk_idx)
        status, commit = await _post_json(
            state,
            kv_url,
            {"op": "commit", "transfer_id": transfer_id},
            endpoint="kv_import",
            replica_id=decode_id,
        )
        if status != 200:
            raise RuntimeError(f"kv import commit failed: HTTP {status}")
        return int(commit.get("adopted_tokens") or 0)
    except BaseException:
        # Free the decode-side reservation; the TTL sweep is only the
        # backstop.  Best-effort: the abort itself may be unreachable.
        try:
            await _post_json(
                state,
                kv_url,
                {"op": "abort", "transfer_id": transfer_id},
                endpoint="kv_import",
                replica_id=decode_id,
            )
        except Exception:  # noqa: BLE001 — fallback proceeds regardless
            logger.debug("kv import abort failed", exc_info=True)
        raise


async def _release_hold(state, prefill_url: str, kv_handle: str) -> None:
    """Best-effort release of the prefill replica's export hold (the
    TTL sweep covers a replica we can no longer reach)."""
    try:
        await _post_json(
            state,
            f"{prefill_url}/internal/kv/release",
            {"handle": kv_handle},
        )
    except Exception:  # noqa: BLE001 — TTL backstop frees the hold
        logger.debug("kv hold release failed", exc_info=True)


async def forward_prefill_handoff(
    state, journal, keys, exclude, prefill, resp, fwd, write,
    client_debug, span,
) -> bool:
    """Pump the prefill-only stream to the client (journaling the first
    token), then hand the KV pages off and continue on a decode-pool
    replica.  Returns True when the client stream completed.  All
    prefill-side failures degrade to recompute-resume on the decode
    pool without touching the migration budget; only decode-side
    continuation failures enter the normal migration loop."""
    # Local import: app.py imports this module lazily per stream, so a
    # top-level back-import would be circular at module load.
    from vllm_distributed_tpu.router.app import (
        MigrationNeeded,
        _forward_resumed,
        _migrate_loop,
        _place_or_none,
        _sse_payloads,
    )

    tracer = get_tracer()
    kv_handle: str | None = None
    handoff_now = False
    prefill_ok = True
    try:
        async for payload in _sse_payloads(resp, state.read_timeout):
            if payload == "[DONE]":
                break
            try:
                obj = json.loads(payload)
            except ValueError:
                continue
            if "error" in obj and not obj.get("choices"):
                # Any typed error on the prefill hop — drain, shed,
                # death — is recoverable: fall back to recompute.
                prefill_ok = False
                break
            if journal.upstream_id is None and obj.get("id"):
                journal.upstream_id = obj["id"]
                journal.model = obj.get("model")
            genuine_finish = False
            for choice in obj.get("choices") or []:
                # Internal-only: the export handle must never reach
                # the client (even debug ones) — it names live pages.
                handle = choice.pop("vdt_kv_handle", None)
                if handle:
                    kv_handle = str(handle)
                finish = choice.get("finish_reason")
                if finish == "length":
                    # The synthetic prefill-only budget (max_tokens=1
                    # forced on the disagg hop): the request is NOT
                    # done — strip the finish and hand off.  A client
                    # asking for max_tokens<=1 is never planned here.
                    choice["finish_reason"] = None
                    handoff_now = True
                elif finish is not None:
                    genuine_finish = True  # EOS/stop at token one
                kept = dict(choice) if client_debug else None
                journal.observe_choice(choice)
                if kept is not None:
                    choice.update(
                        {
                            k: v
                            for k, v in kept.items()
                            if k.startswith("vdt_")
                        }
                    )
            await write(json.dumps(obj))
            if genuine_finish:
                await write("[DONE]")
                if kv_handle:
                    await _release_hold(state, prefill.url, kv_handle)
                state.metrics.record_handoff("finished_at_prefill")
                _emit_handoff_event(
                    state,
                    "finished_at_prefill",
                    from_replica=prefill.replica_id,
                )
                return True
            if handoff_now:
                break
        else:
            prefill_ok = False  # stream closed without a finish
    except (ConnectionResetError, asyncio.CancelledError):
        # CLIENT-side disconnect mid-forward: the prefill replica is
        # healthy — free its hold now instead of at the TTL, and never
        # misattribute the hangup to the replica (parity with
        # _forward_primary, which re-raises for the same reason).
        if kv_handle:
            await _release_hold(state, prefill.url, kv_handle)
        raise
    except Exception as e:  # noqa: BLE001 — prefill-side failure = recompute fallback
        prefill_ok = False
        state.pool.note_unreachable(prefill, f"{type(e).__name__}: {e}")
        state.index.forget(prefill.replica_id)
        exclude.add(prefill.url)

    # ---- pick the decode-side continuation target ----
    target = _place_or_none(
        state, keys, exclude, span, slo_class=journal.slo_class
    )
    if target is None:
        await write(
            json.dumps(
                {"error": "no decode replica for hand-off", "code": 503}
            )
        )
        state.metrics.record_handoff("fallback")
        _emit_handoff_event(
            state, "fallback", from_replica=prefill.replica_id
        )
        return False

    # ---- stream the KV pages across (best-effort) ----
    adopted = 0
    choice = journal.choices.get(0)
    prompt_ids = choice.prompt_token_ids if choice is not None else None
    if prefill_ok and handoff_now and kv_handle and prompt_ids:
        try:
            if _test_before_transfer is not None:
                await _test_before_transfer()
            adopted = await _transfer_pages(
                state,
                prefill.url,
                target.url,
                kv_handle,
                list(prompt_ids),
                prefill_id=prefill.replica_id,
                decode_id=target.replica_id,
            )
        except Exception as e:  # noqa: BLE001 — transfer failure = recompute fallback
            logger.warning(
                "kv hand-off transfer %s -> %s failed (%s); falling "
                "back to recompute-resume",
                prefill.replica_id,
                target.replica_id,
                e,
            )
            adopted = 0
    if kv_handle:
        # Release on EVERY fallback path too (gate failed, prefill
        # stream broke after the handle arrived): a reachable prefill
        # replica frees its pages now, not at the TTL; an unreachable
        # one fails the best-effort call and the TTL backstops.
        await _release_hold(state, prefill.url, kv_handle)
    outcome = "planned" if adopted > 0 else "fallback"
    state.metrics.record_handoff(outcome)
    _emit_handoff_event(
        state,
        outcome,
        from_replica=prefill.replica_id,
        to_replica=target.replica_id,
        adopted_tokens=adopted,
    )
    tracer.event(
        span.ctx,
        "router.handoff",
        outcome=outcome,
        from_replica=prefill.replica_id,
        to_replica=target.replica_id,
        adopted_tokens=adopted,
    )

    # ---- continue decoding on the target ----
    try:
        await _forward_resumed(
            state, journal, target, fwd, write, client_debug
        )
        journal.served_by = target.replica_id
        return True
    except MigrationNeeded as m:
        # The DECODE side failed: this is genuine failure recovery and
        # takes the normal migration loop (budget applies).
        return await _migrate_loop(
            state, journal, keys, exclude, target, m,
            fwd, write, client_debug, span,
        )
