"""Router-side request journal (ISSUE 10 tentpole).

Mirrors ``engine/supervisor.py``'s ``JournalEntry`` semantics one hop
up: for every proxied request the router remembers the original OpenAI
body, each choice's prompt, and the cumulative tokens/text already
forwarded to the client — exactly what a live migration needs to
re-submit the request to another replica via ``/internal/resume`` with
the emitted tokens restored, so the client's SSE stream continues and
greedy outputs stay bit-identical across the switch.

One ``RouterJournal`` per in-flight proxied request (bounded 1:1 by live
router handlers), one ``ChoiceState`` per choice index — a completions
request with P prompts and n samples has P*n flat choice indices, in the
same order the replica assigns them (prompt-major, sample-minor).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class ChoiceState:
    """Client-visible cumulative state of one choice index."""

    index: int
    prompt: str | None = None
    prompt_token_ids: list[int] | None = None
    emitted_token_ids: list[int] = field(default_factory=list)
    # Characters of completion text already forwarded to the client —
    # a resumed stream re-sends cumulative text, and the router slices
    # off this prefix to keep the client stream incremental.
    forwarded_text_len: int = 0
    finish_reason: str | None = None
    # Chat streams: whether the role-bearing first delta went out (a
    # migrated continuation must not repeat it — or skip it).
    role_sent: bool = False

    @property
    def finished(self) -> bool:
        return self.finish_reason is not None

    def observe(
        self,
        new_token_ids: list[int] | None,
        text_delta: str,
        finish_reason: str | None,
        prompt_token_ids: list[int] | None = None,
    ) -> None:
        if prompt_token_ids is not None and self.prompt_token_ids is None:
            self.prompt_token_ids = list(prompt_token_ids)
        if new_token_ids:
            self.emitted_token_ids.extend(new_token_ids)
        self.forwarded_text_len += len(text_delta)
        if finish_reason is not None:
            self.finish_reason = finish_reason

    # ---- durable state (ISSUE 17) ----
    def to_dict(self) -> dict:
        return {
            "index": self.index,
            "prompt": self.prompt,
            "prompt_token_ids": (
                list(self.prompt_token_ids)
                if self.prompt_token_ids is not None
                else None
            ),
            "emitted_token_ids": list(self.emitted_token_ids),
            "forwarded_text_len": self.forwarded_text_len,
            "finish_reason": self.finish_reason,
            "role_sent": self.role_sent,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ChoiceState":
        return cls(
            index=int(d["index"]),
            prompt=d.get("prompt"),
            prompt_token_ids=(
                [int(t) for t in d["prompt_token_ids"]]
                if d.get("prompt_token_ids") is not None
                else None
            ),
            emitted_token_ids=[
                int(t) for t in d.get("emitted_token_ids") or ()
            ],
            forwarded_text_len=int(d.get("forwarded_text_len") or 0),
            finish_reason=d.get("finish_reason"),
            role_sent=bool(d.get("role_sent")),
        )


def _normalize_prompts(body: dict) -> list[tuple[str | None, list[int] | None]]:
    """The completions prompt forms (str | [str] | [int] | [[int]]),
    normalized the same way the replica's handler does."""
    p = body.get("prompt", "")
    if isinstance(p, str):
        return [(p, None)]
    if isinstance(p, list) and p and isinstance(p[0], int):
        return [(None, [int(t) for t in p])]
    if isinstance(p, list) and p and isinstance(p[0], str):
        return [(s, None) for s in p]
    if isinstance(p, list) and p and isinstance(p[0], list):
        return [(None, [int(t) for t in ids]) for ids in p]
    return [("", None)]


def _chat_text(body: dict) -> str:
    """Affinity key text for a chat request: the concatenated message
    contents.  Chat-template boilerplate is shared by every request on
    the same model, so leaving it out keeps the signal in the turns."""
    parts: list[str] = []
    for m in body.get("messages") or ():
        if not isinstance(m, dict):
            continue
        content = m.get("content")
        if isinstance(content, str):
            parts.append(f"{m.get('role', '')}:{content}")
        elif isinstance(content, list):
            for item in content:
                if isinstance(item, dict) and isinstance(
                    item.get("text"), str
                ):
                    parts.append(item["text"])
    return "\n".join(parts)


class RouterJournal:
    """All migration state for one proxied request."""

    def __init__(self, request_id: str, kind: str, body: dict) -> None:
        assert kind in ("completions", "chat"), kind
        self.request_id = request_id
        self.kind = kind
        self.body = body
        self.stream = bool(body.get("stream"))
        n = max(int(body.get("n") or 1), 1)
        self.choices: dict[int, ChoiceState] = {}
        if kind == "chat":
            for i in range(n):
                self.choices[i] = ChoiceState(index=i)
        else:
            prompts = _normalize_prompts(body)
            idx = 0
            for text, ids in prompts:
                for _ in range(n):
                    self.choices[idx] = ChoiceState(
                        index=idx, prompt=text, prompt_token_ids=ids
                    )
                    idx += 1
        # Identity the client saw in the first chunk; migrated
        # continuations keep presenting it.
        self.upstream_id: str | None = None
        self.model: str | None = None
        self.migrations = 0
        self.served_by: str | None = None  # replica_id of current server
        # Effective SLO class (body field or X-VDT-SLO-Class header,
        # body wins — mirroring the replica's _apply_slo_class).  Rides
        # every resume/hand-off so a migrated request keeps its QoS
        # standing and its SLO accounting bucket (ISSUE 16).
        self.slo_class: str | None = None

    # ---- affinity ----
    def affinity_source(self) -> tuple[str | None, list[int] | None]:
        """(text, token_ids) to key the affinity index with — the first
        prompt (multi-prompt batches rarely share placement anyway)."""
        if self.kind == "chat":
            return _chat_text(self.body), None
        first = self.choices.get(0)
        if first is None:
            return "", None
        if first.prompt_token_ids is not None:
            return None, first.prompt_token_ids
        return first.prompt, None

    # ---- chunk accounting ----
    def observe_choice(self, choice: dict) -> dict:
        """Record one upstream SSE chunk's choice dict and return it
        with the internal ``vdt_*`` metadata stripped (what the client
        is allowed to see)."""
        idx = int(choice.get("index") or 0)
        state = self.choices.setdefault(idx, ChoiceState(index=idx))
        new_ids = choice.pop("vdt_token_ids", None)
        prompt_ids = choice.pop("vdt_prompt_token_ids", None)
        if self.kind == "chat":
            delta = choice.get("delta") or {}
            text_delta = delta.get("content") or ""
            if delta.get("role"):
                state.role_sent = True
        else:
            text_delta = choice.get("text") or ""
        state.observe(
            new_ids, text_delta, choice.get("finish_reason"), prompt_ids
        )
        return choice

    def unfinished(self) -> list[ChoiceState]:
        return [c for c in self.choices.values() if not c.finished]

    # ---- durable state (ISSUE 17) ----
    def to_dict(self) -> dict:
        """Checkpoint form for the router WAL: everything a restarted
        router needs to finish this request bit-identically via
        ``resume_payload`` — the original body, per-choice cumulative
        progress, and the client-visible identity."""
        return {
            "request_id": self.request_id,
            "kind": self.kind,
            "body": self.body,
            "stream": self.stream,
            "upstream_id": self.upstream_id,
            "model": self.model,
            "migrations": self.migrations,
            "served_by": self.served_by,
            "slo_class": self.slo_class,
            "choices": [
                self.choices[i].to_dict() for i in sorted(self.choices)
            ],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "RouterJournal":
        j = cls(str(d["request_id"]), str(d["kind"]), dict(d["body"]))
        j.stream = bool(d.get("stream"))
        j.upstream_id = d.get("upstream_id")
        j.model = d.get("model")
        j.migrations = int(d.get("migrations") or 0)
        j.served_by = d.get("served_by")
        j.slo_class = d.get("slo_class")
        # Checkpointed choices replace the freshly-derived skeleton —
        # the checkpoint knows learned prompt ids and emitted progress
        # the body alone can't reconstruct.
        for cd in d.get("choices") or ():
            c = ChoiceState.from_dict(cd)
            j.choices[c.index] = c
        return j

    # ---- migration ----
    def resume_payload(self, choice: ChoiceState) -> dict:
        """The /internal/resume body for one unfinished choice: the
        original OpenAI body (sampling parity), the choice's prompt
        (ids when known — text re-tokenizes identically on a same-model
        replica), and the tokens the client already holds."""
        return {
            "request_id": (
                f"{self.request_id}-m{self.migrations}-{choice.index}"
            ),
            "kind": self.kind,
            "body": self.body,
            "prompt": choice.prompt,
            "prompt_token_ids": choice.prompt_token_ids,
            "emitted_token_ids": list(choice.emitted_token_ids),
            "slo_class": self.slo_class,
        }
