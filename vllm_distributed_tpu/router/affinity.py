"""Router-side prefix-cache affinity index (ISSUE 10 tentpole).

Placement should follow the KV cache (SGLang's cache-aware scheduling,
Zheng et al. 2024, PAPERS.md): a request whose prompt shares a prefix
with work a replica recently served hits that replica's prefix cache
(PR 1) and skips most of its prefill.  The router cannot see replica
allocators directly, so it mirrors the allocator's own indexing scheme —
a hash chain over fixed-size prompt blocks (``PrefixCachingAllocator``
hashes page-aligned token blocks the same way) — over the prompts it has
routed, per replica, fed from response metadata when the replica
confirms service.

Prompts arrive in two forms and each gets its own key namespace (they
must never collide):

- token ids (``t:``): hashed in ``block_tokens``-sized blocks, exactly
  page-granular when ``block_tokens`` matches the engine page size;
- text (``s:``): hashed in ``4 * block_tokens``-byte chunks (~4 UTF-8
  bytes per token), used when the router has no tokenizer — both the
  observe and score sides use the same chunking, so matching stays
  consistent even though the block boundary is approximate.

Since ISSUE 14 the index mirrors the allocator's RADIX structure too,
not just its keying: per replica, block keys form a radix tree (each
node = one block, children keyed by the next block's chain digest) with
**leaf-first LRU eviction**, exactly like
``block_manager.RadixPrefixCachingAllocator``.  The flat LRU set this
replaces could evict an interior block while its suffix blocks survived
— stranded entries that consumed capacity yet could never match again
(scoring walks from the root and stops at the first gap).  Leaf-first
eviction keeps every remembered block reachable, so the same capacity
holds strictly more *matchable* prefix state and steering precision
rises with the allocator's own hit rate.

Bounded: each replica remembers at most ``capacity`` blocks, evicted
leaf-first LRU beyond that (a router restart simply starts cold).
Single-threaded: every call happens on the router's event loop.
"""

from __future__ import annotations

import hashlib
import heapq

_TEXT_BYTES_PER_TOKEN = 4


def chain_keys(
    prompt_text: str | None,
    prompt_token_ids: list[int] | None,
    block_tokens: int,
) -> list[str]:
    """Hash-chain keys for a prompt, most-significant (longest-prefix)
    last: key i covers blocks 0..i, so a replica holding keys 0..k has
    (approximately) the first (k+1) blocks warm."""
    keys: list[str] = []
    prev = b""
    if prompt_token_ids is not None:
        ns = b"t:"
        units = [
            prompt_token_ids[i : i + block_tokens]
            for i in range(0, len(prompt_token_ids), block_tokens)
        ]
        blocks = [
            ",".join(str(t) for t in u).encode() for u in units
        ]
    else:
        ns = b"s:"
        data = (prompt_text or "").encode("utf-8", "surrogateescape")
        step = block_tokens * _TEXT_BYTES_PER_TOKEN
        blocks = [data[i : i + step] for i in range(0, len(data), step)]
    for block in blocks:
        digest = hashlib.sha256(ns + prev + block).digest()
        keys.append(digest.hex())
        prev = digest
    return keys


class _AffinityNode:
    """One remembered block in a replica's radix tree (edge label =
    the block's chain digest, so the tree IS the chain structure)."""

    __slots__ = ("key", "parent", "children", "last_use", "stamp")

    def __init__(self, key: str | None, parent) -> None:
        self.key = key
        self.parent = parent
        self.children: dict[str, _AffinityNode] = {}
        self.last_use = 0
        self.stamp = 0


class _ReplicaTree:
    """Radix tree over one replica's remembered block chains, evicted
    leaf-first LRU (lazy heap, entries validated at pop)."""

    def __init__(self) -> None:
        self.root = _AffinityNode(None, None)
        self.count = 0
        self._heap: list[tuple[int, int, _AffinityNode]] = []
        self._stamp = 0

    def _push_if_leaf(self, node: _AffinityNode) -> None:
        self._stamp += 1
        node.stamp = self._stamp
        if not node.children and node.parent is not None:
            heapq.heappush(self._heap, (node.last_use, node.stamp, node))
            if len(self._heap) > 4 * self.count + 64:
                # Compact stale entries (touch-heavy, eviction-light
                # traffic would otherwise grow the lazy heap by one
                # entry per scored chain, unbounded).
                live = [
                    e
                    for e in self._heap
                    if e[2].stamp == e[1]
                    and not e[2].children
                    and e[2].parent is not None
                ]
                self._heap = live
                heapq.heapify(self._heap)

    def insert(self, keys: list[str], tick: int) -> None:
        node = self.root
        for key in keys:
            child = node.children.get(key)
            if child is None:
                child = _AffinityNode(key, node)
                node.children[key] = child
                self.count += 1
                # The parent stopped being a leaf; its stale heap
                # entries die at validation.
            child.last_use = tick
            node = child
        self._push_if_leaf(node)

    def match(self, keys: list[str], tick: int) -> int:
        """Consecutive leading blocks held, refreshing the whole
        matched path (cache-aware LRU, mirroring the allocator)."""
        node = self.root
        matched = 0
        for key in keys:
            child = node.children.get(key)
            if child is None:
                break
            child.last_use = tick
            matched += 1
            node = child
        if node is not self.root:
            self._push_if_leaf(node)
        return matched

    def evict_leaf(self) -> bool:
        """Remove the least-recently-used LEAF block (never an interior
        block — suffixes can't be stranded)."""
        while self._heap:
            _, stamp, node = heapq.heappop(self._heap)
            if (
                node.stamp != stamp
                or node.children
                or node.parent is None
            ):
                continue
            del node.parent.children[node.key]
            parent = node.parent
            node.parent = None
            self.count -= 1
            self._push_if_leaf(parent)  # may have just become a leaf
            return True
        return False


class PrefixAffinityIndex:
    """Per-replica radix trees of prefix-chain blocks + longest-prefix
    scoring over them (the router-side mirror of the allocator's radix
    walk, ISSUE 14)."""

    def __init__(self, block_tokens: int = 16, capacity: int = 8192):
        self.block_tokens = max(1, block_tokens)
        self.capacity = max(1, capacity)
        self._trees: dict[str, _ReplicaTree] = {}
        self._tick = 0

    def keys_for(
        self,
        prompt_text: str | None = None,
        prompt_token_ids: list[int] | None = None,
    ) -> list[str]:
        return chain_keys(prompt_text, prompt_token_ids, self.block_tokens)

    def observe(self, replica_id: str, keys: list[str]) -> None:
        """Record that ``replica_id`` served a prompt with this chain
        (call when the replica confirms service — first token or
        completed response — so the index tracks caches that exist,
        not placements that failed)."""
        if not keys:
            return
        tree = self._trees.setdefault(replica_id, _ReplicaTree())
        self._tick += 1
        tree.insert(keys, self._tick)
        while tree.count > self.capacity:
            if not tree.evict_leaf():
                break

    def score(self, keys: list[str]) -> dict[str, int]:
        """Approximate warm-prefix length per replica, in tokens: the
        number of consecutive leading chain blocks the replica holds,
        times the block size.  Touches the matched path (LRU refresh
        down the whole chain, like the allocator's radix walk)."""
        scores: dict[str, int] = {}
        for replica_id, tree in self._trees.items():
            self._tick += 1
            matched = tree.match(keys, self._tick)
            if matched:
                scores[replica_id] = matched * self.block_tokens
        return scores

    def warm(
        self,
        replica_id: str,
        prompt_text: str | None = None,
        prompt_token_ids: list[int] | None = None,
    ) -> bool:
        """Seed the mirror from an out-of-band observation — router
        crash recovery (ISSUE 17) replays recovered journals' prompts
        through here so the rebuilt index steers repeat traffic back at
        the replicas whose KV caches are still hot.  Returns whether
        the prompt produced any chain to record."""
        keys = self.keys_for(prompt_text, prompt_token_ids)
        if not keys:
            return False
        self.observe(replica_id, keys)
        return True

    def forget(self, replica_id: str) -> None:
        """Drop a replica's chains (its process died or drained: the
        KV cache backing them is gone)."""
        self._trees.pop(replica_id, None)

    def num_blocks(self, replica_id: str) -> int:
        tree = self._trees.get(replica_id)
        return tree.count if tree is not None else 0
