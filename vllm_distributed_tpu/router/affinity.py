"""Router-side prefix-cache affinity index (ISSUE 10 tentpole).

Placement should follow the KV cache (SGLang's cache-aware scheduling,
Zheng et al. 2024, PAPERS.md): a request whose prompt shares a prefix
with work a replica recently served hits that replica's prefix cache
(PR 1) and skips most of its prefill.  The router cannot see replica
allocators directly, so it mirrors the allocator's own indexing scheme —
a hash chain over fixed-size prompt blocks (``PrefixCachingAllocator``
hashes page-aligned token blocks the same way) — over the prompts it has
routed, per replica, fed from response metadata when the replica
confirms service.

Prompts arrive in two forms and each gets its own key namespace (they
must never collide):

- token ids (``t:``): hashed in ``block_tokens``-sized blocks, exactly
  page-granular when ``block_tokens`` matches the engine page size;
- text (``s:``): hashed in ``4 * block_tokens``-byte chunks (~4 UTF-8
  bytes per token), used when the router has no tokenizer — both the
  observe and score sides use the same chunking, so matching stays
  consistent even though the block boundary is approximate.

Bounded: each replica remembers at most ``capacity`` block keys, LRU
beyond that (a router restart simply starts cold).  Single-threaded:
every call happens on the router's event loop.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict

_TEXT_BYTES_PER_TOKEN = 4


def chain_keys(
    prompt_text: str | None,
    prompt_token_ids: list[int] | None,
    block_tokens: int,
) -> list[str]:
    """Hash-chain keys for a prompt, most-significant (longest-prefix)
    last: key i covers blocks 0..i, so a replica holding keys 0..k has
    (approximately) the first (k+1) blocks warm."""
    keys: list[str] = []
    prev = b""
    if prompt_token_ids is not None:
        ns = b"t:"
        units = [
            prompt_token_ids[i : i + block_tokens]
            for i in range(0, len(prompt_token_ids), block_tokens)
        ]
        blocks = [
            ",".join(str(t) for t in u).encode() for u in units
        ]
    else:
        ns = b"s:"
        data = (prompt_text or "").encode("utf-8", "surrogateescape")
        step = block_tokens * _TEXT_BYTES_PER_TOKEN
        blocks = [data[i : i + step] for i in range(0, len(data), step)]
    for block in blocks:
        digest = hashlib.sha256(ns + prev + block).digest()
        keys.append(digest.hex())
        prev = digest
    return keys


class PrefixAffinityIndex:
    """Per-replica LRU sets of prefix-chain block keys + longest-prefix
    scoring over them."""

    def __init__(self, block_tokens: int = 16, capacity: int = 8192):
        self.block_tokens = max(1, block_tokens)
        self.capacity = max(1, capacity)
        # replica_id -> OrderedDict[key -> None], most recent last.
        self._blocks: dict[str, OrderedDict[str, None]] = {}

    def keys_for(
        self,
        prompt_text: str | None = None,
        prompt_token_ids: list[int] | None = None,
    ) -> list[str]:
        return chain_keys(prompt_text, prompt_token_ids, self.block_tokens)

    def observe(self, replica_id: str, keys: list[str]) -> None:
        """Record that ``replica_id`` served a prompt with this chain
        (call when the replica confirms service — first token or
        completed response — so the index tracks caches that exist,
        not placements that failed)."""
        blocks = self._blocks.setdefault(replica_id, OrderedDict())
        for key in keys:
            if key in blocks:
                blocks.move_to_end(key)
            else:
                blocks[key] = None
        while len(blocks) > self.capacity:
            blocks.popitem(last=False)

    def score(self, keys: list[str]) -> dict[str, int]:
        """Approximate warm-prefix length per replica, in tokens: the
        number of consecutive leading chain keys the replica holds,
        times the block size.  Touches matched keys (LRU refresh)."""
        scores: dict[str, int] = {}
        for replica_id, blocks in self._blocks.items():
            matched = 0
            for key in keys:
                if key not in blocks:
                    break
                blocks.move_to_end(key)
                matched += 1
            if matched:
                scores[replica_id] = matched * self.block_tokens
        return scores

    def forget(self, replica_id: str) -> None:
        """Drop a replica's chains (its process died or drained: the
        KV cache backing them is gone)."""
        self._blocks.pop(replica_id, None)

    def num_blocks(self, replica_id: str) -> int:
        return len(self._blocks.get(replica_id, ()))
