"""Fleet lifecycle + autoscaling for the router (ISSUE 13 tentpole).

Two layers that turn ``--replicas N`` from a static flag into a
traffic-following control system (ROADMAP item 5; Llumnix frames
rescheduling and rescaling as continuous control loops over migratable
requests):

- **ReplicaManager**: owns ``vdt serve`` replicas as supervised child
  processes.  Spawn is health-gated (a replica is NEVER routable before
  its ``/health`` answers 200 — warmup/compile time never eats traffic);
  scale-down goes through the PR 7 ``/drain`` path first, so the
  replica's in-flight streams journal-migrate onto survivors via the
  PR 8 router before the process is terminated; crashes are detected by
  reaping child exit codes and respawned under a crash-loop budget that
  mirrors the PR 3 engine supervisor (``VDT_FLEET_MAX_RESTARTS`` within
  ``VDT_FLEET_RESTART_WINDOW_SECONDS``, exponential backoff, terminal
  exhaustion); every child is synchronously reaped on every exit path
  so no zombie ever holds a port.
- **Autoscaler**: a control loop over the gauges the pool already
  scrapes (PR 7 admission depth per replica), the router's own 429
  tally, and the ISSUE 12 fleet SLO merge (ITL p99 / goodput — the
  DistServe control signal).  It holds a replica-count target with
  hysteresis watermarks, per-direction cooldowns, and hard min/max
  bounds; the decision function is pure so the policy is unit-testable
  on synthetic gauge traces.

Everything here is default-off: a router started with static
``--replica URL`` flags behaves exactly as before.
"""

from __future__ import annotations

import asyncio
import math
import os
import shlex
import signal
import subprocess
import time
from collections import deque
from dataclasses import dataclass, field

from vllm_distributed_tpu import envs
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.utils import get_open_port

logger = init_logger(__name__)


# ---------------------------------------------------------------------
# child-process launchers
# ---------------------------------------------------------------------
class PopenHandle:
    """subprocess.Popen adapter for the ChildHandle duck type the
    manager drives: ``pid``, ``poll()``, ``terminate()``, ``kill()``,
    ``wait(timeout)``.  Tests and the chaos harness substitute fork- or
    stub-based handles with the same surface."""

    def __init__(self, proc: subprocess.Popen) -> None:
        self._proc = proc

    @property
    def pid(self) -> int:
        return self._proc.pid

    def poll(self):
        return self._proc.poll()

    def terminate(self) -> None:
        self._proc.terminate()

    def kill(self) -> None:
        self._proc.kill()

    def wait(self, timeout: float | None = None):
        return self._proc.wait(timeout=timeout)


class AdoptedHandle:
    """ChildHandle for a re-adopted orphan (ISSUE 17): the process was
    spawned by a previous router incarnation, and when that router was
    SIGKILLed the child (in its own session) survived and was
    reparented — so it is NOT our child and ``waitpid`` can never reap
    it.  ``poll()`` degrades to a liveness signal (``os.kill(pid, 0)``)
    and the exit code of a vanished orphan is unknowable (reported as
    -1); ``wait()`` is a bounded poll for the same reason."""

    def __init__(self, pid: int) -> None:
        self.pid = int(pid)
        self._exit_code: int | None = None

    def poll(self):
        if self._exit_code is not None:
            return self._exit_code
        try:
            os.kill(self.pid, 0)
            return None
        except ProcessLookupError:
            self._exit_code = -1
            return self._exit_code
        except PermissionError:
            return None  # alive, owned by someone else

    def terminate(self) -> None:
        os.kill(self.pid, signal.SIGTERM)

    def kill(self) -> None:
        os.kill(self.pid, signal.SIGKILL)

    def wait(self, timeout: float | None = None):
        deadline = time.monotonic() + (
            timeout if timeout is not None else 10.0
        )
        while self.poll() is None:
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"adopted pid {self.pid} still alive after wait"
                )
            time.sleep(0.05)
        return self._exit_code


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


class CommandLauncher:
    """Launches managed replicas from a shell-style command template
    with ``{port}`` / ``{replica_id}`` / ``{role}`` placeholders
    (``--fleet-cmd`` / ``VDT_FLEET_CMD``), e.g.::

        vdt serve meta-llama/Llama-3.2-1B --host 127.0.0.1 --port {port}

    The child gets VDT_REPLICA_ID and VDT_ROUTER_ROLE in its
    environment (so ``/health`` and ``X-VDT-Replica-Id`` carry the
    manager's identity and disaggregation role even if the template
    forgets the placeholders) and its own session id, keeping signal
    delivery scoped to the one replica."""

    def __init__(
        self, template: str, extra_env: dict[str, str] | None = None
    ) -> None:
        if "{port}" not in template:
            raise ValueError(
                "fleet command template must contain a {port} placeholder"
            )
        self.template = template
        self.extra_env = dict(extra_env or {})

    def spawn(
        self, replica_id: str, port: int, role: str = "mixed"
    ) -> PopenHandle:
        argv = shlex.split(
            self.template.format(
                port=port, replica_id=replica_id, role=role
            )
        )
        env = {
            **os.environ,
            **self.extra_env,
            "VDT_REPLICA_ID": replica_id,
            "VDT_ROUTER_ROLE": role,
        }
        proc = subprocess.Popen(  # vdt-lint: disable=thread-leak — reaped by ReplicaManager._reap on every exit path
            argv, env=env, start_new_session=True
        )
        return PopenHandle(proc)


# ---------------------------------------------------------------------
# managed replica state machine
# ---------------------------------------------------------------------
# starting -> ready -> draining -> stopping -> stopped
#     \-> crashed (respawn under budget)      ^
#      \-> failed (warmup timeout) -----------/
_ACTIVE_STATES = ("starting", "ready")


@dataclass
class ManagedReplica:
    replica_id: str
    port: int
    handle: object  # ChildHandle duck type
    state: str = "starting"
    # Disaggregation role this replica was spawned under (ISSUE 15).
    role: str = "mixed"
    spawned_mono: float = 0.0
    ready_mono: float = 0.0
    exit_code: int | None = None
    task: asyncio.Task | None = None  # warmup gate or drain task

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def snapshot(self) -> dict:
        return {
            "replica_id": self.replica_id,
            "url": self.url,
            "state": self.state,
            "role": self.role,
            "pid": getattr(self.handle, "pid", None),
            "exit_code": self.exit_code,
        }


class ReplicaManager:
    """Supervises the managed replica set toward ``target`` replicas.
    All mutation happens on the router's event loop (the reconcile loop
    and the HTTP handlers share it), so no locking."""

    def __init__(
        self,
        pool,
        metrics,
        launcher,
        *,
        target: int = 0,
        warmup_timeout: float | None = None,
        drain_timeout: float | None = None,
        check_interval: float | None = None,
        max_restarts: int | None = None,
        restart_window: float | None = None,
        backoff_base: float | None = None,
        backoff_cap: float | None = None,
        health_check=None,
        drainer=None,
        port_factory=get_open_port,
        role_targets: dict[str, int] | None = None,
        persist=None,
    ) -> None:
        def _env(value, name):
            return getattr(envs, name) if value is None else value

        self.pool = pool
        self.metrics = metrics
        self.launcher = launcher
        self.target = max(int(target), 0)
        # Disaggregated pools (ISSUE 15): fixed per-role counts spawned
        # alongside the (autoscalable) mixed target — e.g.
        # {"prefill": 1, "decode": 2}.  Empty = all-mixed, the exact
        # pre-disagg behavior.
        self.role_targets = {
            role: max(int(n), 0)
            for role, n in (role_targets or {}).items()
            if role in ("prefill", "decode") and int(n) > 0
        }
        self.warmup_timeout = _env(
            warmup_timeout, "VDT_FLEET_WARMUP_TIMEOUT_SECONDS"
        )
        self.drain_timeout = _env(
            drain_timeout, "VDT_FLEET_DRAIN_TIMEOUT_SECONDS"
        )
        self.check_interval = _env(
            check_interval, "VDT_FLEET_CHECK_INTERVAL_SECONDS"
        )
        self.max_restarts = _env(max_restarts, "VDT_FLEET_MAX_RESTARTS")
        self.restart_window = _env(
            restart_window, "VDT_FLEET_RESTART_WINDOW_SECONDS"
        )
        self.backoff_base = _env(
            backoff_base, "VDT_FLEET_RESTART_BACKOFF_SECONDS"
        )
        self.backoff_cap = _env(
            backoff_cap, "VDT_FLEET_RESTART_BACKOFF_CAP_SECONDS"
        )
        self._health_check = health_check or self._http_health
        self._drainer = drainer or self._http_drain
        self._port_factory = port_factory
        # Durable membership log (ISSUE 17): every spawn/exit is
        # recorded so a restarted router can re-adopt still-running
        # children instead of leaking or double-spawning them.  None =
        # no persistence, the exact pre-ISSUE-17 behavior.
        self.persist = persist
        self.replicas: list[ManagedReplica] = []
        self.events: deque[dict] = deque(maxlen=512)
        self.restarts_total = 0
        self.exhausted = False  # crash-loop budget spent
        # vdt-lint: disable=unbounded-queue — pruned to the restart
        # window on every use; length bounded by max_restarts + 1
        self._restart_times: deque[float] = deque()
        self._backoff = float(self.backoff_base)
        self._spawn_gate_mono = 0.0  # no spawn before this (backoff)
        self._seq = 0
        self.session = None
        # Installed by RouterState.attach_fleet (ISSUE 19); standalone
        # managers (unit tests) fall back to the noop passthrough.
        self.resilience = None
        # Fleet sentinel (ISSUE 20), installed by attach_fleet: every
        # lifecycle event forwards into the unified timeline, and the
        # sentinel's degraded-replica recycle recommendations land in
        # ``recycle_recommended`` (advisory — the manager records them
        # for the operator/autoscaler; it never kills on its own).
        self.sentinel = None
        self.recycle_recommended: dict[str, dict] = {}
        self._task: asyncio.Task | None = None
        self._stopped = asyncio.Event()

    # ---- durable membership (ISSUE 17) ----
    def _persist_replica(self, mr: ManagedReplica) -> None:
        if self.persist is None or self.persist.closed:
            return
        try:
            self.persist.record_replica(
                mr.replica_id,
                port=mr.port,
                pid=getattr(mr.handle, "pid", None),
                role=mr.role,
                template=getattr(self.launcher, "template", None),
            )
        except Exception:  # noqa: BLE001 — a sick WAL must not take down supervision
            logger.exception("persist of replica %s failed", mr.replica_id)

    def _persist_gone(self, replica_id: str) -> None:
        if self.persist is None or self.persist.closed:
            return
        try:
            self.persist.record_replica_gone(replica_id)
        except Exception:  # noqa: BLE001 — a sick WAL must not take down supervision
            logger.exception(
                "persist of replica %s removal failed", replica_id
            )

    def persist_targets(self) -> None:
        """Record the current scale targets in the WAL.  The targets
        are control-plane state: a router crash between a scale-up and
        its convergence must not revert the fleet to the CLI default."""
        if self.persist is None or self.persist.closed:
            return
        try:
            self.persist.record_fleet_targets(
                self.target, dict(self.role_targets)
            )
        except Exception:  # noqa: BLE001 — a sick WAL must not take down supervision
            logger.exception("persist of fleet targets failed")

    # ---- introspection ----
    def record_event(self, kind: str, replica_id: str = "", **detail) -> None:
        # The manager's own bounded ring predates the sentinel and
        # feeds /router/fleet; the unified timeline gets the same event
        # through the emitter API below.
        # vdt-lint: disable=sentinel-emitter — legacy /router/fleet ring, mirrored into the sentinel right below
        self.events.append(
            {
                "mono": round(time.monotonic(), 4),
                "kind": kind,
                "replica_id": replica_id,
                **detail,
            }
        )
        if self.sentinel is not None:
            try:
                self.sentinel.emit(kind, replica_id=replica_id, **detail)
            except Exception:  # noqa: BLE001 — the timeline must never break fleet supervision
                logger.exception("sentinel fleet-event forward failed")

    def note_recycle_recommendation(
        self, replica_id: str, **detail
    ) -> None:
        """Advisory sink for the sentinel's degraded-replica verdicts
        (ISSUE 20): recorded in the event log and surfaced in
        ``snapshot()`` — the manager deliberately does NOT act on it."""
        self.recycle_recommended[replica_id] = {
            "mono": round(time.monotonic(), 4),
            **detail,
        }
        self.record_event("recycle_recommended", replica_id, **detail)

    def active(self, role: str | None = None) -> list[ManagedReplica]:
        """Replicas counting toward the target (starting or serving),
        optionally filtered to one disaggregation role."""
        return [
            r
            for r in self.replicas
            if r.state in _ACTIVE_STATES
            and (role is None or r.role == role)
        ]

    def ready_count(self) -> int:
        return sum(1 for r in self.replicas if r.state == "ready")

    def snapshot(self) -> dict:
        return {
            "target": self.target,
            "role_targets": dict(self.role_targets),
            "ready": self.ready_count(),
            "active": len(self.active()),
            "exhausted": self.exhausted,
            "restarts_total": self.restarts_total,
            "replicas": [r.snapshot() for r in self.replicas],
            "events": list(self.events),
            "recycle_recommended": {
                rid: dict(detail)
                for rid, detail in self.recycle_recommended.items()
            },
        }

    # ---- scaling entry points ----
    def scale_to(self, n: int, reason: str = "manual") -> int:
        """Set the replica-count target; the reconcile loop converges.
        An explicit resize also clears crash-loop exhaustion — it is
        the operator override that says 'try again'."""
        n = max(int(n), 0)
        if n != self.target:
            direction = "up" if n > self.target else "down"
            self.record_event(
                "scale", from_target=self.target, to=n, reason=reason
            )
            if self.metrics is not None:
                self.metrics.record_scale(direction, reason)
            logger.info(
                "fleet target %d -> %d (%s)", self.target, n, reason
            )
        changed = n != self.target
        self.target = n
        if reason == "manual":
            self.exhausted = False
        if changed:
            self.persist_targets()
        return self.target

    def scale_role_to(self, role: str, n: int, reason: str = "manual") -> int:
        """Set one disagg role's target (ISSUE 16 per-role autoscaling);
        the reconcile loop converges just like the mixed target — one
        spawn per tick up, drain-then-terminate down."""
        if role not in ("prefill", "decode"):
            raise ValueError(f"unknown disagg role {role!r}")
        n = max(int(n), 0)
        current = self.role_targets.get(role, 0)
        if n != current:
            direction = "up" if n > current else "down"
            self.record_event(
                "scale_role",
                role=role,
                from_target=current,
                to=n,
                reason=reason,
            )
            if self.metrics is not None:
                self.metrics.record_scale(direction, reason)
            logger.info(
                "fleet %s target %d -> %d (%s)", role, current, n, reason
            )
        changed = n != current
        self.role_targets[role] = n
        if changed:
            self.persist_targets()
        return n

    # ---- lifecycle ----
    def start(self, session) -> None:
        if self._task is not None:
            return
        self.session = session
        self._stopped.clear()
        self._task = asyncio.get_running_loop().create_task(
            self._reconcile_loop()
        )

    async def _reconcile_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                await self._reconcile()
            except asyncio.CancelledError:
                return
            except Exception:  # noqa: BLE001 — the supervisor loop must outlive one bad tick
                logger.exception("fleet reconcile failed")
            try:
                await asyncio.wait_for(
                    self._stopped.wait(), timeout=self.check_interval
                )
            except asyncio.TimeoutError:
                continue

    def _targets(self) -> dict[str, int]:
        """Per-role convergence targets: the (resizable) mixed target
        plus the fixed disagg role counts (ISSUE 15)."""
        targets = {"mixed": self.target}
        targets.update(self.role_targets)
        return targets

    async def _reconcile(self) -> None:
        self._sweep_exits()
        now = time.monotonic()
        spawned = False
        for role, target in self._targets().items():
            active = self.active(role)
            if (
                len(active) < target
                and not spawned
                and not self.exhausted
                and now >= self._spawn_gate_mono
            ):
                # One spawn per tick ACROSS roles: converging a big jump
                # gradually keeps the warmups (and their compile storms)
                # from stampeding.
                self._spawn_one(role)
                spawned = True
            elif len(active) > target:
                for victim in self._pick_victims(
                    len(active) - target, role
                ):
                    victim.task = asyncio.get_running_loop().create_task(
                        self._retire(victim)
                    )
        if self.metrics is not None:
            self.metrics.update_fleet(self)

    # ---- crash detection ----
    def _sweep_exits(self) -> None:
        for mr in list(self.replicas):
            if mr.state in ("stopping", "stopped", "crashed", "failed"):
                continue
            rc = mr.handle.poll()
            if rc is None:
                continue
            # The child died under us: a crash, not a managed stop.
            mr.exit_code = rc
            was_ready = mr.state == "ready"
            mr.state = "crashed"
            if mr.task is not None:
                mr.task.cancel()
            self.pool.remove(mr.url)
            self.record_event("crash", mr.replica_id, exit_code=rc)
            if self.metrics is not None:
                self.metrics.record_fleet_restart("crash")
            logger.warning(
                "managed replica %s (pid %s) exited %s while %s",
                mr.replica_id,
                getattr(mr.handle, "pid", "?"),
                rc,
                "serving" if was_ready else "warming",
            )
            self.replicas.remove(mr)
            self._persist_gone(mr.replica_id)
            self._note_crash()

    def _note_crash(self) -> None:
        """Crash-loop bookkeeping, mirroring the PR 3 supervisor: count
        restarts inside the window, back off exponentially, and go
        terminal (stop respawning) when the budget is spent."""
        now = time.monotonic()
        while (
            self._restart_times
            and now - self._restart_times[0] > self.restart_window
        ):
            self._restart_times.popleft()
        if self.max_restarts <= 0 or (
            len(self._restart_times) >= self.max_restarts
        ):
            if not self.exhausted:
                self.exhausted = True
                self.record_event(
                    "restart_budget_exhausted",
                    window_restarts=len(self._restart_times),
                )
                logger.error(
                    "fleet crash-loop budget exhausted (%d restarts in "
                    "%.0fs window); not respawning — resize to retry",
                    len(self._restart_times),
                    self.restart_window,
                )
            return
        self._restart_times.append(now)
        self.restarts_total += 1
        self._spawn_gate_mono = now + self._backoff
        self._backoff = min(self._backoff * 2, self.backoff_cap)

    # ---- restart recovery: orphan re-adoption (ISSUE 17) ----
    def adopt_recovered(
        self,
        recovered: dict[str, dict],
        *,
        verify_window: float | None = None,
    ) -> list[ManagedReplica]:
        """Re-adopt the WAL's recorded children instead of leaking or
        respawning them.  For each record: a dead pid is reaped from
        the log (the normal reconcile respawns the shortfall); a live
        pid becomes a supervised :class:`ManagedReplica` again — state
        ``ready`` (it was serving when the old router died) behind an
        :class:`AdoptedHandle`, entered into the pool in the
        ``verifying`` grace state so it takes no traffic until a probe
        confirms it, while ``_adopt_gate`` checks that ``/health`` still
        answers with the recorded ``VDT_REPLICA_ID`` (a reused pid or
        port belongs to a stranger — dropped, never signalled).

        Must be called before ``start()``/the first reconcile tick, on
        the running event loop."""
        vw = float(
            verify_window
            if verify_window is not None
            else envs.VDT_ROUTER_STATE_VERIFY_WINDOW_SECONDS
        )
        adopted: list[ManagedReplica] = []
        for replica_id, rec in recovered.items():
            pid = rec.get("pid")
            port = rec.get("port")
            if not pid or not port:
                self._persist_gone(replica_id)
                continue
            if not _pid_alive(int(pid)):
                # Reaped from the log; the reconcile loop respawns the
                # shortfall through the normal spawn path.  Not charged
                # to the crash budget — the child didn't crash-loop,
                # it died while no supervisor existed.
                self.record_event("adopt_dead", replica_id, pid=pid)
                logger.info(
                    "recorded replica %s (pid %s) is gone; will respawn",
                    replica_id,
                    pid,
                )
                self._persist_gone(replica_id)
                continue
            role = rec.get("role") or "mixed"
            if role not in ("prefill", "decode", "mixed"):
                role = "mixed"
            now = time.monotonic()
            mr = ManagedReplica(
                replica_id=replica_id,
                port=int(port),
                handle=AdoptedHandle(int(pid)),
                state="ready",
                role=role,
                spawned_mono=now,
                ready_mono=now,
            )
            self.replicas.append(mr)
            self.pool.add(
                mr.url,
                replica_id=replica_id,
                role=role,
                verify_window=vw,
            )
            self.record_event("adopt", replica_id, pid=pid, port=port)
            logger.info(
                "re-adopted replica %s (pid %s, port %s); verifying",
                replica_id,
                pid,
                port,
            )
            mr.task = asyncio.get_running_loop().create_task(
                self._adopt_gate(mr, vw)
            )
            adopted.append(mr)
        # Keep fresh spawn ids disjoint from adopted ones: fleet-<seq>
        # must not collide with a replica we just re-adopted.
        for mr in adopted:
            tail = mr.replica_id.rsplit("-", 1)[-1]
            if tail.isdigit():
                self._seq = max(self._seq, int(tail))
        return adopted

    async def _health_identity(self, url: str) -> tuple[bool, str]:
        """One bounded /health read: (answered-200, replica_id).
        Lifecycle probes pass replica_id=None to the resilience wrapper
        on purpose: a breaker opened by the old incarnation must never
        gate the probe that would prove the new one healthy."""
        import aiohttp

        from vllm_distributed_tpu.router.resilience import (
            ResilienceManager,
        )

        rz = self.resilience or ResilienceManager.noop()
        timeout = aiohttp.ClientTimeout(total=2, connect=2)

        async def fetch() -> tuple[bool, str]:
            async with await rz.request(
                self.session,
                "GET",
                f"{url}/health",
                endpoint="health",
                timeout=timeout,
            ) as resp:
                if resp.status != 200:
                    return False, ""
                try:
                    body = await resp.json()
                except Exception:  # noqa: BLE001 — 200 with no JSON body still proves liveness
                    body = {}
                return True, str((body or {}).get("replica_id") or "")

        try:
            return await rz.hedged("health", None, fetch)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — not answering (yet)
            return False, ""

    async def _adopt_gate(self, mr: ManagedReplica, verify_window: float) -> None:
        """Identity check for a re-adopted child: within the grace
        window, /health must answer 200 with the recorded replica id.
        A stranger on the port (pid/port reuse) is dropped from
        supervision without being signalled — it is not ours to kill;
        a silent window expiry reaps the pid we do own and respawns
        through the normal crash budget."""
        deadline = time.monotonic() + max(verify_window, 0.5)
        try:
            while time.monotonic() < deadline:
                if mr.state != "ready":
                    return  # retired/crashed mid-verify
                if mr.handle.poll() is not None:
                    return  # died; _sweep_exits attributes the crash
                answered, rid = await self._health_identity(mr.url)
                if answered:
                    if rid and rid != mr.replica_id:
                        self.record_event(
                            "adopt_identity_mismatch",
                            mr.replica_id,
                            found=rid,
                        )
                        logger.warning(
                            "port %d answers as %r, not %r; dropping "
                            "adoption (not signalling a stranger)",
                            mr.port,
                            rid,
                            mr.replica_id,
                        )
                        mr.state = "failed"
                        self.pool.remove(mr.url)
                        if mr in self.replicas:
                            self.replicas.remove(mr)
                        self._persist_gone(mr.replica_id)
                        self._note_crash()
                        return
                    self.record_event("adopt_verified", mr.replica_id)
                    return
                await asyncio.sleep(
                    min(0.5, max(self.check_interval / 2, 0.05))
                )
        except asyncio.CancelledError:
            raise
        if mr.state != "ready":
            return
        # Grace window expired with the pid alive but /health mute:
        # whatever is running is not servable — reap our pid, respawn.
        mr.state = "failed"
        self.record_event(
            "adopt_verify_timeout", mr.replica_id, timeout=verify_window
        )
        logger.error(
            "re-adopted replica %s never verified within %.0fs; reaping",
            mr.replica_id,
            verify_window,
        )
        self.pool.remove(mr.url)
        await self._reap(mr)
        if mr in self.replicas:
            self.replicas.remove(mr)
        self._persist_gone(mr.replica_id)
        self._note_crash()

    # ---- spawn + health-gated warmup ----
    def _spawn_one(self, role: str = "mixed") -> ManagedReplica:
        self._seq += 1
        replica_id = (
            f"fleet-{self._seq}"
            if role == "mixed"
            else f"fleet-{role}-{self._seq}"
        )
        port = self._port_factory()
        try:
            handle = self.launcher.spawn(replica_id, port, role=role)
        except TypeError:
            # Legacy launcher surface (tests/chaos harness fakes that
            # predate roles): only the mixed pool can use it.
            handle = self.launcher.spawn(replica_id, port)
        mr = ManagedReplica(
            replica_id=replica_id,
            port=port,
            handle=handle,
            role=role,
            spawned_mono=time.monotonic(),
        )
        self.replicas.append(mr)
        self.record_event(
            "spawn",
            replica_id,
            port=port,
            role=role,
            pid=getattr(handle, "pid", None),
        )
        self._persist_replica(mr)
        mr.task = asyncio.get_running_loop().create_task(
            self._warmup_gate(mr)
        )
        return mr

    async def _http_health(self, url: str) -> bool:
        import aiohttp

        from vllm_distributed_tpu.router.resilience import (
            ResilienceManager,
        )

        rz = self.resilience or ResilienceManager.noop()
        timeout = aiohttp.ClientTimeout(total=2, connect=2)
        try:
            # replica_id=None: warmup probes must never be breaker-gated
            # (see _health_identity).
            async with await rz.request(
                self.session,
                "GET",
                f"{url}/health",
                endpoint="health",
                timeout=timeout,
            ) as resp:
                return resp.status == 200
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — not up yet
            return False

    async def _warmup_gate(self, mr: ManagedReplica) -> None:
        """Poll the child's /health until it answers 200 — only then
        does the replica enter the pool (already marked healthy, so it
        is routable immediately).  A child that dies or never comes up
        within the warmup deadline is reaped and counts as a crash."""
        deadline = time.monotonic() + self.warmup_timeout
        try:
            while time.monotonic() < deadline:
                if mr.handle.poll() is not None:
                    return  # exit; _sweep_exits attributes the crash
                if await self._health_check(mr.url):
                    if mr.state != "starting":
                        return  # retired mid-warmup
                    mr.state = "ready"
                    mr.ready_mono = time.monotonic()
                    self._backoff = float(self.backoff_base)
                    self.pool.add(
                        mr.url,
                        replica_id=mr.replica_id,
                        state="healthy",
                        role=mr.role,
                    )
                    self.record_event("ready", mr.replica_id)
                    logger.info(
                        "managed replica %s ready on %s after %.1fs",
                        mr.replica_id,
                        mr.url,
                        mr.ready_mono - mr.spawned_mono,
                    )
                    return
                await asyncio.sleep(
                    min(0.1, max(self.check_interval / 2, 0.01))
                )
        except asyncio.CancelledError:
            raise
        if mr.state != "starting":
            return
        # Warmup deadline blown: treat as a crash (reap + budget).
        mr.state = "failed"
        self.record_event(
            "warmup_failed", mr.replica_id, timeout=self.warmup_timeout
        )
        if self.metrics is not None:
            self.metrics.record_fleet_restart("warmup_failed")
        logger.error(
            "managed replica %s never became healthy within %.0fs",
            mr.replica_id,
            self.warmup_timeout,
        )
        await self._reap(mr)
        if mr in self.replicas:
            self.replicas.remove(mr)
        self._persist_gone(mr.replica_id)
        self._note_crash()

    # ---- scale-down: drain, then terminate, then reap ----
    def _pick_victims(
        self, n: int, role: str = "mixed"
    ) -> list[ManagedReplica]:
        """Newest-first within the role: the youngest replica has the
        coldest caches (prefix affinity steers repeat traffic at the
        old-timers), so retiring it loses the least steering
        precision."""
        victims: list[ManagedReplica] = []
        # Prefer replicas still warming (no work to drain), then the
        # most recently spawned ready ones.
        for mr in reversed(self.replicas):
            if len(victims) == n:
                break
            if mr.state == "starting" and mr.role == role:
                victims.append(mr)
        for mr in reversed(self.replicas):
            if len(victims) == n:
                break
            if (
                mr.state == "ready"
                and mr.role == role
                and mr not in victims
            ):
                victims.append(mr)
        return victims

    async def _http_drain(self, url: str, timeout: float) -> None:
        import aiohttp

        from vllm_distributed_tpu.router.resilience import (
            ResilienceManager,
        )

        rz = self.resilience or ResilienceManager.noop()
        # The drain deadline is the caller's contract, not a latency
        # estimate: keep the explicit timeout, never the adaptive one.
        async with await rz.request(
            self.session,
            "POST",
            f"{url}/drain",
            endpoint="drain",
            adaptive=False,
            params={"timeout": str(timeout)},
            timeout=aiohttp.ClientTimeout(total=timeout + 10),
        ) as resp:
            await asyncio.wait_for(resp.read(), timeout=timeout + 10)

    async def _retire(self, mr: ManagedReplica) -> None:
        """The scale-down path: every routable victim is DRAINED before
        it is terminated — /drain stops admission and journals/cuts its
        in-flight streams, which the router live-migrates onto the
        survivors — so a resize never loses admitted work."""
        was_ready = mr.state == "ready"
        mr.state = "draining"
        if was_ready:
            self.record_event("drain", mr.replica_id)
            try:
                await asyncio.wait_for(
                    self._drainer(mr.url, self.drain_timeout),
                    timeout=self.drain_timeout + 15,
                )
                self.record_event("drained", mr.replica_id)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — a dead/deaf victim is terminated anyway
                self.record_event(
                    "drain_failed", mr.replica_id, error=str(e)
                )
                logger.warning(
                    "drain of %s failed (%s); terminating anyway",
                    mr.replica_id,
                    e,
                )
        else:
            self.record_event("abort_warmup", mr.replica_id)
        self.pool.remove(mr.url)
        mr.state = "stopping"
        await self._reap(mr)
        mr.state = "stopped"
        self.record_event("stopped", mr.replica_id, exit_code=mr.exit_code)
        if mr in self.replicas:
            self.replicas.remove(mr)
        self._persist_gone(mr.replica_id)

    async def _reap(self, mr: ManagedReplica) -> None:
        """TERM, bounded wait, KILL, synchronous reap.  Nothing returns
        until the child's exit status is collected — no zombie ever
        holds the port."""
        handle = mr.handle
        if handle.poll() is None:
            try:
                handle.terminate()
            except (ProcessLookupError, OSError):
                pass
            deadline = time.monotonic() + 5.0
            while handle.poll() is None and time.monotonic() < deadline:
                await asyncio.sleep(0.05)
        if handle.poll() is None:
            try:
                handle.kill()
            except (ProcessLookupError, OSError):
                pass
        # Collect the exit status off-loop (wait() blocks); bounded so
        # an unkillable child cannot wedge shutdown.
        loop = asyncio.get_running_loop()
        try:
            await loop.run_in_executor(
                None, lambda: handle.wait(timeout=10)
            )
        except Exception as e:  # noqa: BLE001 — already reaped or truly stuck; poll() below records what we know
            logger.debug("reap wait for %s: %s", mr.replica_id, e)
        mr.exit_code = handle.poll()

    # ---- shutdown (router exit / SIGTERM) ----
    async def stop(
        self, *, drain: bool = True, drain_timeout: float | None = None
    ) -> None:
        """Retire the whole managed fleet: gracefully drain every
        serving replica (bounded), then terminate and reap every child
        so a router kill never leaks ``vdt serve`` processes."""
        self._stopped.set()
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await asyncio.wait_for(task, timeout=5.0)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                pass
        for mr in self.replicas:
            if mr.task is not None:
                mr.task.cancel()
        bound = (
            self.drain_timeout if drain_timeout is None else drain_timeout
        )
        if drain and self.session is not None:
            drainables = [r for r in self.replicas if r.state == "ready"]
            if drainables:
                self.record_event(
                    "shutdown_drain", count=len(drainables), timeout=bound
                )

                async def _drain_one(mr: ManagedReplica) -> None:
                    mr.state = "draining"
                    self.record_event("drain", mr.replica_id)
                    try:
                        await self._drainer(mr.url, bound)
                        self.record_event("drained", mr.replica_id)
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:  # noqa: BLE001 — shutdown proceeds to terminate regardless
                        self.record_event(
                            "drain_failed", mr.replica_id, error=str(e)
                        )

                try:
                    await asyncio.wait_for(
                        asyncio.gather(
                            *(_drain_one(r) for r in drainables),
                            return_exceptions=True,
                        ),
                        timeout=bound + 15,
                    )
                except asyncio.TimeoutError:
                    logger.warning(
                        "fleet shutdown drain exceeded %.0fs; "
                        "terminating remaining replicas",
                        bound,
                    )
        for mr in list(self.replicas):
            self.pool.remove(mr.url)
            await self._reap(mr)
            mr.state = "stopped"
            self.record_event(
                "stopped", mr.replica_id, exit_code=mr.exit_code
            )
            self._persist_gone(mr.replica_id)
        self.replicas.clear()
        if self.metrics is not None:
            self.metrics.update_fleet(self)


# ---------------------------------------------------------------------
# autoscaler
# ---------------------------------------------------------------------
@dataclass
class FleetSignals:
    """One tick's worth of control inputs."""

    routable: int = 0
    waiting: float = 0.0  # summed vllm:num_requests_waiting
    running: float = 0.0  # summed vllm:num_requests_running
    reject_rate: float = 0.0  # router 429s per second since last tick
    itl_p99_ms: float | None = None  # fleet merge (None = not sampled)
    # Worst SLO class whose windowed goodput ratio sags below the
    # floor (ISSUE 16; None = trigger off or everyone attaining).
    goodput_sag: str | None = None
    # EWMA long-prompt arrival rate, req/s (per-role prefill sizing).
    prefill_rate: float = 0.0

    @property
    def waiting_per_replica(self) -> float:
        return self.waiting / max(self.routable, 1)


@dataclass
class AutoscalerConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    interval: float = 5.0
    up_waiting: float = 4.0
    down_waiting: float = 1.0
    up_cooldown: float = 15.0
    down_cooldown: float = 60.0
    max_reject_rate: float = 0.0  # 0 = trigger off
    itl_p99_ms: float = 0.0  # 0 = trigger off
    # Per-class goodput trigger (ISSUE 16): scale up when any class's
    # windowed goodput ratio drops below the floor.  0 = off.
    goodput_floor: float = 0.0
    goodput_min_requests: int = 20
    # Per-role prefill-pool sizing (ISSUE 16): target =
    # ceil(long-prompt EWMA rate / prefill_rps), clamped to
    # [prefill_min, prefill_max].  prefill_rps is the benched per-
    # replica crossover throughput; 0 = off (static --fleet-prefill).
    prefill_rps: float = 0.0
    prefill_min: int = 0
    prefill_max: int = 4

    @classmethod
    def from_env(cls) -> "AutoscalerConfig":
        return cls(
            min_replicas=envs.VDT_AUTOSCALE_MIN_REPLICAS,
            max_replicas=envs.VDT_AUTOSCALE_MAX_REPLICAS,
            interval=envs.VDT_AUTOSCALE_INTERVAL_SECONDS,
            up_waiting=envs.VDT_AUTOSCALE_UP_WAITING,
            down_waiting=envs.VDT_AUTOSCALE_DOWN_WAITING,
            up_cooldown=envs.VDT_AUTOSCALE_UP_COOLDOWN_SECONDS,
            down_cooldown=envs.VDT_AUTOSCALE_DOWN_COOLDOWN_SECONDS,
            max_reject_rate=envs.VDT_AUTOSCALE_MAX_REJECT_RATE,
            itl_p99_ms=envs.VDT_AUTOSCALE_ITL_P99_MS,
            goodput_floor=envs.VDT_AUTOSCALE_GOODPUT_FLOOR,
            goodput_min_requests=envs.VDT_AUTOSCALE_GOODPUT_MIN_REQUESTS,
            prefill_rps=envs.VDT_AUTOSCALE_PREFILL_RPS,
            prefill_min=envs.VDT_AUTOSCALE_PREFILL_MIN,
            prefill_max=envs.VDT_AUTOSCALE_PREFILL_MAX,
        )

    def __post_init__(self) -> None:
        if self.min_replicas < 1:
            raise ValueError("autoscaler min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                "autoscaler needs min_replicas <= max_replicas, got "
                f"{self.min_replicas} > {self.max_replicas}"
            )


def decide(
    target: int,
    signals: FleetSignals,
    cfg: AutoscalerConfig,
    now: float,
    last_up: float,
    last_down: float,
) -> tuple[int, str | None]:
    """Pure scaling policy: returns (new_target, reason) — reason None
    when holding.  Hysteresis: scale up above ``up_waiting`` mean queue
    depth per routable replica (or on a hot 429-rate / ITL-p99
    trigger), down only below the separate ``down_waiting`` mark with
    every trigger quiet; one step per decision; per-direction cooldowns
    (a scale-down also waits out the UP cooldown so the fleet never
    flaps around a burst); hard [min, max] clamp."""
    if target < cfg.min_replicas:
        return cfg.min_replicas, "min_bound"
    if target > cfg.max_replicas:
        return cfg.max_replicas, "max_bound"
    if signals.routable <= 0:
        # Nothing serving yet (all warming, or a fleet-wide outage):
        # signals are unreadable, and respawn is the manager's job.
        return target, None
    reject_hot = (
        cfg.max_reject_rate > 0
        and signals.reject_rate > cfg.max_reject_rate
    )
    itl_hot = (
        cfg.itl_p99_ms > 0
        and signals.itl_p99_ms is not None
        and signals.itl_p99_ms > cfg.itl_p99_ms
    )
    goodput_hot = (
        cfg.goodput_floor > 0 and signals.goodput_sag is not None
    )
    queue_hot = signals.waiting_per_replica > cfg.up_waiting
    if queue_hot or reject_hot or itl_hot or goodput_hot:
        if target >= cfg.max_replicas or now - last_up < cfg.up_cooldown:
            return target, None
        if queue_hot:
            reason = "queue_depth"
        elif reject_hot:
            reason = "reject_rate"
        elif itl_hot:
            reason = "itl_p99"
        else:
            # Class name is registry-bounded (MAX_CLASSES), so the
            # reason string space stays small.
            reason = f"goodput:{signals.goodput_sag}"
        return target + 1, reason
    if (
        signals.waiting_per_replica < cfg.down_waiting
        and target > cfg.min_replicas
        and now - last_down >= cfg.down_cooldown
        and now - last_up >= cfg.down_cooldown
    ):
        return target - 1, "idle"
    return target, None


class Autoscaler:
    """The control loop: each tick gathers FleetSignals from the pool
    gauges + router tallies (and, when the ITL trigger is armed, the
    ISSUE 12 fleet SLO merge via ``slo_probe``), runs ``decide``, and
    resizes the manager's target."""

    def __init__(
        self,
        manager: ReplicaManager,
        pool,
        metrics,
        cfg: AutoscalerConfig | None = None,
        *,
        slo_probe=None,  # async () -> classes dict (app._fleet_slo)
        prefill_demand=None,  # router_qos.PrefillDemand (shared w/ app)
    ) -> None:
        from vllm_distributed_tpu.router.qos import GoodputTracker

        self.manager = manager
        self.pool = pool
        self.metrics = metrics
        self.cfg = cfg or AutoscalerConfig.from_env()
        self.slo_probe = slo_probe
        self.prefill_demand = prefill_demand
        self.goodput = GoodputTracker(
            self.cfg.goodput_floor, self.cfg.goodput_min_requests
        )
        self.last_up = -float("inf")
        self.last_down = -float("inf")
        self.decisions: deque[dict] = deque(maxlen=128)
        self.sentinel = None  # RouterSentinel (wired by app.attach_fleet)
        self._last_rejects = 0.0
        self._last_tick_mono = 0.0
        self._task: asyncio.Task | None = None
        self._stopped = asyncio.Event()

    # ---- signal gathering ----
    def _reject_total(self) -> float:
        counts = getattr(self.metrics, "counts", None) or {}
        return float(
            sum(
                v
                for k, v in counts.items()
                if k.startswith("requests.") and k.endswith(".rejected")
            )
        )

    async def gather_signals(self) -> FleetSignals:
        routable = [r for r in self.pool.replicas if r.routable]
        now = time.monotonic()
        rejects = self._reject_total()
        dt = now - self._last_tick_mono if self._last_tick_mono else 0.0
        rate = (
            max(rejects - self._last_rejects, 0.0) / dt if dt > 0 else 0.0
        )
        self._last_rejects = rejects
        self._last_tick_mono = now
        itl = None
        goodput_sag = None
        slo_armed = self.cfg.itl_p99_ms > 0 or self.cfg.goodput_floor > 0
        if slo_armed and self.slo_probe is not None:
            try:
                classes = await asyncio.wait_for(
                    self.slo_probe(), timeout=20
                )
                p99s = [
                    d.get("itl_p99_ms")
                    for d in (classes or {}).values()
                    if d.get("itl_p99_ms") is not None
                ]
                if p99s:
                    itl = max(p99s)
                if self.cfg.goodput_floor > 0:
                    goodput_sag = self.goodput.update(classes or {})
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — the SLO trigger degrades to queue-depth-only
                logger.debug("autoscaler SLO probe failed: %s", e)
        prefill_rate = 0.0
        if self.cfg.prefill_rps > 0 and self.prefill_demand is not None:
            prefill_rate = self.prefill_demand.sample(now)
        return FleetSignals(
            routable=len(routable),
            waiting=sum(r.waiting for r in routable),
            running=sum(r.running for r in routable),
            reject_rate=rate,
            itl_p99_ms=itl,
            goodput_sag=goodput_sag,
            prefill_rate=prefill_rate,
        )

    # ---- one tick (also driven directly by tests) ----
    async def tick(self) -> tuple[int, str | None]:
        signals = await self.gather_signals()
        now = time.monotonic()
        new_target, reason = decide(
            self.manager.target,
            signals,
            self.cfg,
            now,
            self.last_up,
            self.last_down,
        )
        if reason is not None and new_target != self.manager.target:
            if new_target > self.manager.target:
                self.last_up = now
            else:
                self.last_down = now
            self.decisions.append(
                {
                    "mono": round(now, 3),
                    "from": self.manager.target,
                    "to": new_target,
                    "reason": reason,
                    "waiting_per_replica": round(
                        signals.waiting_per_replica, 3
                    ),
                    "reject_rate": round(signals.reject_rate, 3),
                    "itl_p99_ms": signals.itl_p99_ms,
                }
            )
            if self.sentinel is not None:
                try:
                    self.sentinel.emit(
                        "autoscale_decision",
                        from_target=self.manager.target,
                        to=new_target,
                        reason=reason,
                        waiting_per_replica=round(
                            signals.waiting_per_replica, 3
                        ),
                        reject_rate=round(signals.reject_rate, 3),
                        itl_p99_ms=signals.itl_p99_ms,
                    )
                except Exception:  # noqa: BLE001 — observability must not block scaling
                    logger.exception("sentinel autoscale event failed")
            self.manager.scale_to(new_target, reason=f"autoscale:{reason}")
        self._tick_prefill(signals, now)
        return new_target, reason

    def _tick_prefill(self, signals: FleetSignals, now: float) -> None:
        """Per-role prefill-pool sizing (ISSUE 16): track the EWMA
        long-prompt arrival rate against the benched per-replica
        crossover.  Deliberately simpler than ``decide`` — the EWMA is
        its own damping, and the manager's one-spawn-per-tick /
        drain-then-retire reconcile absorbs step changes, so admitted
        work never drops through a resize."""
        cfg = self.cfg
        if cfg.prefill_rps <= 0 or self.prefill_demand is None:
            return
        want = math.ceil(signals.prefill_rate / cfg.prefill_rps)
        want = min(max(want, cfg.prefill_min), cfg.prefill_max)
        current = self.manager.role_targets.get("prefill", 0)
        if want == current:
            return
        self.decisions.append(
            {
                "mono": round(now, 3),
                "role": "prefill",
                "from": current,
                "to": want,
                "reason": "prefill_demand",
                "prefill_rate": round(signals.prefill_rate, 3),
            }
        )
        if self.sentinel is not None:
            try:
                self.sentinel.emit(
                    "autoscale_decision",
                    role="prefill",
                    from_target=current,
                    to=want,
                    reason="prefill_demand",
                    prefill_rate=round(signals.prefill_rate, 3),
                )
            except Exception:  # noqa: BLE001 — observability must not block scaling
                logger.exception("sentinel autoscale event failed")
        self.manager.scale_role_to(
            "prefill", want, reason="autoscale:prefill_demand"
        )

    # ---- loop plumbing ----
    def start(self) -> None:
        if self._task is not None:
            return
        self._stopped.clear()
        self._task = asyncio.get_running_loop().create_task(self._loop())

    async def _loop(self) -> None:
        while not self._stopped.is_set():
            try:
                await asyncio.wait_for(
                    self._stopped.wait(), timeout=self.cfg.interval
                )
                return
            except asyncio.TimeoutError:
                pass
            try:
                await self.tick()
            except asyncio.CancelledError:
                return
            except Exception:  # noqa: BLE001 — the control loop must outlive one bad tick
                logger.exception("autoscaler tick failed")

    async def stop(self) -> None:
        self._stopped.set()
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await asyncio.wait_for(task, timeout=5.0)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                pass

    def snapshot(self) -> dict:
        return {
            "config": {
                "min_replicas": self.cfg.min_replicas,
                "max_replicas": self.cfg.max_replicas,
                "interval": self.cfg.interval,
                "up_waiting": self.cfg.up_waiting,
                "down_waiting": self.cfg.down_waiting,
                "up_cooldown": self.cfg.up_cooldown,
                "down_cooldown": self.cfg.down_cooldown,
                "max_reject_rate": self.cfg.max_reject_rate,
                "itl_p99_ms": self.cfg.itl_p99_ms,
                "goodput_floor": self.cfg.goodput_floor,
                "goodput_min_requests": self.cfg.goodput_min_requests,
                "prefill_rps": self.cfg.prefill_rps,
                "prefill_min": self.cfg.prefill_min,
                "prefill_max": self.cfg.prefill_max,
            },
            "goodput_window": {
                cls: {"requests": r, "goodput": g}
                for cls, (r, g) in self.goodput.window.items()
            },
            "prefill_rate": (
                round(self.prefill_demand.rate, 3)
                if self.prefill_demand is not None
                else None
            ),
            "decisions": list(self.decisions),
        }
