"""Router-side fleet sentinel (ISSUE 20): timeline merging, fleet SLO
burn-rate alerting, and per-replica anomaly scoring.

The router already touches every replica a few times a second (the
pool's health/metrics probes, ISSUE 4/7); the sentinel rides those
probes instead of adding traffic:

* **Clock offsets** — each ``/health`` round trip doubles as an NTP-ish
  sample (the replica's wall clock vs the midpoint of the router's
  send/recv stamps), kept when its RTT beats the stored best (the same
  accept/decay rule as tracing.py's heartbeat offsets).  ``/router/
  timeline`` uses them to correct every replica's ``ts_wall`` onto the
  router's clock before the merge.
* **Anomaly scoring** — the probe's ``/metrics`` text is re-parsed for
  the sentinel signals (ITL p99, roofline fraction, compile rate,
  pipeline breaks, KV host-tier hit rate, retry rate) and each signal
  is scored as a robust z (median/MAD over the live pool): immune to a
  single sick replica dragging the baseline, unlike mean/stddev.
  Scores export as ``vdt_router:replica_anomaly_score{replica_id,
  signal}``; a replica whose worst |z| crosses the threshold raises a
  ``replica_degraded`` alert and (with ``VDT_SENTINEL_PLACEMENT=1``) is
  deprioritized — never ejected — by placement.
* **Fleet burn rate** — the per-class ``vllm:slo_requests_total`` /
  ``vllm:goodput_requests_total`` counters from the same scrape are
  summed across replicas and fed to a shared
  :class:`~vllm_distributed_tpu.engine.sentinel.BurnRateTracker`;
  multi-window breaches raise ``slo_burn`` alerts.

``merge_timelines`` is a pure function of (per-log event lists, clock
offsets): sorted by corrected timestamp with a total-order tiebreak, so
the merge is order-independent and bit-equal to recomputing from any
partition of the union — the same determinism contract as the ISSUE 12
SLO merge, pinned by tests.
"""

from __future__ import annotations

import re
import statistics
import time
from collections import deque
from typing import Callable

from vllm_distributed_tpu.engine.sentinel import (
    BurnRateTracker,
    SentinelLog,
)
from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)

#: Per-replica condition signals scored by the sentinel.  Rates are
#: per-second deltas between consecutive probes of the same replica.
SIGNALS = (
    "itl_p99_ms",          # vllm:itl_p99_ms (engine-merged p99)
    "roofline_frac",       # vllm:step_roofline_frac
    "compile_rate",        # d(vllm:xla_compiles_total)/dt
    "pipeline_break_rate", # d(vllm:pipeline_breaks_total)/dt
    "kv_host_hit_rate",    # d(host-tier hits)/d(prefix-cache queries)
    "retry_rate",          # d(granted retries targeting the replica)/dt
)

#: Minimum MAD-derived scale per signal: deviations smaller than this
#: are noise, not anomalies, even when the pool is otherwise identical
#: (MAD of a near-constant pool is ~0, which would make any jitter an
#: infinite z).
SIGNAL_EPS = {
    "itl_p99_ms": 5.0,
    "roofline_frac": 0.05,
    "compile_rate": 0.1,
    "pipeline_break_rate": 0.1,
    "kv_host_hit_rate": 0.05,
    "retry_rate": 0.1,
}

_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')

# 1/0.6745: scales MAD to the stddev of a normal distribution, so the
# anomaly threshold reads in familiar sigma units.
_MAD_TO_SIGMA = 1.4826


def robust_zscores(
    values: dict[str, float], eps: float
) -> dict[str, float]:
    """Median/MAD z-score per key; all-zero when fewer than 3 samples
    (an outlier is undefined without a pool to stand out from)."""
    if len(values) < 3:
        return {k: 0.0 for k in values}
    med = statistics.median(values.values())
    mad = statistics.median(abs(v - med) for v in values.values())
    scale = max(_MAD_TO_SIGMA * mad, eps)
    return {k: (v - med) / scale for k, v in values.items()}


def parse_sentinel_samples(text: str) -> dict:
    """Pull the sentinel's signal inputs out of one replica's
    Prometheus exposition (single pass, labels parsed only for the few
    families that need them)."""
    out: dict = {
        "compiles": 0.0,
        "pipeline_breaks": 0.0,
        "prefix_queries": 0.0,
        "host_hits": 0.0,
        "slo": {},  # cls -> [requests, goodput]
    }
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, _, rest = line.partition(" ")
        if not rest:
            continue
        family, _, labelpart = name.partition("{")
        try:
            value = float(rest.split()[0])
        except ValueError:
            continue
        if family == "vllm:itl_p99_ms":
            out["itl_p99_ms"] = value
        elif family == "vllm:step_roofline_frac":
            out["roofline_frac"] = value
        elif family == "vllm:xla_compiles_total":
            out["compiles"] += value
        elif family == "vllm:pipeline_breaks_total":
            out["pipeline_breaks"] += value
        elif family == "vllm:prefix_cache_queries_total":
            out["prefix_queries"] += value
        elif family == "vllm:prefix_cache_hits_total":
            labels = dict(_LABEL_RE.findall(labelpart))
            if labels.get("tier") == "host":
                out["host_hits"] += value
        elif family in (
            "vllm:slo_requests_total",
            "vllm:goodput_requests_total",
        ):
            labels = dict(_LABEL_RE.findall(labelpart))
            cls = labels.get("slo_class")
            if not cls:
                continue
            slot = out["slo"].setdefault(cls, [0, 0])
            slot[0 if family == "vllm:slo_requests_total" else 1] += value
    return out


def merge_timelines(
    parts: dict[str, list[dict]],
    offsets: dict[str, float] | None = None,
) -> list[dict]:
    """Merge per-log event lists into one fleet timeline.

    ``parts`` maps the log OWNER (replica id, or "router") to its
    ``/debug/events`` list; ``offsets`` maps owner -> (owner_wall -
    router_wall) so each event's ``ts_wall`` is corrected onto the
    router's clock: ``ts = ts_wall - offset``.  Events sort by
    ``(ts, origin, source, seq)`` — ``(origin, source, seq)`` is unique
    per event, making the order total: merging any shuffling or
    partition of the union yields a bit-identical result.
    """
    offsets = offsets or {}
    merged: list[dict] = []
    for owner, events in parts.items():
        offset = offsets.get(owner, 0.0)
        for ev in events:
            out = dict(ev)
            out["origin"] = owner
            out["ts"] = round(float(ev.get("ts_wall", 0.0)) - offset, 6)
            merged.append(out)
    merged.sort(
        key=lambda e: (
            e["ts"],
            e["origin"],
            e.get("source", ""),
            e.get("seq", 0),
        )
    )
    return merged


class RouterSentinel:
    """The router's sentinel state: its own event log, the bounded
    alerts feed, the fleet burn tracker, and per-replica anomaly
    scores.  All mutation happens on the router's event loop (probe
    callbacks and request handlers share it)."""

    def __init__(
        self,
        metrics=None,
        resilience=None,
        anomaly_threshold: float | None = None,
        clock: Callable[[], float] = time.monotonic,
        wall: Callable[[], float] = time.time,
    ) -> None:
        from vllm_distributed_tpu import envs

        if anomaly_threshold is None:
            anomaly_threshold = envs.VDT_SENTINEL_ANOMALY_THRESHOLD
        self.log = SentinelLog("router", clock=clock, wall=wall)
        self.alerts: deque[dict] = deque(maxlen=256)
        self.burn = BurnRateTracker(clock=clock)
        self.metrics = metrics
        self.resilience = resilience
        self.manager = None  # ReplicaManager, attached with the fleet
        self.anomaly_threshold = anomaly_threshold
        self._clock = clock
        self._wall = wall
        # rid -> signal -> latest value / score.
        self.signals: dict[str, dict[str, float]] = {}
        self.scores: dict[str, dict[str, float]] = {}
        # rid -> previous cumulative counters (for rate deltas).
        self._prev: dict[str, dict] = {}
        # rid -> cls -> (requests, goodput): per-replica last-seen SLO
        # counters, summed into the fleet burn tracker.
        self._slo_counts: dict[str, dict[str, tuple[float, float]]] = {}
        # rids currently in the degraded-alert state (edge-triggered).
        self._degraded: set[str] = set()

    # ---- emission ----
    def emit(self, kind: str, replica_id: str = "", **attrs) -> None:
        self.log.emit(kind, replica_id=replica_id, **attrs)

    def alert(self, kind: str, replica_id: str = "", **attrs) -> None:
        """Append to the bounded alerts feed, mirror into the timeline
        (as ``alert_<kind>``), and count."""
        entry = {
            "ts_wall": round(self._wall(), 3),
            "kind": kind,
            "replica_id": replica_id or None,
            **attrs,
        }
        self.alerts.append(entry)
        self.log.emit(f"alert_{kind}", replica_id=replica_id, **attrs)
        if self.metrics is not None:
            self.metrics.record_alert(kind)
        logger.warning("sentinel alert: %s", entry)

    def alerts_snapshot(self) -> list[dict]:
        return list(self.alerts)

    # ---- probe feedback (pool hooks) ----
    def note_replica_state(self, replica_id: str, old: str, new: str) -> None:
        self.emit(
            "replica_state", replica_id=replica_id, old=old, new=new
        )
        if new == "unreachable" and old in (
            "healthy", "verifying", "unknown"
        ):
            self.alert(
                "replica_unreachable", replica_id=replica_id, was=old
            )

    def note_breaker(self, replica_id: str, state: str) -> None:
        self.emit("breaker_transition", replica_id=replica_id, state=state)
        if state == "open":
            self.alert(
                "replica_degraded",
                replica_id=replica_id,
                reason="breaker_open",
            )

    def note_probe(
        self, replica_id: str, metrics_text: str, now: float | None = None
    ) -> None:
        """Digest one replica's /metrics scrape: refresh its signal
        values, the fleet burn tracker, and the pool-wide anomaly
        scores."""
        if now is None:
            now = self._clock()
        samples = parse_sentinel_samples(metrics_text)
        sig = self.signals.setdefault(replica_id, {})
        if "itl_p99_ms" in samples:
            sig["itl_p99_ms"] = samples["itl_p99_ms"]
        if "roofline_frac" in samples:
            sig["roofline_frac"] = samples["roofline_frac"]
        retries = 0.0
        if self.resilience is not None:
            retries = float(
                self.resilience.replica_retries.get(replica_id, 0)
            )
        prev = self._prev.get(replica_id)
        if prev is not None and now > prev["t"]:
            dt = now - prev["t"]
            sig["compile_rate"] = max(
                samples["compiles"] - prev["compiles"], 0.0
            ) / dt
            sig["pipeline_break_rate"] = max(
                samples["pipeline_breaks"] - prev["pipeline_breaks"], 0.0
            ) / dt
            d_queries = samples["prefix_queries"] - prev["prefix_queries"]
            if d_queries > 0:
                sig["kv_host_hit_rate"] = (
                    max(samples["host_hits"] - prev["host_hits"], 0.0)
                    / d_queries
                )
            sig["retry_rate"] = max(retries - prev["retries"], 0.0) / dt
        self._prev[replica_id] = {
            "t": now,
            "compiles": samples["compiles"],
            "pipeline_breaks": samples["pipeline_breaks"],
            "prefix_queries": samples["prefix_queries"],
            "host_hits": samples["host_hits"],
            "retries": retries,
        }
        if samples["slo"]:
            self._slo_counts[replica_id] = {
                cls: (req, good)
                for cls, (req, good) in samples["slo"].items()
            }
            self._observe_fleet_burn(now)
        self._rescore()

    def _observe_fleet_burn(self, now: float) -> None:
        """Sum the per-replica cumulative SLO counters into fleet
        totals and feed the multi-window burn tracker."""
        fleet: dict[str, list[float]] = {}
        for per_cls in self._slo_counts.values():
            for cls, (req, good) in per_cls.items():
                slot = fleet.setdefault(cls, [0.0, 0.0])
                slot[0] += req
                slot[1] += good
        for cls, (req, good) in fleet.items():
            for fired in self.burn.observe(cls, int(req), int(good), now):
                self.alert("slo_burn", **fired)
        if self.metrics is not None:
            self.metrics.update_burn(self.burn, now)

    def _rescore(self) -> None:
        """Recompute robust z-scores for every signal over the pool and
        re-evaluate the degraded set (edge-triggered alerts)."""
        scores: dict[str, dict[str, float]] = {
            rid: {} for rid in self.signals
        }
        for signal in SIGNALS:
            values = {
                rid: sig[signal]
                for rid, sig in self.signals.items()
                if signal in sig
            }
            for rid, z in robust_zscores(
                values, SIGNAL_EPS[signal]
            ).items():
                scores[rid][signal] = round(z, 3)
        self.scores = scores
        if self.metrics is not None:
            for rid, per_sig in scores.items():
                for signal, z in per_sig.items():
                    self.metrics.set_anomaly_score(rid, signal, z)
        for rid, per_sig in scores.items():
            worst = max(
                per_sig.items(),
                key=lambda kv: abs(kv[1]),
                default=(None, 0.0),
            )
            score = abs(worst[1])
            if score >= self.anomaly_threshold:
                if rid not in self._degraded:
                    self._degraded.add(rid)
                    self.alert(
                        "replica_degraded",
                        replica_id=rid,
                        signal=worst[0],
                        score=round(worst[1], 3),
                        reason="anomaly",
                    )
                    self._recommend_recycle(rid, worst[0], worst[1])
            elif score < self.anomaly_threshold * 0.8:
                # Hysteresis: re-arm only once clearly back in band.
                self._degraded.discard(rid)

    def _recommend_recycle(
        self, replica_id: str, signal: str, score: float
    ) -> None:
        """Advisory only: surface a recycle recommendation to the
        ReplicaManager (it records, never actuates — ISSUE 20 keeps the
        sentinel's hands off the replica lifecycle)."""
        if self.manager is None:
            return
        try:
            self.manager.note_recycle_recommendation(
                replica_id, signal=signal, score=round(score, 3)
            )
        except Exception:  # noqa: BLE001 — a recommendation must never break the probe path
            logger.exception("recycle recommendation failed")

    # ---- placement + fleet queries ----
    def outliers(self) -> set[str]:
        """Replica ids currently scoring past the anomaly threshold —
        what VDT_SENTINEL_PLACEMENT deprioritizes."""
        out = set()
        for rid, per_sig in self.scores.items():
            if per_sig and max(abs(z) for z in per_sig.values()) >= (
                self.anomaly_threshold
            ):
                out.add(rid)
        return out

    def forget_replica(self, replica_id: str) -> None:
        self.signals.pop(replica_id, None)
        self.scores.pop(replica_id, None)
        self._prev.pop(replica_id, None)
        self._slo_counts.pop(replica_id, None)
        self._degraded.discard(replica_id)

    def snapshot(self) -> dict:
        """Debug view for /router/state."""
        return {
            "scores": {
                rid: dict(per_sig)
                for rid, per_sig in sorted(self.scores.items())
            },
            "degraded": sorted(self._degraded),
            "burn": self.burn.snapshot(),
            "burn_peak": round(self.burn.peak, 3),
            "alerts": len(self.alerts),
            "events": len(self.log),
        }
