"""Resilient DCN data plane for the router (ISSUE 19).

Every outbound router→replica HTTP call goes through one
``ResilienceManager`` (``RouterState.resilience``), which layers four
independently-gated mechanisms over the raw aiohttp session:

- **Circuit breakers** (per replica): closed → open after
  ``VDT_ROUTER_BREAKER_FAILURES`` consecutive transport
  failures/timeouts (or a windowed timeout-rate trip), open →
  half-open after ``VDT_ROUTER_BREAKER_COOLDOWN_SECONDS`` with exactly
  ONE probe request allowed through, half-open → closed on probe
  success (→ back to open on probe failure).  Breaker state feeds
  placement: an open replica is skipped like an unhealthy one
  (``vdt_router:breaker_state{replica_id}``: 0 closed, 1 half-open,
  2 open).
- **Retry budget** (global + per-replica, Finagle-style monotonic
  token accounting): a retry/hedge is granted only while
  ``granted < min + ratio * attempts`` — so retries can never amplify
  offered outbound load beyond ``ratio`` (plus the fixed ``min``
  reserve) over ANY horizon.  Exhausted budget degrades to the
  existing 503/migration paths instead of retrying.
- **Adaptive deadlines**: per-endpoint EWMA latency quantiles
  (mean + 2·EWMA-absolute-deviation ≈ p95 for the exponential-ish
  tails these calls have) replace the fixed unary ``ClientTimeout``
  totals, clamped to [floor, ceiling].  Streaming timeouts
  (``total=None``) are untouched — sock_read still governs and the
  journal migrates.
- **Hedged requests** on idempotent read paths: after a p95-based
  delay (never below the configured floor) a second identical request
  races the first; first winner cancels the loser, hedges are drawn
  from the retry budget (``vdt_router:hedges_total{outcome}``).

All default-off: with no resilience env set, ``request()`` is a pure
passthrough to ``session.request`` with the caller's own timeout —
byte-identical wire behavior to the pre-ISSUE-19 router (pinned by
tests/test_resilience.py's A/B tests).  The clock and sleep are
injectable so every state machine is unit-testable on synthetic time.
"""

from __future__ import annotations

import asyncio
import time
from collections import Counter as _TallyCounter
from collections import deque
from dataclasses import dataclass

import aiohttp

from vllm_distributed_tpu import envs
from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)

# Breaker states and their gauge encoding.
CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
BREAKER_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

# Minimum window samples before the timeout-rate trip can fire (a
# single timeout must never open a breaker through the rate path).
_RATE_MIN_SAMPLES = 10
# Latency samples before an endpoint's adaptive deadline / hedge delay
# engages (until then the caller's fixed timeout stands).
_MIN_LATENCY_SAMPLES = 8


class BreakerOpen(Exception):
    """Raised by ``request()`` before any I/O when the target replica's
    breaker rejects the call.  Call sites treat it like a transport
    failure (the replica is already suspected)."""

    def __init__(self, replica_id: str) -> None:
        super().__init__(f"circuit breaker open for replica {replica_id}")
        self.replica_id = replica_id


class CircuitBreaker:
    """One replica's breaker.  All transitions happen on the router's
    event loop (no locking), driven by ``acquire``/``record_*``."""

    def __init__(
        self,
        *,
        failures: int,
        cooldown: float,
        timeout_rate: float,
        window: float,
        clock,
    ) -> None:
        self.failures = failures
        self.cooldown = cooldown
        self.timeout_rate = timeout_rate
        self.window = window
        self.clock = clock
        self.state = CLOSED
        self.consecutive = 0
        self.opened_at = 0.0
        self.probe_inflight = False
        # (mono, was_timeout) outcomes inside the rate window; the
        # time-based prune in record_failure is the real bound, maxlen
        # backstops a clock that stops advancing.
        self._events: deque[tuple[float, bool]] = deque(maxlen=4096)

    def _trip(self, now: float) -> None:
        self.state = OPEN
        self.opened_at = now
        self.probe_inflight = False

    def can_route(self) -> bool:
        """Non-mutating placement view: may a request be sent now?"""
        if self.state == CLOSED:
            return True
        if self.state == OPEN:
            return self.clock() - self.opened_at >= self.cooldown
        return not self.probe_inflight

    def acquire(self) -> bool:
        """Mutating admission: True = go ahead (and in half-open, this
        call IS the single probe); False = rejected."""
        if self.state == CLOSED:
            return True
        now = self.clock()
        if self.state == OPEN:
            if now - self.opened_at < self.cooldown:
                return False
            self.state = HALF_OPEN
            self.probe_inflight = True
            return True
        if self.probe_inflight:
            return False
        self.probe_inflight = True
        return True

    def record_success(self) -> None:
        self.consecutive = 0
        if self.state == HALF_OPEN:
            self.state = CLOSED
            self.probe_inflight = False
            self._events.clear()

    def record_failure(self, *, timeout: bool) -> None:
        now = self.clock()
        if self.state == HALF_OPEN:
            # The probe failed: re-open and re-arm the cooldown.
            self._trip(now)
            return
        if self.state == OPEN:
            return  # a straggler launched pre-trip; already open
        self.consecutive += 1
        if self.failures > 0 and self.consecutive >= self.failures:
            self._trip(now)
            return
        if self.timeout_rate > 0:
            # vdt-lint: disable=sentinel-emitter — breaker timeout-rate sample window, not a timeline event ring
            self._events.append((now, timeout))
            while self._events and self._events[0][0] < now - self.window:
                self._events.popleft()
            n = len(self._events)
            if n >= _RATE_MIN_SAMPLES:
                rate = sum(1 for _, t in self._events if t) / n
                if rate >= self.timeout_rate:
                    self._trip(now)


class LatencyTracker:
    """EWMA mean + EWMA absolute deviation per endpoint; the p95
    estimate ``mean + 2·dev`` feeds adaptive deadlines and hedge
    delays."""

    def __init__(self, alpha: float = 0.1) -> None:
        self.alpha = alpha
        self.n = 0
        self.mean = 0.0
        self.dev = 0.0

    def observe(self, x: float) -> None:
        self.n += 1
        if self.n == 1:
            self.mean = x
            self.dev = x / 2.0
            return
        self.mean += self.alpha * (x - self.mean)
        self.dev += self.alpha * (abs(x - self.mean) - self.dev)

    def p95(self) -> float | None:
        if self.n < _MIN_LATENCY_SAMPLES:
            return None
        return self.mean + 2.0 * self.dev


@dataclass
class ResilienceConfig:
    """One knob per mechanism; the all-defaults instance means every
    mechanism is off and the manager is a pure passthrough."""

    breaker_failures: int = 0  # 0 = consecutive-failure trip off
    breaker_cooldown: float = 5.0
    breaker_timeout_rate: float = 0.0  # 0 = rate trip off
    breaker_window: float = 30.0
    retry_ratio: float = 0.0  # 0 = budget off (unbounded, as before)
    retry_min: float = 10.0
    adaptive_deadline: bool = False
    deadline_floor: float = 1.0
    deadline_ceiling: float = 0.0  # 0 = the router read timeout
    deadline_multiplier: float = 3.0
    hedge: bool = False
    hedge_min_delay: float = 0.05
    kv_chunk_retries: int = 0  # 0 = single-attempt transfer, as before
    connect_timeout: float = 5.0
    read_timeout: float = 600.0

    @property
    def breaker_on(self) -> bool:
        return self.breaker_failures > 0 or self.breaker_timeout_rate > 0

    @property
    def budget_on(self) -> bool:
        return self.retry_ratio > 0

    @property
    def enabled(self) -> bool:
        return (
            self.breaker_on
            or self.budget_on
            or self.adaptive_deadline
            or self.hedge
            or self.kv_chunk_retries > 0
        )

    @classmethod
    def from_env(
        cls,
        *,
        connect_timeout: float | None = None,
        read_timeout: float | None = None,
    ) -> "ResilienceConfig":
        return cls(
            breaker_failures=envs.VDT_ROUTER_BREAKER_FAILURES,
            breaker_cooldown=envs.VDT_ROUTER_BREAKER_COOLDOWN_SECONDS,
            breaker_timeout_rate=envs.VDT_ROUTER_BREAKER_TIMEOUT_RATE,
            breaker_window=envs.VDT_ROUTER_BREAKER_WINDOW_SECONDS,
            retry_ratio=envs.VDT_ROUTER_RETRY_BUDGET_RATIO,
            retry_min=envs.VDT_ROUTER_RETRY_BUDGET_MIN,
            adaptive_deadline=bool(envs.VDT_ROUTER_ADAPTIVE_DEADLINE),
            deadline_floor=envs.VDT_ROUTER_DEADLINE_FLOOR_SECONDS,
            deadline_ceiling=envs.VDT_ROUTER_DEADLINE_CEILING_SECONDS,
            deadline_multiplier=envs.VDT_ROUTER_DEADLINE_MULTIPLIER,
            hedge=bool(envs.VDT_ROUTER_HEDGE),
            hedge_min_delay=envs.VDT_ROUTER_HEDGE_MIN_DELAY_MS / 1000.0,
            kv_chunk_retries=envs.VDT_ROUTER_KV_CHUNK_RETRIES,
            connect_timeout=(
                envs.VDT_ROUTER_CONNECT_TIMEOUT_SECONDS
                if connect_timeout is None
                else connect_timeout
            ),
            read_timeout=(
                envs.VDT_ROUTER_READ_TIMEOUT_SECONDS
                if read_timeout is None
                else read_timeout
            ),
        )


class ResilienceManager:
    """The one wrapper every outbound router HTTP call goes through
    (vdt-lint VDT010 enforces it).  Disabled (the default) it adds
    nothing to the wire; enabled, each mechanism engages only when its
    own knob is set."""

    _noop: "ResilienceManager | None" = None

    def __init__(
        self,
        config: ResilienceConfig | None = None,
        *,
        metrics=None,
        clock=time.monotonic,
        sleep=asyncio.sleep,
    ) -> None:
        self.cfg = config or ResilienceConfig()
        self.metrics = metrics
        self.clock = clock
        self._sleep = sleep
        self.breakers: dict[str, CircuitBreaker] = {}
        self.latency: dict[str, LatencyTracker] = {}
        # Breaker transitions entered, keyed "replica_id:state" — the
        # chaos harness asserts the open → half_open → closed walk.
        self.transitions: _TallyCounter = _TallyCounter()
        # Monotonic budget counters (global + per replica): the retry
        # amplification bound is granted <= min + ratio * attempts.
        self.first_attempts = 0
        self.retries_granted = 0
        self.retries_denied = 0
        self.replica_attempts: _TallyCounter = _TallyCounter()
        self.replica_retries: _TallyCounter = _TallyCounter()
        # Fleet sentinel (ISSUE 20): RouterState installs its
        # RouterSentinel here so breaker transitions enter the unified
        # timeline (and open transitions raise degraded-replica alerts).
        self.sentinel = None

    def open_breaker_count(self) -> int:
        """Breakers currently OPEN — flight-recorder step context
        (ISSUE 20 satellite: data-plane health at the moment of
        failure)."""
        return sum(1 for br in self.breakers.values() if br.state == OPEN)

    def retry_budget_balance(self) -> float:
        """Retries still grantable under the amplification bound
        (granted <= min + ratio * first_attempts); -1.0 while the
        budget is off (unbounded)."""
        if not self.cfg.budget_on:
            return -1.0
        allowance = (
            self.cfg.retry_min + self.cfg.retry_ratio * self.first_attempts
        )
        return max(allowance - self.retries_granted, 0.0)

    @classmethod
    def noop(cls) -> "ResilienceManager":
        """Shared always-off passthrough for components constructed
        without a RouterState (unit tests, standalone pools)."""
        if cls._noop is None:
            cls._noop = cls(ResilienceConfig())
        return cls._noop

    @classmethod
    def from_env(
        cls,
        *,
        metrics=None,
        connect_timeout: float | None = None,
        read_timeout: float | None = None,
    ) -> "ResilienceManager":
        return cls(
            ResilienceConfig.from_env(
                connect_timeout=connect_timeout, read_timeout=read_timeout
            ),
            metrics=metrics,
        )

    @property
    def enabled(self) -> bool:
        return self.cfg.enabled

    # ---- breakers ----
    def _breaker(self, replica_id: str) -> CircuitBreaker:
        br = self.breakers.get(replica_id)
        if br is None:
            br = self.breakers[replica_id] = CircuitBreaker(
                failures=self.cfg.breaker_failures,
                cooldown=self.cfg.breaker_cooldown,
                timeout_rate=self.cfg.breaker_timeout_rate,
                window=self.cfg.breaker_window,
                clock=self.clock,
            )
            if self.metrics is not None:
                self.metrics.set_breaker_state(
                    replica_id, BREAKER_GAUGE[CLOSED]
                )
        return br

    def _note_state(
        self, replica_id: str, br: CircuitBreaker, before: str
    ) -> None:
        if br.state == before:
            return
        self.transitions[f"{replica_id}:{br.state}"] += 1
        if self.metrics is not None:
            self.metrics.set_breaker_state(
                replica_id, BREAKER_GAUGE[br.state]
            )
        if self.sentinel is not None:
            try:
                self.sentinel.note_breaker(replica_id, br.state)
            except Exception:  # noqa: BLE001 — timeline is observe-only; never fail the data plane
                logger.exception("sentinel breaker hook failed")
        logger.info(
            "breaker for %s: %s -> %s", replica_id, before, br.state
        )

    def replica_available(self, replica_id: str) -> bool:
        """Placement filter: False while the replica's breaker rejects
        all traffic (open pre-cooldown, or half-open with its single
        probe already in flight)."""
        if not self.cfg.breaker_on:
            return True
        br = self.breakers.get(replica_id)
        return True if br is None else br.can_route()

    def forget_replica(self, replica_id: str) -> None:
        self.breakers.pop(replica_id, None)
        self.replica_attempts.pop(replica_id, None)
        self.replica_retries.pop(replica_id, None)

    # ---- retry budget ----
    def try_spend_retry(
        self, replica_id: str | None = None, *, kind: str = "retry"
    ) -> bool:
        """Grant one retry/hedge from the budget.  Budget off = always
        granted (the pre-ISSUE-19 unbounded-retry behavior)."""
        if not self.cfg.budget_on:
            return True
        allowance = (
            self.cfg.retry_min + self.cfg.retry_ratio * self.first_attempts
        )
        ok = self.retries_granted + 1 <= allowance
        if ok and replica_id is not None:
            per_min = max(1.0, self.cfg.retry_min / 4.0)
            ok = (
                self.replica_retries[replica_id] + 1
                <= per_min
                + self.cfg.retry_ratio * self.replica_attempts[replica_id]
            )
        if ok:
            self.retries_granted += 1
            if replica_id is not None:
                self.replica_retries[replica_id] += 1
        else:
            self.retries_denied += 1
        if kind == "retry" and self.metrics is not None:
            self.metrics.record_retry("granted" if ok else "denied")
        return ok

    # ---- adaptive deadlines ----
    def observe_latency(self, endpoint: str, seconds: float) -> None:
        tr = self.latency.get(endpoint)
        if tr is None:
            tr = self.latency[endpoint] = LatencyTracker()
        tr.observe(seconds)

    def deadline(self, endpoint: str) -> float | None:
        """Adaptive total deadline for a unary call, or None while
        adaptive deadlines are off or the endpoint has too few
        samples (the caller's fixed timeout stands)."""
        if not self.cfg.adaptive_deadline:
            return None
        tr = self.latency.get(endpoint)
        p95 = tr.p95() if tr is not None else None
        if p95 is None:
            return None
        ceiling = self.cfg.deadline_ceiling or self.cfg.read_timeout
        return min(
            max(self.cfg.deadline_multiplier * p95, self.cfg.deadline_floor),
            ceiling,
        )

    # ---- the wrapped request ----
    async def request(
        self,
        session,
        method: str,
        url: str,
        *,
        endpoint: str,
        replica_id: str | None = None,
        counted: bool = True,
        adaptive: bool = True,
        timeout=None,
        **kw,
    ):
        """One outbound HTTP call.  Returns the aiohttp ClientResponse
        (usable as ``async with await ...``); raises BreakerOpen before
        any I/O when the replica's breaker rejects, and propagates
        transport errors unchanged (after feeding the breaker)."""
        cfg = self.cfg
        if not cfg.enabled:
            # vdt-lint: disable=resilient-http — the disabled-mode passthrough IS the wrapper's byte-identical escape hatch
            return await session.request(method, url, timeout=timeout, **kw)
        if counted:
            self.first_attempts += 1
            if replica_id is not None:
                self.replica_attempts[replica_id] += 1
        br = None
        if cfg.breaker_on and replica_id is not None:
            br = self._breaker(replica_id)
            before = br.state
            ok = br.acquire()
            self._note_state(replica_id, br, before)
            if not ok:
                if self.metrics is not None:
                    self.metrics.record_breaker_rejection()
                raise BreakerOpen(replica_id)
        if (
            adaptive
            and cfg.adaptive_deadline
            and timeout is not None
            and timeout.total is not None
        ):
            total = self.deadline(endpoint)
            if total is not None:
                timeout = aiohttp.ClientTimeout(
                    total=total,
                    connect=timeout.connect,
                    sock_read=timeout.sock_read,
                )
        t0 = self.clock()
        try:
            # vdt-lint: disable=resilient-http — the wrapper's single real egress point
            resp = await session.request(method, url, timeout=timeout, **kw)
        except asyncio.CancelledError:
            raise
        except asyncio.TimeoutError:
            if br is not None:
                before = br.state
                br.record_failure(timeout=True)
                self._note_state(replica_id, br, before)
            raise
        except Exception:
            if br is not None:
                before = br.state
                br.record_failure(timeout=False)
                self._note_state(replica_id, br, before)
            raise
        self.observe_latency(endpoint, self.clock() - t0)
        if br is not None:
            before = br.state
            br.record_success()
            self._note_state(replica_id, br, before)
        return resp

    # ---- hedging ----
    def hedge_delay(self, endpoint: str) -> float | None:
        """The p95-based hedge delay, or None while hedging is off or
        the endpoint is cold (never hedge blind)."""
        if not self.cfg.hedge:
            return None
        tr = self.latency.get(endpoint)
        p95 = tr.p95() if tr is not None else None
        if p95 is None:
            return None
        return max(p95, self.cfg.hedge_min_delay)

    async def hedged(self, endpoint: str, replica_id: str | None, factory):
        """Race two executions of ``factory`` (an idempotent fetch
        coroutine factory) after the hedge delay; the first completion
        wins and the loser is cancelled.  The hedge is drawn from the
        retry budget; off/cold endpoints run the factory once,
        unchanged."""
        if not self.cfg.hedge:
            return await factory()
        delay = self.hedge_delay(endpoint)
        if delay is None:
            return await factory()
        loop = asyncio.get_running_loop()
        primary = loop.create_task(factory())
        timer = loop.create_task(self._sleep(delay))
        hedge = None
        try:
            # vdt-lint: disable=unbounded-wait — primary carries its own aiohttp ClientTimeout and timer is a bounded sleep
            await asyncio.wait(
                {primary, timer}, return_when=asyncio.FIRST_COMPLETED
            )
            if primary.done():
                return await primary  # vdt-lint: disable=unbounded-wait — task already done
            if not self.try_spend_retry(replica_id, kind="hedge"):
                self._record_hedge("denied")
                # vdt-lint: disable=unbounded-wait — bounded by the request's own ClientTimeout
                return await primary
            hedge = loop.create_task(factory())
            while True:
                pending = {t for t in (primary, hedge) if not t.done()}
                if pending:
                    # vdt-lint: disable=unbounded-wait — both tasks carry their own ClientTimeout
                    await asyncio.wait(
                        pending, return_when=asyncio.FIRST_COMPLETED
                    )
                # Prefer any SUCCESSFUL completion (a failed primary
                # must not discard a hedge that is about to succeed).
                for task, outcome in (
                    (primary, "primary_won"),
                    (hedge, "hedge_won"),
                ):
                    if (
                        task.done()
                        and not task.cancelled()
                        and task.exception() is None
                    ):
                        self._record_hedge(outcome)
                        # vdt-lint: disable=async-blocking,unbounded-wait — asyncio.Task.result() on a DONE task returns immediately
                        return task.result()
                if primary.done() and hedge.done():
                    self._record_hedge("both_failed")
                    # vdt-lint: disable=async-blocking,unbounded-wait — done task; raises the primary error
                    return primary.result()
        finally:
            for task in (primary, timer, hedge):
                if task is not None and not task.done():
                    task.cancel()

    def _record_hedge(self, outcome: str) -> None:
        if self.metrics is not None:
            self.metrics.record_hedge(outcome)

    # ---- introspection ----
    def snapshot(self) -> dict:
        return {
            "enabled": self.enabled,
            "breakers": {
                rid: br.state for rid, br in self.breakers.items()
            },
            "breaker_transitions": dict(self.transitions),
            "budget": {
                "ratio": self.cfg.retry_ratio,
                "min": self.cfg.retry_min,
                "first_attempts": self.first_attempts,
                "retries_granted": self.retries_granted,
                "retries_denied": self.retries_denied,
            },
            "deadlines": {
                ep: self.deadline(ep) for ep in sorted(self.latency)
            },
        }
