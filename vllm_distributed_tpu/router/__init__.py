"""Multi-replica router (ISSUE 10): cache-affinity placement + live
request migration over journal-replay.  See router/app.py for the
subsystem overview."""

from vllm_distributed_tpu.router.affinity import PrefixAffinityIndex
from vllm_distributed_tpu.router.app import (
    RouterState,
    build_router_app,
)
from vllm_distributed_tpu.router.journal import ChoiceState, RouterJournal
from vllm_distributed_tpu.router.metrics import (
    RouterMetrics,
    merge_expositions,
)
from vllm_distributed_tpu.router.pool import Replica, ReplicaPool

__all__ = [
    "ChoiceState",
    "PrefixAffinityIndex",
    "Replica",
    "ReplicaPool",
    "RouterJournal",
    "RouterMetrics",
    "RouterState",
    "build_router_app",
    "merge_expositions",
]
