"""Multi-replica router front-end (ISSUE 10 tentpole).

An OpenAI-compatible aiohttp process that fans requests out over N
engine replicas (each an independent api_server + mesh slice):

- **Proxy**: ``/v1/completions`` + ``/v1/chat/completions`` with SSE
  passthrough, ``/v1/models`` from a live replica, aggregated
  ``/health`` + ``/metrics`` (every replica's exposition re-labeled
  ``replica="<id>"``), and ``/router/state`` introspection.
- **Placement**: prefix-cache affinity first (PrefixAffinityIndex over
  recently served prompts per replica, fed from response metadata —
  SGLang's cache-aware scheduling), falling back to least-loaded by the
  PR 7 admission gauges scraped from ``/metrics``; 429s put a replica
  in Retry-After backoff instead of marking it down.
- **Live migration** (Llumnix, recompute-based): the router journals
  each proxied request's prompt + streamed tokens
  (``router/journal.py``, mirroring the engine JournalEntry), and when
  the serving replica dies, drains, or sheds the request under
  pressure, re-submits the journal to a healthy replica over
  ``/internal/resume`` with the emitted tokens restored — the client's
  SSE stream continues and greedy outputs are bit-identical to an
  unmigrated run.

The router deliberately holds no model state: it can restart cold (the
affinity index refills from traffic) and it never interprets sampling
params — the original body rides along so the resumed admission is
parameter-identical to the first one.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import AsyncIterator

from aiohttp import web

from vllm_distributed_tpu import envs
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.router.affinity import PrefixAffinityIndex
from vllm_distributed_tpu.router.journal import RouterJournal
from vllm_distributed_tpu.router.metrics import (
    RouterMetrics,
    merge_expositions,
)
from vllm_distributed_tpu.router.pool import Replica, ReplicaPool
from vllm_distributed_tpu.router.qos import PrefillDemand, QosRouterPolicy
from vllm_distributed_tpu.router.resilience import ResilienceManager
from vllm_distributed_tpu.router.sentinel import (
    RouterSentinel,
    merge_timelines,
)
from vllm_distributed_tpu.tracing import get_tracer
from vllm_distributed_tpu.utils import Counter
from vllm_distributed_tpu.version import __version__

logger = init_logger(__name__)

# Wire-protocol headers shared with entrypoints/openai/api_server.py
# (duplicated by value: the router process must not import the engine
# stack just for four strings).
TRACE_HEADER = "X-VDT-Trace-Id"
DEADLINE_HEADER = "X-VDT-Deadline-Ms"
SLO_CLASS_HEADER = "X-VDT-SLO-Class"
REPLICA_HEADER = "X-VDT-Replica-Id"
ROUTER_HEADER = "X-VDT-Router"
# Disaggregated prefill (ISSUE 15): marks the prefill-pool hop; the
# replica runs the request prefill-only and holds its KV for export.
DISAGG_HEADER = "X-VDT-Disagg"
# Crash-safe router (ISSUE 17): with a state dir attached, the router
# echoes every proxied request's id; a client whose stream died with
# the router reconnects by re-POSTing with the echoed id (plus how
# many tokens per choice it already holds) and the journaled remainder
# replays bit-identically.  Unknown/expired ids get a clean 503.
REQUEST_ID_HEADER = "X-VDT-Request-Id"
RESUME_ID_HEADER = "X-VDT-Resume-Id"
# Per-choice "tokens" or "tokens:textchars" counts, comma-separated in
# ascending choice-index order, of what the client already holds.
RESUME_TOKENS_HEADER = "X-VDT-Resume-Tokens"

_PATHS = {"completions": "/v1/completions", "chat": "/v1/chat/completions"}


class MigrationNeeded(Exception):
    """Internal control flow: the current replica can no longer serve
    this stream; re-place the remainder.  ``exclude``/``forget`` are
    False for transient signals (a busy 429 target): the replica stays
    eligible once its Retry-After backoff expires and keeps its
    affinity history — its caches are intact."""

    def __init__(
        self, reason: str, *, exclude: bool = True, forget: bool = True
    ) -> None:
        super().__init__(reason)
        self.reason = reason
        self.exclude = exclude
        self.forget = forget


class StreamAbort(Exception):
    """Internal control flow: a terminal error frame has already been
    written to the client — end the stream, do NOT migrate further."""


class RouterState:
    def __init__(
        self,
        replica_urls: list[str],
        *,
        policy: str | None = None,
        max_migrations: int | None = None,
        affinity_block_tokens: int | None = None,
        affinity_capacity: int | None = None,
        affinity_min_tokens: int | None = None,
        health_interval: float | None = None,
        connect_timeout: float | None = None,
        read_timeout: float | None = None,
        api_key: str | None = None,
        allow_empty_pool: bool = False,
    ) -> None:
        def _env(value, name):
            return getattr(envs, name) if value is None else value

        self.policy = _env(policy, "VDT_ROUTER_POLICY")
        if self.policy not in ("affinity", "least_loaded", "round_robin"):
            raise ValueError(f"unknown router policy {self.policy!r}")
        self.max_migrations = _env(
            max_migrations, "VDT_ROUTER_MAX_MIGRATIONS"
        )
        self.affinity_min_tokens = _env(
            affinity_min_tokens, "VDT_ROUTER_AFFINITY_MIN_TOKENS"
        )
        self.connect_timeout = _env(
            connect_timeout, "VDT_ROUTER_CONNECT_TIMEOUT_SECONDS"
        )
        self.read_timeout = _env(
            read_timeout, "VDT_ROUTER_READ_TIMEOUT_SECONDS"
        )
        self.api_key = api_key
        self.pool = ReplicaPool(
            replica_urls,
            health_interval=_env(
                health_interval, "VDT_ROUTER_HEALTH_INTERVAL_SECONDS"
            ),
            connect_timeout=self.connect_timeout,
            # Fleet mode (ISSUE 13) starts with an empty pool: the
            # ReplicaManager populates it as spawned replicas pass
            # their health-gated warmup.
            allow_empty=allow_empty_pool,
        )
        self.index = PrefixAffinityIndex(
            block_tokens=_env(
                affinity_block_tokens, "VDT_ROUTER_AFFINITY_BLOCK_TOKENS"
            ),
            capacity=_env(
                affinity_capacity, "VDT_ROUTER_AFFINITY_CAPACITY"
            ),
        )
        self.metrics = RouterMetrics()
        # Resilient data plane (ISSUE 19): every outbound HTTP call
        # goes through this manager (VDT010).  With no resilience env
        # set it is a pure passthrough — wire behavior byte-identical
        # to the fixed-timeout router.
        self.resilience = ResilienceManager.from_env(
            metrics=self.metrics,
            connect_timeout=self.connect_timeout,
            read_timeout=self.read_timeout,
        )
        self.pool.resilience = self.resilience
        # Fleet sentinel (ISSUE 20): unified timeline + burn-rate
        # alerting + per-replica anomaly scoring.  Observe-only unless
        # VDT_SENTINEL_PLACEMENT opts placement in.
        self.sentinel = RouterSentinel(
            metrics=self.metrics, resilience=self.resilience
        )
        self.pool.sentinel = self.sentinel
        self.resilience.sentinel = self.sentinel
        self.sentinel_placement = envs.VDT_SENTINEL_PLACEMENT
        self.request_counter = Counter()
        # Disaggregated prefill/decode (ISSUE 15): the hand-off engages
        # only for prompts at/above the crossover AND when the pool
        # actually contains both a prefill-role and a decode-capable
        # replica — an all-mixed pool never takes the path.
        self.disagg_min_prompt_tokens = envs.VDT_DISAGG_MIN_PROMPT_TOKENS
        self.disagg_chunk_layers = envs.VDT_DISAGG_CHUNK_LAYERS
        # QoS placement policy (ISSUE 16): filters the candidate set
        # per SLO class before the routing policy picks within it.
        # shared mode (the default) is a passthrough.
        self.qos = QosRouterPolicy.from_env()
        # Long-prompt arrival EWMA feeding per-role prefill-pool
        # autoscaling; shared with the Autoscaler via attach_fleet.
        self.prefill_demand = PrefillDemand(
            envs.VDT_AUTOSCALE_PREFILL_EWMA_SECONDS
        )
        self._rr = 0
        self.session = None  # aiohttp.ClientSession, set on startup
        # Crash-safe state (ISSUE 17), installed by attach_persist():
        # None = no durable state, the exact pre-ISSUE-17 behavior.
        self.persist = None  # router.persist.RouterStateLog
        self.recovered = None  # router.persist.RecoveredState, until startup
        # request_id -> (expiry_mono, journal dict): recovered in-flight
        # journals awaiting their clients' reconnect, TTL-bounded.
        self.recovered_journals: dict[str, tuple[float, dict]] = {}
        self.recovery_ttl = 0.0
        self.rid_prefix = "rtr"
        # Elastic fleet (ISSUE 13): set by attach_fleet() before the
        # app starts; None = static replica set, exactly the PR 8
        # behavior.
        self.manager = None  # router.fleet.ReplicaManager
        self.autoscaler = None  # router.fleet.Autoscaler
        # Pool-membership hygiene: when a replica leaves (scale-down,
        # crash), its labeled series leave the router's exposition and
        # its prefix-affinity chains are dropped — a departed replica's
        # caches are gone, and a churning autoscaled fleet must not
        # accumulate dead replicas' index state forever.
        def _forget(replica) -> None:
            self.metrics.forget_replica(replica.replica_id)
            self.index.forget(replica.replica_id)
            self.resilience.forget_replica(replica.replica_id)
            self.sentinel.forget_replica(replica.replica_id)

        self.pool.on_remove.append(_forget)

    def attach_fleet(self, manager, autoscaler=None) -> None:
        """Install the fleet lifecycle layer; started on app startup
        (the manager needs the router's client session)."""
        self.manager = manager
        self.autoscaler = autoscaler
        manager.resilience = self.resilience
        # Fleet lifecycle events forward into the unified timeline, and
        # the sentinel's recycle recommendations flow back (advisory).
        manager.sentinel = self.sentinel
        self.sentinel.manager = manager
        if autoscaler is not None:
            autoscaler.sentinel = self.sentinel

    def attach_persist(self, log, recovered=None) -> None:
        """Install the durable-state WAL (ISSUE 17) and any state it
        recovered.  Request ids become unique across incarnations
        (``rtr-<pid>-<n>``) so a restarted router's fresh requests can
        never collide with journals recovered from the previous one."""
        import os

        self.persist = log
        log.sentinel = self.sentinel
        self.recovered = recovered
        self.recovery_ttl = envs.VDT_ROUTER_STATE_RECOVERY_TTL_SECONDS
        self.rid_prefix = f"rtr-{os.getpid()}"

    # ---- durable-state hooks (no-ops without attach_persist) ----
    def persist_checkpoint(self, journal, *, force: bool = False) -> None:
        if self.persist is None or self.persist.closed:
            return
        try:
            self.persist.checkpoint_journal(journal, force=force)
        except Exception:  # noqa: BLE001 — a sick WAL must not take down serving
            logger.exception(
                "journal checkpoint for %s failed", journal.request_id
            )

    def persist_done(self, request_id: str) -> None:
        if self.persist is None or self.persist.closed:
            return
        try:
            self.persist.journal_done(request_id)
        except Exception:  # noqa: BLE001 — a sick WAL must not take down serving
            logger.exception("journal done for %s failed", request_id)

    def take_recovered(self, request_id: str) -> dict | None:
        """Claim a recovered journal for a reconnecting client (pop:
        the first reconnect wins).  Expired entries are reaped lazily
        and marked done in the WAL so compaction drops them."""
        now = time.monotonic()
        expired = [
            rid
            for rid, (deadline, _) in self.recovered_journals.items()
            if deadline < now
        ]
        for rid in expired:
            self.recovered_journals.pop(rid, None)
            self.persist_done(rid)
        entry = self.recovered_journals.pop(request_id, None)
        return entry[1] if entry is not None else None

    # ---- placement ----
    def place(
        self,
        keys: list[str],
        exclude: set[str],
        pool: str = "serve",
        slo_class: str | None = None,
    ) -> tuple[Replica | None, str]:
        """Pick a replica for a prompt with affinity chain ``keys``.
        Returns (replica, deciding_policy).  Role-aware (ISSUE 15):
        ``pool="prefill"`` picks only prefill-role replicas (the
        hand-off hop); ``pool="serve"`` keeps prefill-role replicas out
        of normal placement whenever any decode-capable candidate
        exists (they must stay free for prefill bursts), falling back
        to them only when nothing else is routable — availability over
        purity."""
        cands = self.pool.candidates(exclude)
        # Breaker state feeds placement (ISSUE 19): an open-breaker
        # replica is skipped exactly like an unhealthy one.  No-op
        # filter while breakers are off.
        pre_breaker = len(cands)
        cands = [
            r
            for r in cands
            if self.resilience.replica_available(r.replica_id)
        ]
        if not cands and pre_breaker:
            self.metrics.record_breaker_rejection()
            return None, "breaker_open"
        if pool == "prefill":
            cands = [r for r in cands if r.role == "prefill"]
        else:
            non_prefill = [r for r in cands if r.role != "prefill"]
            if non_prefill:
                cands = non_prefill
            # QoS placement (ISSUE 16) narrows the serve pool per
            # class (segregate/reserve); the affinity walk and load
            # policy below then pick within the class's slice.  The
            # prefill hop stays unfiltered — that pool is sized by
            # phase, not by class.
            cands = self.qos.filter(cands, slo_class)
        if not cands:
            return None, "none"
        # Sentinel deprioritization (ISSUE 20, VDT_SENTINEL_PLACEMENT):
        # anomaly-scored outliers are picked only when nothing in-band
        # can take the request — deprioritized, never ejected.
        if self.sentinel_placement and len(cands) > 1:
            outliers = self.sentinel.outliers()
            if outliers:
                in_band = [
                    r for r in cands if r.replica_id not in outliers
                ]
                if in_band:
                    cands = in_band
        if self.policy == "round_robin":
            self._rr += 1
            return cands[self._rr % len(cands)], "round_robin"
        if self.policy == "affinity" and keys:
            scores = self.index.score(keys)
            scored = [
                (scores.get(r.replica_id, 0), r) for r in cands
            ]
            best = max(s for s, _ in scored)
            if best >= self.affinity_min_tokens:
                tied = [r for s, r in scored if s == best]
                return min(tied, key=lambda r: r.load_key), "affinity"
        return min(cands, key=lambda r: r.load_key), "least_loaded"


# ---- helpers ----
def _error(message: str, status: int = 400, retry_after: int | None = None):
    headers = (
        {"Retry-After": str(retry_after)} if retry_after is not None else None
    )
    return web.json_response(
        {
            "object": "error",
            "message": message,
            "type": "router_error",
            "code": status,
        },
        status=status,
        headers=headers,
    )


def _forward_headers(request: web.Request, trace_ctx) -> dict[str, str]:
    """Headers for the router→replica hop: the internal metadata marker,
    the client's auth and deadline verbatim, and the trace parent so the
    replica's spans land under the router's root span."""
    headers = {ROUTER_HEADER: "1"}
    auth = request.headers.get("Authorization")
    if auth:
        headers["Authorization"] = auth
    deadline = request.headers.get(DEADLINE_HEADER)
    if deadline:
        headers[DEADLINE_HEADER] = deadline
    slo_class = request.headers.get(SLO_CLASS_HEADER)
    if slo_class:
        headers[SLO_CLASS_HEADER] = slo_class
    if trace_ctx is not None:
        headers[TRACE_HEADER] = f"{trace_ctx[0]}-{trace_ctx[1]}"
    return headers


async def _sse_payloads(resp, read_timeout: float) -> AsyncIterator[str]:
    """Yield the payload of each ``data:`` SSE line, line-buffered (TCP
    chunk boundaries need not align with event boundaries).  Each read
    is deadline-bounded: a silently wedged replica must trigger
    migration, not hang the client stream forever."""
    buf = b""
    while True:
        chunk = await asyncio.wait_for(
            resp.content.readany(), timeout=read_timeout
        )
        if not chunk:
            return
        buf += chunk
        while b"\n" in buf:
            line, _, buf = buf.partition(b"\n")
            line = line.strip()
            if line.startswith(b"data:"):
                yield line[5:].strip().decode("utf-8", "replace")


def _upstream_timeout(state: RouterState, streaming: bool):
    import aiohttp

    if streaming:
        return aiohttp.ClientTimeout(
            total=None,
            connect=state.connect_timeout,
            sock_read=state.read_timeout,
        )
    return aiohttp.ClientTimeout(
        total=state.read_timeout, connect=state.connect_timeout
    )


# ---- the proxy ----
async def _proxy(request: web.Request, kind: str) -> web.StreamResponse:
    state: RouterState = request.app["router_state"]
    try:
        body = await request.json()
        if not isinstance(body, dict):
            raise ValueError("body must be a JSON object")
    except Exception as e:  # noqa: BLE001
        state.metrics.record_request(kind, "bad_request")
        return _error(f"invalid request: {e}")
    if state.persist is not None and request.headers.get(RESUME_ID_HEADER):
        # Crash recovery (ISSUE 17): a client whose stream died with
        # the previous router incarnation finishing its request.
        return await _proxy_reconnect(request, state, kind)
    request_id = f"{state.rid_prefix}-{next(state.request_counter)}"
    journal = RouterJournal(request_id, kind, body)
    # Effective SLO class, body field over header (the same precedence
    # the replica applies): drives per-class placement here and rides
    # every migration/hand-off so the request keeps its QoS standing.
    slo_class = body.get("slo_class") or request.headers.get(
        SLO_CLASS_HEADER
    )
    if slo_class:
        journal.slo_class = str(slo_class)
    text, ids = journal.affinity_source()
    keys = state.index.keys_for(text, ids)
    # Long-prompt arrivals feed the prefill-pool demand EWMA (ISSUE
    # 16) whether or not the hand-off engages this time — demand is a
    # property of the workload, not of current pool membership.
    from vllm_distributed_tpu.router import disagg as _disagg

    if (
        state.disagg_min_prompt_tokens > 0
        and _disagg.estimate_prompt_tokens(journal)
        >= state.disagg_min_prompt_tokens
    ):
        state.prefill_demand.observe()
    # Admission checkpoint (ISSUE 17): once this record is durable the
    # request is replayable after a router crash; a crash before it
    # means the client's reconnect gets a clean 503 (retry fresh).
    state.persist_checkpoint(journal, force=True)
    tracer = get_tracer()
    with tracer.span(
        "router.request",
        trace_root=True,
        kind=kind,
        request_id=request_id,
    ) as span:
        fwd = _forward_headers(request, span.ctx)
        if journal.stream:
            response = await _proxy_stream(
                request, state, journal, keys, fwd, span
            )
        else:
            response = await _proxy_unary(
                request, state, journal, keys, fwd, span
            )
        span.set_attribute("migrations", journal.migrations)
        span.set_attribute("served_by", journal.served_by)
    # Terminal for this incarnation (completed, failed with a terminal
    # frame, or client gone): nothing left to replay.
    state.persist_done(journal.request_id)
    return response


def _soonest_backoff_expiry(
    state: RouterState, exclude: set[str]
) -> float | None:
    """Seconds until the first healthy-but-backed-off candidate frees
    up (capped), or None when no candidate is merely busy."""
    now = time.monotonic()
    waits = [
        r.backoff_until - now
        for r in state.pool.replicas
        if r.state == "healthy"
        and r.url not in exclude
        and r.backoff_until > now
    ]
    if not waits:
        return None
    # The wait cap follows the adaptive proxy deadline when adaptive
    # deadlines are on (ISSUE 19 satellite); the historical fixed 5s
    # otherwise.
    cap = 5.0
    if state.resilience.enabled:
        cap = state.resilience.deadline("proxy") or cap
    return min(max(min(waits) + 0.05, 0.1), cap)


def _place_or_none(
    state: RouterState,
    keys: list[str],
    exclude: set[str],
    span,
    pool: str = "serve",
    slo_class: str | None = None,
) -> Replica | None:
    replica, how = state.place(keys, exclude, pool, slo_class)
    if replica is not None:
        state.metrics.record_placement(how)
        get_tracer().event(
            span.ctx,
            "router.placed",
            replica_id=replica.replica_id,
            policy=how,
        )
    return replica


async def _proxy_unary(
    request, state: RouterState, journal, keys, fwd, span
) -> web.Response:
    """Non-streaming proxy.  Nothing reaches the client until a replica
    answers, so 'migration' here is whole-request resubmission — greedy
    regeneration is bit-identical anyway, and no delivered token is
    ever lost because none were delivered."""
    kind = journal.kind
    path = _PATHS[kind]
    exclude: set[str] = set()
    last_429: tuple[bytes, int, dict] | None = None
    while True:
        replica = _place_or_none(
            state, keys, exclude, span, slo_class=journal.slo_class
        )
        if replica is None:
            if last_429 is not None:
                raw, status, headers = last_429
                state.metrics.record_request(kind, "rejected")
                return web.Response(
                    body=raw,
                    status=status,
                    content_type="application/json",
                    headers=headers,
                )
            state.metrics.record_request(kind, "failed")
            return _error(
                "no healthy replica available", 503, retry_after=5
            )
        try:
            async with await state.resilience.request(
                state.session,
                "POST",
                f"{replica.url}{path}",
                endpoint="proxy",
                replica_id=replica.replica_id,
                json=journal.body,
                headers=fwd,
                timeout=_upstream_timeout(state, streaming=False),
            ) as resp:
                raw = await asyncio.wait_for(
                    resp.read(), timeout=state.read_timeout
                )
                status = resp.status
                served_id = resp.headers.get(
                    REPLICA_HEADER, replica.replica_id
                )
                retry_after = resp.headers.get("Retry-After")
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — any transport failure = resubmit elsewhere
            state.pool.note_unreachable(replica, f"{type(e).__name__}: {e}")
            state.index.forget(replica.replica_id)
            exclude.add(replica.url)
            journal.migrations += 1
            state.metrics.record_migration("unreachable")
            if journal.migrations > state.max_migrations:
                state.metrics.record_request(kind, "failed")
                return _error(
                    f"replica failed and migration budget exhausted: {e}",
                    502,
                )
            if not state.resilience.try_spend_retry():
                # Budget exhausted (ISSUE 19): degrade to the existing
                # 503 path instead of amplifying the retry storm.
                state.metrics.record_request(kind, "failed")
                return _error(
                    "replica failed and retry budget exhausted",
                    503,
                    retry_after=1,
                )
            continue
        if status == 429:
            # Healthy but full: back the replica off for Retry-After
            # and try the next candidate; only when every replica is
            # full does the client see the 429.  Deliberately NOT added
            # to ``exclude`` — backoff expiry re-admits it (busy once
            # is not failed-for-this-request).
            try:
                backoff = float(retry_after or "1")
            except ValueError:
                backoff = 1.0
            state.pool.note_backoff(replica, backoff)
            last_429 = (
                raw, status, {"Retry-After": retry_after or "1"},
            )
            continue
        if status in (502, 503):
            if state.resilience.enabled and retry_after is not None:
                # Honor the replica's own Retry-After on 503 (ISSUE 19
                # satellite) so other requests stop hammering it until
                # it expects to recover, not just this one.
                try:
                    state.pool.note_backoff(replica, float(retry_after))
                except ValueError:
                    pass
            exclude.add(replica.url)
            journal.migrations += 1
            state.metrics.record_migration("dead")
            if journal.migrations > state.max_migrations:
                state.metrics.record_request(kind, "failed")
                break
            if not state.resilience.try_spend_retry():
                # Budget exhausted: surface the replica's own 5xx
                # instead of resubmitting (the existing degraded path).
                state.metrics.record_request(kind, "failed")
                break
            continue
        if status == 200:
            journal.served_by = served_id
            state.index.observe(served_id, keys)
            state.metrics.record_request(
                kind,
                "migrated_completed" if journal.migrations else "completed",
            )
        else:
            state.metrics.record_request(kind, "bad_request")
        headers = {REPLICA_HEADER: served_id}
        if state.persist is not None:
            headers[REQUEST_ID_HEADER] = journal.request_id
        return web.Response(
            body=raw,
            status=status,
            content_type="application/json",
            headers=headers,
        )
    return web.Response(
        body=raw, status=status, content_type="application/json"
    )


async def _proxy_stream(
    request, state: RouterState, journal, keys, fwd, span
) -> web.StreamResponse:
    """Streaming proxy with live migration.  The first replica is
    engaged before the client response commits (pre-stream failures are
    silent re-placements); once the SSE stream is open, failures turn
    into journal-replay onto the next replica and the client stream
    simply continues."""
    kind = journal.kind
    path = _PATHS[kind]
    exclude: set[str] = set()
    # Debug/bench passthrough: a client that speaks the internal header
    # keeps the vdt_token_ids metadata (chaos_soak and the router tests
    # assert exact token sequences end-to-end with it).
    client_debug = request.headers.get(ROUTER_HEADER) == "1"

    # Disaggregated prefill (ISSUE 15): long single-choice prompts
    # prefill on the prefill pool and hand their KV off at first token.
    from vllm_distributed_tpu.router import disagg

    plan = disagg.plan_handoff(state, journal, keys)

    # ---- engage the first replica before committing client headers ----
    resp = None
    replica = None
    last_429: tuple[bytes, str] | None = None
    while resp is None:
        replica = _place_or_none(
            state,
            keys,
            exclude,
            span,
            pool="prefill" if plan is not None else "serve",
            slo_class=journal.slo_class,
        )
        if replica is None and plan is not None:
            # Prefill pool gone (excluded/backed off mid-loop): give up
            # on the hand-off and serve normally on the decode pool.
            plan = None
            continue
        if replica is None:
            if last_429 is not None:
                raw, retry_after = last_429
                state.metrics.record_request(kind, "rejected")
                return web.Response(
                    body=raw,
                    status=429,
                    content_type="application/json",
                    headers={"Retry-After": retry_after},
                )
            state.metrics.record_request(kind, "failed")
            return _error(
                "no healthy replica available", 503, retry_after=5
            )
        try:
            candidate = await state.resilience.request(
                state.session,
                "POST",
                f"{replica.url}{path}",
                endpoint="proxy",
                replica_id=replica.replica_id,
                json=journal.body,
                headers=(
                    {**fwd, DISAGG_HEADER: "prefill"}
                    if plan is not None
                    else fwd
                ),
                timeout=_upstream_timeout(state, streaming=True),
            )
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — pre-stream failure: silently try the next replica
            state.pool.note_unreachable(replica, f"{type(e).__name__}: {e}")
            exclude.add(replica.url)
            if not state.resilience.try_spend_retry():
                state.metrics.record_request(kind, "failed")
                return _error(
                    "replica failed and retry budget exhausted",
                    503,
                    retry_after=1,
                )
            continue
        if candidate.status == 429:
            raw = await asyncio.wait_for(
                candidate.read(), timeout=state.read_timeout
            )
            retry_after = candidate.headers.get("Retry-After", "1")
            try:
                state.pool.note_backoff(replica, float(retry_after))
            except ValueError:
                state.pool.note_backoff(replica, 1.0)
            candidate.release()
            # Backoff, not ``exclude``: a busy replica stays a valid
            # migration target for this stream once it frees up.
            last_429 = (raw, retry_after)
            continue
        if candidate.status != 200:
            raw = await asyncio.wait_for(
                candidate.read(), timeout=state.read_timeout
            )
            status = candidate.status
            ra_header = candidate.headers.get("Retry-After")
            candidate.release()
            if status in (502, 503):
                if state.resilience.enabled and ra_header is not None:
                    # ISSUE 19 satellite: honor the replica's own
                    # Retry-After on 503 for everyone, not just this
                    # request's exclude set.
                    try:
                        state.pool.note_backoff(replica, float(ra_header))
                    except ValueError:
                        pass
                exclude.add(replica.url)
                if not state.resilience.try_spend_retry():
                    state.metrics.record_request(kind, "failed")
                    return web.Response(
                        body=raw,
                        status=status,
                        content_type="application/json",
                        headers={REPLICA_HEADER: replica.replica_id},
                    )
                continue
            state.metrics.record_request(kind, "bad_request")
            return web.Response(
                body=raw,
                status=status,
                content_type="application/json",
                headers={REPLICA_HEADER: replica.replica_id},
            )
        resp = candidate
    journal.served_by = resp.headers.get(REPLICA_HEADER, replica.replica_id)

    headers = {
        "Content-Type": "text/event-stream",
        "Cache-Control": "no-cache",
        REPLICA_HEADER: journal.served_by,
    }
    if span.ctx is not None:
        headers[TRACE_HEADER] = span.ctx[0]
    if state.persist is not None:
        # The reconnect handle (ISSUE 17): with durable state on, the
        # client can finish this stream across a router crash.
        headers[REQUEST_ID_HEADER] = journal.request_id
    response = web.StreamResponse(headers=headers)
    await response.prepare(request)

    async def write(payload: str) -> None:
        await response.write(f"data: {payload}\n\n".encode())

    completed = False
    try:
        try:
            try:
                if plan is not None:
                    # Hand-off path: internal failure handling (prefill
                    # death -> recompute fallback, decode death -> the
                    # migration loop) lives in disagg.py.
                    completed = await disagg.forward_prefill_handoff(
                        state, journal, keys, exclude, replica, resp,
                        fwd, write, client_debug, span,
                    )
                else:
                    completed = await _forward_primary(
                        state, journal, replica, resp, write, client_debug
                    )
            except MigrationNeeded as m:
                completed = await _migrate_loop(
                    state, journal, keys, exclude, replica, m,
                    fwd, write, client_debug, span,
                )
        except StreamAbort:
            completed = False
        finally:
            resp.close()
        if completed:
            state.index.observe(journal.served_by, keys)
            state.metrics.record_request(
                kind,
                "migrated_completed" if journal.migrations else "completed",
            )
        else:
            state.metrics.record_request(kind, "failed")
    except (ConnectionResetError, asyncio.CancelledError):
        logger.info("client disconnected from %s", journal.request_id)
    await response.write_eof()
    return response


async def _migrate_loop(
    state, journal, keys, exclude, victim, mig: MigrationNeeded,
    fwd, write, client_debug, span,
) -> bool:
    """Re-place the unfinished remainder of a live stream until it
    completes, the migration budget runs out, or no replica is left."""
    while True:
        if mig.exclude:
            exclude.add(victim.url)
        if mig.forget:
            # The victim's prefix cache is gone (dead) or going
            # (drain): stop steering siblings toward it.  Transient
            # busy signals keep their affinity history.
            state.index.forget(victim.replica_id)
        if mig.reason != "resume_retry":
            # A budget-granted re-dial of the same idempotent resume
            # is not a new migration hop: it is bounded by the retry
            # budget, not the migration cap.
            journal.migrations += 1
        state.metrics.record_migration(mig.reason)
        get_tracer().event(
            span.ctx,
            "router.migrated",
            reason=mig.reason,
            from_replica=victim.replica_id,
            migrations=journal.migrations,
        )
        if journal.migrations > state.max_migrations:
            await write(
                json.dumps(
                    {
                        "error": "migration budget exhausted "
                        f"(last trigger: {mig.reason})",
                        "code": 502,
                    }
                )
            )
            return False
        if not state.resilience.try_spend_retry():
            # Retry budget exhausted (ISSUE 19): the stream degrades to
            # the existing terminal-503 path instead of re-placing.
            await write(
                json.dumps(
                    {
                        "error": "retry budget exhausted "
                        f"(last trigger: {mig.reason})",
                        "code": 503,
                    }
                )
            )
            return False
        target = _place_or_none(
            state, keys, exclude, span, slo_class=journal.slo_class
        )
        if target is None:
            # Every candidate may just be in Retry-After backoff (busy,
            # not dead): wait out the earliest expiry (capped) and look
            # again before declaring the admitted work lost.
            delay = _soonest_backoff_expiry(state, exclude)
            if delay is not None:
                await asyncio.sleep(delay)
                target = _place_or_none(
                    state, keys, exclude, span, slo_class=journal.slo_class
                )
        if target is None and state.resilience.enabled:
            # Resilient data plane (ISSUE 19): a lossy link can leave
            # every candidate momentarily unreachable or breaker-open;
            # the next health tick (or breaker cooldown) usually heals
            # it.  Re-poll placement briefly before declaring the
            # admitted work lost — bounded, and only with the
            # resilience stack armed.
            deadline = time.monotonic() + 3.0
            while target is None and time.monotonic() < deadline:
                await asyncio.sleep(0.25)
                target = _place_or_none(
                    state, keys, exclude, span, slo_class=journal.slo_class
                )
        if target is None:
            await write(
                json.dumps(
                    {
                        "error": "no healthy replica to migrate to",
                        "code": 503,
                    }
                )
            )
            return False
        logger.warning(
            "migrating %s (%d choice(s) live) %s -> %s after %s",
            journal.request_id,
            len(journal.unfinished()),
            victim.replica_id,
            target.replica_id,
            mig.reason,
        )
        try:
            await _forward_resumed(
                state, journal, target, fwd, write, client_debug
            )
        except MigrationNeeded as m:
            victim, mig = target, m
            continue
        journal.served_by = target.replica_id
        return True


async def _forward_primary(
    state, journal, replica: Replica, resp, write, client_debug
) -> bool:
    """Pump the initial upstream SSE stream to the client, journaling
    every chunk.  Returns True when the stream completed; raises
    MigrationNeeded when the replica died, drained, or shed mid-flight.
    """
    try:
        async for payload in _sse_payloads(resp, state.read_timeout):
            if payload == "[DONE]":
                await write("[DONE]")
                return True
            try:
                obj = json.loads(payload)
            except ValueError:
                continue  # malformed frame: drop, journal stays truthful
            if "error" in obj and not obj.get("choices"):
                # Typed mid-stream error frame (api_server's streaming
                # handlers emit these for drain/shed/death/overload):
                # every 429/503-coded frame is recoverable work — the
                # journal restores whatever was delivered — so migrate;
                # only final (400-class) errors surface to the client.
                reason = str(obj.get("reason") or "")
                code = obj.get("code")
                if reason in ("draining", "overloaded"):
                    raise MigrationNeeded(reason)
                if code == 503:
                    raise MigrationNeeded("dead")
                if code == 429:
                    raise MigrationNeeded(reason or "overloaded")
                await write(payload)
                return False
            if journal.upstream_id is None and obj.get("id"):
                journal.upstream_id = obj["id"]
                journal.model = obj.get("model")
            migrate = False
            for choice in obj.get("choices") or []:
                if choice.get("finish_reason") == "overloaded":
                    # Hot-replica shed (preempt-to-shed): take the
                    # content but not the finish — the remainder
                    # migrates instead of the client eating a partial
                    # "overloaded" result.
                    choice["finish_reason"] = None
                    migrate = True
                kept = dict(choice) if client_debug else None
                journal.observe_choice(choice)
                if kept is not None:
                    choice.update(
                        {
                            k: v
                            for k, v in kept.items()
                            if k.startswith("vdt_")
                        }
                    )
            await write(json.dumps(obj))
            # Progress checkpoint (ISSUE 17), rate-limited inside the
            # WAL; the reconnect protocol reconciles either direction
            # of checkpoint-vs-client lag via X-VDT-Resume-Tokens.
            state.persist_checkpoint(journal)
            if migrate:
                raise MigrationNeeded("overloaded")
    except asyncio.CancelledError:
        raise
    except (MigrationNeeded, ConnectionResetError):
        raise
    except Exception as e:  # noqa: BLE001 — any upstream transport failure = migrate
        state.pool.note_unreachable(replica, f"{type(e).__name__}: {e}")
        raise MigrationNeeded("unreachable") from e
    # EOF without [DONE]: the replica vanished mid-stream.
    raise MigrationNeeded("eof")


def _synth_chunk(journal, choice, delta_text, new_ids, finish, client_debug):
    """A client-facing OpenAI chunk for a resumed continuation, keeping
    the identity (id/model) the client saw in the first chunk."""
    rid = journal.upstream_id or journal.request_id
    model = journal.model or ""
    if journal.kind == "chat":
        delta: dict = {}
        if not choice.role_sent:
            delta["role"] = "assistant"
            delta["content"] = delta_text or ""
        elif delta_text:
            delta["content"] = delta_text
        chunk = {
            "id": rid,
            "object": "chat.completion.chunk",
            "created": int(time.time()),
            "model": model,
            "choices": [
                {
                    "index": choice.index,
                    "delta": delta,
                    "finish_reason": finish,
                }
            ],
        }
    else:
        chunk = {
            "id": rid,
            "object": "text_completion",
            "created": int(time.time()),
            "model": model,
            "choices": [
                {
                    "index": choice.index,
                    "text": delta_text,
                    "finish_reason": finish,
                }
            ],
        }
    if client_debug:
        chunk["choices"][0]["vdt_token_ids"] = list(new_ids or ())
    return chunk


async def _forward_resumed(
    state, journal, target: Replica, fwd, write, client_debug
) -> None:
    """Resume every unfinished choice on ``target`` over
    /internal/resume, converting internal frames back into client
    chunks.  Returns when all choices finish; raises MigrationNeeded if
    the target fails mid-continuation."""
    pending = journal.unfinished()
    if not pending:
        await write("[DONE]")
        return
    # Per-choice pump tasks feed one bounded queue; this coroutine is
    # the only consumer and the client stream's only writer.
    frames: asyncio.Queue = asyncio.Queue(maxsize=64)

    async def pump(choice) -> None:
        try:
            resp = await state.resilience.request(
                state.session,
                "POST",
                f"{target.url}/internal/resume",
                endpoint="resume",
                replica_id=target.replica_id,
                json=journal.resume_payload(choice),
                headers=fwd,
                timeout=_upstream_timeout(state, streaming=True),
            )
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — reported to the consumer as a failure frame
            await frames.put(("failed", choice, str(e)))
            return
        try:
            if resp.status == 429:
                # Busy, not broken: report separately so the consumer
                # backs the target off instead of writing it off.
                await resp.text()
                await frames.put(
                    ("busy", choice, resp.headers.get("Retry-After", "1"))
                )
                return
            if resp.status != 200:
                body = await resp.text()
                await frames.put(
                    ("failed", choice, f"HTTP {resp.status}: {body[:200]}")
                )
                return
            async for payload in _sse_payloads(resp, state.read_timeout):
                if payload == "[DONE]":
                    break
                try:
                    obj = json.loads(payload)
                except ValueError:
                    continue
                await frames.put(("frame", choice, obj))
            await frames.put(("eof", choice, None))
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — reported to the consumer as a failure frame
            await frames.put(("failed", choice, str(e)))
        finally:
            resp.close()

    tasks = [
        asyncio.get_running_loop().create_task(pump(c)) for c in pending
    ]
    open_indices = {c.index for c in pending}
    try:
        while open_indices:
            tag, choice, obj = await asyncio.wait_for(
                frames.get(), timeout=state.read_timeout
            )
            if tag == "busy":
                # Full target (429 + Retry-After): eject it briefly and
                # re-place, but do NOT exclude it for this request or
                # drop its affinity history — its caches are intact.
                try:
                    retry_after = float(obj)
                except (TypeError, ValueError):
                    retry_after = 1.0
                state.pool.note_backoff(target, retry_after)
                raise MigrationNeeded(
                    "target_busy", exclude=False, forget=False
                )
            if tag == "failed":
                # A dropped connection is not a dead replica (ISSUE
                # 19): /internal/resume is idempotent per request id,
                # so while the retry budget grants, re-place with the
                # target still in the candidate set — only a denied
                # budget (or disabled stack) writes the replica off.
                if state.resilience.enabled and (
                    state.resilience.try_spend_retry(target.replica_id)
                ):
                    raise MigrationNeeded(
                        "resume_retry", exclude=False, forget=False
                    )
                raise MigrationNeeded("resume_failed")
            if tag == "eof":
                if choice.index in open_indices:
                    # Stream ended without a finish: target died too.
                    raise MigrationNeeded("eof")
                continue
            if "error" in obj:
                if obj.get("code") in (429, 503):
                    raise MigrationNeeded(
                        str(obj.get("reason") or "dead")
                    )
                # Final (400-class) error: one clean terminal frame,
                # then end the stream — never migrate a deterministic
                # rejection into N duplicate error frames.
                await write(json.dumps(obj))
                raise StreamAbort()
            cum_text = obj.get("text") or ""
            delta_text = cum_text[choice.forwarded_text_len:]
            new_ids = obj.get("token_ids") or []
            finish = obj.get("finish_reason")
            shed = finish == "overloaded"
            if shed:
                # Pressure-shed on the TARGET too: same policy as the
                # primary path — keep the content, drop the finish, and
                # migrate the remainder instead of surfacing a
                # truncated "overloaded" result.
                finish = None
            # Reconnect fast-forward (ISSUE 17): when the client holds
            # MORE tokens than the recovered checkpoint (the crash beat
            # the checkpoint cadence), the resumed replica re-emits the
            # overlap — greedy regeneration makes it bit-identical to
            # what the client already has, so drop those frames while
            # still advancing the journal.  Frame-atomic: a frame
            # carrying more than the remaining overlap forwards whole.
            skip_map = getattr(journal, "resume_skip", None)
            skip = skip_map.get(choice.index, 0) if skip_map else 0
            if (
                skip > 0
                and new_ids
                and skip >= len(new_ids)
                and finish is None
                and not shed
            ):
                skip_map[choice.index] = skip - len(new_ids)
                choice.observe(
                    new_ids, delta_text, None, obj.get("prompt_token_ids")
                )
                state.persist_checkpoint(journal)
                continue
            chunk = _synth_chunk(
                journal, choice, delta_text, new_ids, finish, client_debug
            )
            choice.observe(
                new_ids, delta_text, finish, obj.get("prompt_token_ids")
            )
            if delta_text or new_ids or finish is not None:
                await write(json.dumps(chunk))
                # Only a chunk actually written can have carried the
                # role-bearing first delta.
                choice.role_sent = True
            state.persist_checkpoint(journal)
            if shed:
                raise MigrationNeeded("overloaded")
            if finish is not None:
                open_indices.discard(choice.index)
    finally:
        for t in tasks:
            t.cancel()
    if bool((journal.body.get("stream_options") or {}).get("include_usage")):
        prompt_tokens = sum(
            len(c.prompt_token_ids or ()) for c in journal.choices.values()
        )
        completion_tokens = sum(
            len(c.emitted_token_ids) for c in journal.choices.values()
        )
        await write(
            json.dumps(
                {
                    "id": journal.upstream_id or journal.request_id,
                    "object": (
                        "chat.completion.chunk"
                        if journal.kind == "chat"
                        else "text_completion"
                    ),
                    "created": int(time.time()),
                    "model": journal.model or "",
                    "choices": [],
                    "usage": {
                        "prompt_tokens": prompt_tokens,
                        "completion_tokens": completion_tokens,
                        "total_tokens": prompt_tokens + completion_tokens,
                    },
                }
            )
        )
    await write("[DONE]")


# ---- crash-recovery reconnect (ISSUE 17) ----
def _parse_resume_counts(
    journal, header: str
) -> tuple[dict[int, int], str | None]:
    """Reconcile the client's held position against the recovered
    checkpoint.  Header entries are per-choice ``tokens`` or
    ``tokens:textchars`` in ascending choice-index order.  Client
    behind the checkpoint (the write beat the crash but not the
    socket): REWIND the journal to the client's position — truncate
    the emitted prefix and clear any unseen finish, so the resumed
    replica regenerates (bit-identically) from where the client
    actually stopped.  Client ahead of the checkpoint: return the
    per-choice overlap to skip during forwarding."""
    skip: dict[int, int] = {}
    if not header:
        return skip, None
    entries = [e.strip() for e in header.split(",")]
    indices = sorted(journal.choices)
    if len(entries) > len(indices):
        return skip, "more counts than choices"
    for idx, entry in zip(indices, entries):
        tok_s, _, text_s = entry.partition(":")
        try:
            held_tok = int(tok_s)
            held_text = int(text_s) if text_s else None
        except ValueError:
            return skip, f"invalid count {entry!r}"
        if held_tok < 0 or (held_text is not None and held_text < 0):
            return skip, f"negative count {entry!r}"
        choice = journal.choices[idx]
        have = len(choice.emitted_token_ids)
        if held_tok < have:
            del choice.emitted_token_ids[held_tok:]
            if held_text is not None:
                choice.forwarded_text_len = min(
                    held_text, choice.forwarded_text_len
                )
            choice.finish_reason = None
        elif held_tok > have:
            skip[idx] = held_tok - have
            if held_text is not None and held_text < choice.forwarded_text_len:
                choice.forwarded_text_len = held_text
    return skip, None


async def _proxy_reconnect(
    request: web.Request, state: RouterState, kind: str
) -> web.StreamResponse:
    """Finish a request interrupted by a router crash: claim its
    recovered journal, reconcile positions with what the client holds,
    and replay the remainder onto a healthy replica via the normal
    /internal/resume machinery.  Admitted work finishes bit-identical;
    an id the WAL never admitted (or whose TTL lapsed) gets a clean
    503 — the client retries as a fresh request."""
    resume_id = request.headers.get(RESUME_ID_HEADER, "")
    entry = state.take_recovered(resume_id)
    if entry is None:
        state.metrics.record_request(kind, "rejected")
        return _error(
            f"unknown or expired resume id {resume_id!r}; "
            "retry as a new request",
            503,
            retry_after=1,
        )
    try:
        journal = RouterJournal.from_dict(entry)
    except Exception as e:  # noqa: BLE001 — a checkpoint this incarnation can't parse is unreplayable
        logger.exception("recovered journal %s unusable", resume_id)
        state.persist_done(resume_id)
        state.metrics.record_request(kind, "failed")
        return _error(f"recovered journal unusable: {e}", 503, retry_after=1)
    if journal.kind != kind:
        state.metrics.record_request(kind, "bad_request")
        return _error(
            f"resume id {resume_id!r} belongs to a {journal.kind} request"
        )
    skip, err = _parse_resume_counts(
        journal, request.headers.get(RESUME_TOKENS_HEADER, "")
    )
    if err is not None:
        state.metrics.record_request(kind, "bad_request")
        return _error(f"invalid {RESUME_TOKENS_HEADER}: {err}")
    journal.resume_skip = skip
    # The crash hand-off consumes one migration slot, mirroring any
    # other replica switch the client's stream lives through.
    journal.migrations += 1
    # Re-admit into THIS incarnation's WAL: a second crash mid-replay
    # must leave the request reconnectable again.
    state.persist_checkpoint(journal, force=True)
    text, ids = journal.affinity_source()
    keys = state.index.keys_for(text, ids)
    tracer = get_tracer()
    with tracer.span(
        "router.reconnect",
        trace_root=True,
        kind=kind,
        request_id=journal.request_id,
    ) as span:
        fwd = _forward_headers(request, span.ctx)
        if journal.stream:
            response = await _reconnect_stream(
                request, state, journal, keys, fwd, span
            )
        else:
            # Non-streaming: nothing was delivered before the crash, so
            # completing "from the journal" is whole-request
            # resubmission of the journaled body — greedy regeneration
            # answers bit-identically.
            response = await _proxy_unary(
                request, state, journal, keys, fwd, span
            )
        span.set_attribute("migrations", journal.migrations)
        span.set_attribute("served_by", journal.served_by)
    state.persist_done(journal.request_id)
    return response


async def _reconnect_stream(
    request, state: RouterState, journal, keys, fwd, span
) -> web.StreamResponse:
    """Streaming half of the reconnect: commit the client response,
    re-send any finish the crash swallowed, then drive the standard
    resume/migration machinery until the remainder completes."""
    kind = journal.kind
    exclude: set[str] = set()
    client_debug = request.headers.get(ROUTER_HEADER) == "1"
    headers = {
        "Content-Type": "text/event-stream",
        "Cache-Control": "no-cache",
        REQUEST_ID_HEADER: journal.request_id,
    }
    if journal.served_by:
        headers[REPLICA_HEADER] = journal.served_by
    if span.ctx is not None:
        headers[TRACE_HEADER] = span.ctx[0]
    response = web.StreamResponse(headers=headers)
    await response.prepare(request)

    async def write(payload: str) -> None:
        await response.write(f"data: {payload}\n\n".encode())

    completed = False
    try:
        try:
            # Choices the checkpoint saw finish: the client reconnected,
            # so at minimum the [DONE] (and possibly the finish chunk)
            # was lost — re-state the finish with an empty delta.
            for choice in journal.choices.values():
                if choice.finished:
                    await write(
                        json.dumps(
                            _synth_chunk(
                                journal,
                                choice,
                                "",
                                [],
                                choice.finish_reason,
                                client_debug,
                            )
                        )
                    )
            target = _place_or_none(
                state, keys, exclude, span, slo_class=journal.slo_class
            )
            if target is None:
                delay = _soonest_backoff_expiry(state, exclude)
                if delay is not None:
                    await asyncio.sleep(delay)
                    target = _place_or_none(
                        state, keys, exclude, span,
                        slo_class=journal.slo_class,
                    )
            if target is None:
                await write(
                    json.dumps(
                        {
                            "error": "no healthy replica to resume on",
                            "code": 503,
                        }
                    )
                )
            else:
                try:
                    await _forward_resumed(
                        state, journal, target, fwd, write, client_debug
                    )
                    journal.served_by = target.replica_id
                    completed = True
                except MigrationNeeded as m:
                    completed = await _migrate_loop(
                        state, journal, keys, exclude, target, m,
                        fwd, write, client_debug, span,
                    )
        except StreamAbort:
            completed = False
        if completed:
            state.index.observe(journal.served_by, keys)
            state.metrics.record_request(kind, "migrated_completed")
        else:
            state.metrics.record_request(kind, "failed")
    except (ConnectionResetError, asyncio.CancelledError):
        logger.info(
            "client disconnected from reconnect %s", journal.request_id
        )
    await response.write_eof()
    return response


# ---- route handlers ----
async def completions(request: web.Request) -> web.StreamResponse:
    return await _proxy(request, "completions")


async def chat_completions(request: web.Request) -> web.StreamResponse:
    return await _proxy(request, "chat")


async def health(request: web.Request) -> web.Response:
    """Aggregate health: 200 while at least one replica is routable
    (the router itself is up either way; the body carries the full
    per-replica picture)."""
    state: RouterState = request.app["router_state"]
    state.metrics.update_replicas(state.pool)
    replicas = state.pool.snapshot()
    routable = sum(1 for r in state.pool.replicas if r.routable)
    healthy = sum(
        1 for r in state.pool.replicas if r.state == "healthy"
    )
    body = {
        "status": "ok" if routable else "unavailable",
        "role": "router",
        "replicas_total": len(replicas),
        "replicas_routable": routable,
        "replicas_healthy": healthy,
        "replicas": replicas,
    }
    if routable and healthy < len(replicas):
        body["status"] = "degraded"
    return web.json_response(
        body,
        status=200 if routable else 503,
        headers=None if routable else {"Retry-After": "5"},
    )


async def metrics(request: web.Request) -> web.Response:
    """Aggregated exposition: every replica's /metrics re-labeled with
    ``replica="<id>"``, plus the router's own vdt_router:* families."""
    import aiohttp

    state: RouterState = request.app["router_state"]
    state.metrics.update_replicas(state.pool)
    timeout = aiohttp.ClientTimeout(
        total=10, connect=state.connect_timeout
    )

    async def scrape(replica: Replica) -> tuple[str, str] | None:
        async def fetch() -> tuple[str, str] | None:
            async with await state.resilience.request(
                state.session,
                "GET",
                f"{replica.url}/metrics",
                endpoint="metrics",
                replica_id=replica.replica_id,
                timeout=timeout,
            ) as resp:
                if resp.status != 200:
                    return None
                return (replica.replica_id, await resp.text())

        try:
            # Idempotent read: hedge it (ISSUE 19) — a straggling
            # replica must not stall the whole merged exposition.
            return await state.resilience.hedged(
                "metrics", replica.replica_id, fetch
            )
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — a dead replica just drops out of the aggregate
            return None

    # Refresh the fleet per-class goodput gauges (ISSUE 12) so one
    # scrape of the router carries both the per-replica families and
    # the merged vdt_router:fleet_* series the autoscaler wants.  The
    # /slo sweep runs CONCURRENTLY with the /metrics sweep — the two
    # are independent, and serializing them would double scrape latency
    # behind one slow replica.
    async def fleet_refresh() -> None:
        try:
            await _fleet_slo(state)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — gauges are best-effort
            logger.debug("fleet SLO refresh failed: %s", e)

    parts, _ = await asyncio.wait_for(
        asyncio.gather(
            asyncio.gather(*(scrape(r) for r in state.pool.replicas)),
            fleet_refresh(),
        ),
        timeout=15,
    )
    merged = merge_expositions([p for p in parts if p is not None])
    own = state.metrics.render().decode()
    return web.Response(
        text=merged + own, content_type="text/plain"
    )


async def _fleet_slo(state: RouterState) -> dict:
    """Scrape every routable replica's /slo and fold the per-class
    views into the fleet picture (ISSUE 12).  The merge is pure integer
    addition over log-bucket histograms (engine/slo.py), so the result
    is bit-equal to recomputing from the union of the replicas' raw
    timelines regardless of scrape order.  Also refreshes the
    vdt_router:fleet_* gauges — the exact series the autoscaler
    (ROADMAP item 5) scrapes."""
    import aiohttp

    from vllm_distributed_tpu.engine.slo import merge_class_views

    timeout = aiohttp.ClientTimeout(total=10, connect=state.connect_timeout)

    async def scrape(replica: Replica) -> tuple[str, dict] | None:
        async def fetch() -> tuple[str, dict] | None:
            async with await state.resilience.request(
                state.session,
                "GET",
                f"{replica.url}/slo?timelines=0",
                endpoint="slo",
                replica_id=replica.replica_id,
                timeout=timeout,
            ) as resp:
                if resp.status != 200:
                    return None
                return (replica.replica_id, await resp.json())

        try:
            # Idempotent read: hedged like the /metrics sweep.
            return await state.resilience.hedged(
                "slo", replica.replica_id, fetch
            )
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — a dead replica drops out of the merge
            return None

    parts = await asyncio.wait_for(
        asyncio.gather(*(scrape(r) for r in state.pool.replicas)),
        timeout=15,
    )
    views = [p for p in parts if p is not None]
    classes = merge_class_views([v for _, v in views])
    state.metrics.update_fleet_slo(classes)
    return {
        "classes": classes,
        "replicas_merged": [rid for rid, _ in views],
    }


async def router_slo(request: web.Request) -> web.Response:
    """Fleet per-class SLO/goodput (ISSUE 12): merged histograms,
    attainment counts, goodput ratios, and p50/p99 from the merged
    log-bucket histograms."""
    state: RouterState = request.app["router_state"]
    return web.json_response(await _fleet_slo(state))


async def router_timeline(request: web.Request) -> web.Response:
    """Fleet-wide unified event timeline (ISSUE 20): every replica's
    /debug/events merged with the router's own sentinel log, each
    replica's wall stamps corrected by its probe-derived clock offset.
    The merge is a pure sort with a total-order tiebreak — bit-equal to
    recomputing from any partition of the union (pinned by tests)."""
    import aiohttp

    state: RouterState = request.app["router_state"]
    timeout = aiohttp.ClientTimeout(total=10, connect=state.connect_timeout)

    async def scrape(replica: Replica) -> tuple[str, list] | None:
        async def fetch() -> tuple[str, list] | None:
            async with await state.resilience.request(
                state.session,
                "GET",
                f"{replica.url}/debug/events",
                endpoint="events",
                replica_id=replica.replica_id,
                timeout=timeout,
            ) as resp:
                if resp.status != 200:
                    return None
                body = await resp.json()
                return (replica.replica_id, body.get("events") or [])

        try:
            # Idempotent read: hedged like the /slo and /metrics sweeps.
            return await state.resilience.hedged(
                "events", replica.replica_id, fetch
            )
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — an unreachable replica's slice just drops out
            return None

    parts = await asyncio.wait_for(
        asyncio.gather(*(scrape(r) for r in state.pool.replicas)),
        timeout=15,
    )
    logs: dict[str, list] = {"router": state.sentinel.log.snapshot()}
    offsets: dict[str, float] = {"router": 0.0}
    for part in parts:
        if part is None:
            continue
        rid, events = part
        logs[rid] = events
        rep = state.pool.by_id(rid)
        if rep is not None and rep.clock_rtt >= 0:
            offsets[rid] = rep.clock_offset
    return web.json_response(
        {
            "events": merge_timelines(logs, offsets),
            "merged": sorted(logs),
            "clock_offsets": {
                k: round(v, 6) for k, v in offsets.items()
            },
        }
    )


async def router_alerts(request: web.Request) -> web.Response:
    """Bounded sentinel alert feed (ISSUE 20): burn-rate breaches and
    degraded/unreachable replica detections, newest last.  Every alert
    also entered the timeline as an ``alert_*`` event."""
    state: RouterState = request.app["router_state"]
    return web.json_response(
        {
            "alerts": state.sentinel.alerts_snapshot(),
            "burn": state.sentinel.burn.snapshot(),
            "burn_peak": round(state.sentinel.burn.peak, 3),
            "anomaly_scores": state.sentinel.snapshot()["scores"],
        }
    )


async def router_state(request: web.Request) -> web.Response:
    """Introspection: pool snapshot, tally counters, affinity stats."""
    state: RouterState = request.app["router_state"]
    body = {
        "policy": state.policy,
        "replicas": state.pool.snapshot(),
        "counters": dict(state.metrics.counts),
        "affinity_blocks": {
            r.replica_id: state.index.num_blocks(r.replica_id)
            for r in state.pool.replicas
        },
        "sentinel": state.sentinel.snapshot(),
    }
    if state.resilience.enabled:
        body["resilience"] = state.resilience.snapshot()
    if state.manager is not None:
        body["fleet"] = {
            "target": state.manager.target,
            "ready": state.manager.ready_count(),
            "exhausted": state.manager.exhausted,
        }
    return web.json_response(body)


async def router_fleet(request: web.Request) -> web.Response:
    """Fleet lifecycle introspection (ISSUE 13): managed replica state
    machine, event log (spawn/ready/drain/stop/crash ordering — the
    chaos harness asserts every scale-down drained first), restart
    budget, and autoscaler decisions.  404 on a static router."""
    state: RouterState = request.app["router_state"]
    if state.manager is None:
        return _error("fleet mode is not enabled on this router", 404)
    body = state.manager.snapshot()
    if state.autoscaler is not None:
        body["autoscaler"] = state.autoscaler.snapshot()
    return web.json_response(body)


async def router_scale(request: web.Request) -> web.Response:
    """Manual resize: ``POST /router/scale {"replicas": N}`` (or
    ``?replicas=N``).  Sets the fleet target; the supervisor converges
    — scale-ups health-gate before serving, scale-downs drain before
    the process dies.  404 on a static router."""
    state: RouterState = request.app["router_state"]
    if state.manager is None:
        return _error("fleet mode is not enabled on this router", 404)
    raw = request.query.get("replicas")
    if raw is None:
        try:
            body = await request.json()
            raw = (body or {}).get("replicas")
        except Exception:  # noqa: BLE001 — surfaced as the 400 below
            raw = None
    try:
        n = int(raw)
        if n < 0:
            raise ValueError
    except (TypeError, ValueError):
        return _error(
            "replicas must be a non-negative integer "
            "(?replicas=N or JSON {\"replicas\": N})"
        )
    state.manager.scale_to(n, reason="manual")
    return web.json_response(
        {
            "target": state.manager.target,
            "ready": state.manager.ready_count(),
            "active": len(state.manager.active()),
        }
    )


async def list_models(request: web.Request) -> web.Response:
    state: RouterState = request.app["router_state"]
    import aiohttp

    timeout = aiohttp.ClientTimeout(total=10, connect=state.connect_timeout)
    for replica in state.pool.candidates() or state.pool.replicas:
        try:
            async with await state.resilience.request(
                state.session,
                "GET",
                f"{replica.url}/v1/models",
                endpoint="models",
                replica_id=replica.replica_id,
                timeout=timeout,
            ) as resp:
                if resp.status == 200:
                    return web.json_response(await resp.json())
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — fall through to the next replica
            continue
    return _error("no replica answered /v1/models", 503, retry_after=5)


async def version(request: web.Request) -> web.Response:
    return web.json_response({"version": __version__, "role": "router"})


# ---- app assembly ----
def _config_record(state: RouterState) -> dict:
    """The QoS/placement knob snapshot stored in the WAL's config
    record (ISSUE 17)."""
    return {
        "policy": state.policy,
        "max_migrations": int(state.max_migrations),
        "qos": state.qos.config_fingerprint(),
    }


def _rebuild_from_recovery(state: RouterState) -> None:
    """Warm the control plane from the recovered WAL (ISSUE 17): every
    journaled request re-seeds the affinity mirror for the replica that
    was serving it (its prefix KV is still hot there), and unfinished
    journals go on the TTL shelf awaiting their clients' reconnect."""
    recovered = state.recovered
    if recovered is None:
        return
    current_cfg = _config_record(state)
    if recovered.config is not None and recovered.config != current_cfg:
        # The scheduling state in the WAL was built under different
        # knobs (QoS classes/placement or routing policy changed across
        # the restart).  Recovery still proceeds — membership and
        # journals are knob-independent — but the flip is surfaced.
        logger.warning(
            "router config changed across restart: recovered %s, now %s",
            recovered.config,
            current_cfg,
        )
    if state.persist is not None and not state.persist.closed:
        try:
            state.persist.record_config(current_cfg)
        except Exception:  # noqa: BLE001 — a sick WAL must not block boot
            logger.exception("recording router config failed")
    deadline = time.monotonic() + state.recovery_ttl
    restored = 0
    for rid, jdict in recovered.journals.items():
        try:
            journal = RouterJournal.from_dict(jdict)
        except Exception:  # noqa: BLE001 — one bad checkpoint must not sink the rest
            logger.exception("recovered journal %s unusable; dropping", rid)
            state.persist_done(rid)
            continue
        if journal.served_by:
            text, ids = journal.affinity_source()
            state.index.warm(journal.served_by, text, ids)
        state.recovered_journals[rid] = (deadline, jdict)
        restored += 1
    if restored or recovered.replicas:
        logger.info(
            "router recovery: %d journal(s) awaiting reconnect "
            "(TTL %.0fs), %d replica record(s) processed",
            restored,
            state.recovery_ttl,
            len(recovered.replicas),
        )
    state.recovered = None


async def _on_startup(app: web.Application) -> None:
    import aiohttp

    state: RouterState = app["router_state"]
    state.session = aiohttp.ClientSession()
    # Crash recovery (ISSUE 17) runs before the first probe sweep:
    # re-adopted children must be pool members (in their verifying
    # grace window) by the time probes and the reconcile loop look.
    if (
        state.recovered is not None
        and state.manager is not None
        and state.recovered.replicas
    ):
        state.manager.session = state.session
        state.manager.adopt_recovered(state.recovered.replicas)
    # One synchronous sweep so the first request after boot has health
    # states to place against, then the steady poll loop.
    await state.pool.probe_all(state.session)
    state.pool.start(state.session)
    if state.manager is not None:
        state.manager.start(state.session)
    if state.autoscaler is not None:
        state.autoscaler.start()
    if state.recovered is not None:
        _rebuild_from_recovery(state)
        # SLO baselines: one best-effort fleet scrape so per-class
        # attainment starts from the live pool's view, not from zero.
        try:
            await _fleet_slo(state)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — baselines warm up via the steady scrape anyway
            logger.debug("recovery SLO scrape failed: %s", e)


async def _on_cleanup(app: web.Application) -> None:
    state: RouterState = app["router_state"]
    if state.autoscaler is not None:
        await state.autoscaler.stop()
    if state.manager is not None:
        # Idempotent: if the CLI's SIGTERM handler already drained and
        # reaped the managed fleet, this is a no-op sweep.  Children
        # are ALWAYS reaped here — a router exit never leaks them.
        await state.manager.stop(drain=True)
    await state.pool.stop()
    if state.session is not None:
        await state.session.close()
    if state.persist is not None:
        state.persist.close()


@web.middleware
async def router_auth_middleware(request: web.Request, handler):
    state: RouterState = request.app["router_state"]
    if state.api_key and request.path not in (
        "/health", "/ping", "/version", "/metrics",
    ):
        import hmac

        header = request.headers.get("Authorization", "")
        expect = f"Bearer {state.api_key}".encode()
        got = header.encode("utf-8", "surrogateescape")
        if not hmac.compare_digest(got, expect):
            return _error("invalid or missing API key", 401)
    return await handler(request)


def build_router_app(state: RouterState) -> web.Application:
    app = web.Application(
        client_max_size=64 * 2**20,
        middlewares=[router_auth_middleware],
    )
    app["router_state"] = state
    app.router.add_get("/health", health)
    app.router.add_get("/ping", health)
    app.router.add_get("/version", version)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/router/state", router_state)
    app.router.add_get("/router/slo", router_slo)
    app.router.add_get("/router/timeline", router_timeline)
    app.router.add_get("/router/alerts", router_alerts)
    app.router.add_get("/router/fleet", router_fleet)
    app.router.add_post("/router/scale", router_scale)
    app.router.add_get("/v1/models", list_models)
    app.router.add_post("/v1/completions", completions)
    app.router.add_post("/v1/chat/completions", chat_completions)
    app.on_startup.append(_on_startup)
    app.on_cleanup.append(_on_cleanup)
    return app
