"""Router-side Prometheus instruments + replica exposition merging.

Two halves:

- ``RouterMetrics``: the router's own counters/gauges (placements,
  migrations, outcomes, per-replica liveness), prefixed ``vdt_router:``
  so they can never collide with the engines' ``vllm:`` families.
  Degrades to no-op without prometheus_client, like metrics.py.
- ``merge_expositions``: the aggregated ``/metrics`` body — every
  replica's exposition re-labeled with ``replica="<id>"`` and grouped
  into one valid text-format document (one HELP/TYPE per family, all
  replicas' samples under it), so one scrape of the router sees the
  whole deployment with per-replica attribution.

Plain counters are mirrored in ``RouterMetrics.counts`` regardless of
prometheus availability — tests and the ``/router/state`` debug endpoint
read those.
"""

from __future__ import annotations

from collections import Counter as _TallyCounter


def _inject_label(sample_line: str, key: str, value: str) -> str:
    """Add one label to a Prometheus text-format sample line."""
    esc = value.replace("\\", r"\\").replace('"', r"\"")
    if "{" in sample_line:
        idx = sample_line.rindex("}")
        return (
            f'{sample_line[:idx]},{key}="{esc}"'
            f"}}{sample_line[idx + 1:]}"
        )
    name, _, rest = sample_line.partition(" ")
    return f'{name}{{{key}="{esc}"}} {rest}'


def merge_expositions(parts: list[tuple[str, str]]) -> str:
    """Merge ``[(replica_id, exposition_text), ...]`` into one valid
    exposition: families deduplicated (first replica's HELP/TYPE wins),
    every sample tagged ``replica="<id>"``."""
    order: list[str] = []
    families: dict[str, dict] = {}
    for replica_id, text in parts:
        current: dict | None = None
        for line in text.splitlines():
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                name = line.split(" ", 3)[2]
                fam = families.get(name)
                if fam is None:
                    fam = families[name] = {
                        "help": None, "type": None, "samples": [],
                    }
                    order.append(name)
                kind = "help" if line.startswith("# HELP ") else "type"
                if fam[kind] is None:
                    fam[kind] = line
                current = fam
            elif line and not line.startswith("#"):
                if current is None:
                    continue
                current["samples"].append(
                    _inject_label(line, "replica", replica_id)
                )
    out: list[str] = []
    for name in order:
        fam = families[name]
        if fam["help"]:
            out.append(fam["help"])
        if fam["type"]:
            out.append(fam["type"])
        out.extend(fam["samples"])
    return "\n".join(out) + ("\n" if out else "")


class RouterMetrics:
    """Router-process instruments; every record call also tallies into
    ``counts`` so behavior is observable without prometheus_client."""

    def __init__(self, enabled: bool = True) -> None:
        self.counts: _TallyCounter = _TallyCounter()
        self.enabled = enabled
        self.registry = None
        if not enabled:
            return
        try:
            from prometheus_client import (
                CollectorRegistry,
                Counter,
                Gauge,
            )
        except ImportError:
            self.enabled = False
            return
        self.registry = CollectorRegistry()
        self._requests = Counter(
            "vdt_router:requests",
            "Proxied requests by kind and outcome (completed | "
            "migrated_completed | rejected | failed | bad_request)",
            ["kind", "outcome"],
            registry=self.registry,
        )
        self._migrations = Counter(
            "vdt_router:migrations",
            "Live request migrations by trigger (unreachable | eof | "
            "draining | overloaded | dead | resume_failed | error)",
            ["reason"],
            registry=self.registry,
        )
        # ---- disaggregated prefill/decode (ISSUE 15) ----
        # Planned KV hand-offs are the HAPPY path of role separation,
        # deliberately distinct from vdt_router:migrations (failure
        # recovery) — a hand-off never burns the migration budget.
        self._handoffs = Counter(
            "vdt_router:handoffs",
            "Prefill->decode hand-offs by outcome (planned = KV pages "
            "streamed and adopted; fallback = transfer failed/skipped, "
            "continued via recompute-resume on the decode pool; "
            "finished_at_prefill = the request legitimately finished "
            "on its first token)",
            ["outcome"],
            registry=self.registry,
        )
        # ---- resilient data plane (ISSUE 19) ----
        self._retries = Counter(
            "vdt_router:retries_total",
            "Retry-budget decisions (granted | denied).  Denied retries "
            "degrade to the existing 503/migration outcomes instead of "
            "amplifying load",
            ["outcome"],
            registry=self.registry,
        )
        self._hedges = Counter(
            "vdt_router:hedges_total",
            "Hedged idempotent reads by outcome (primary_won | "
            "hedge_won | denied = retry budget refused the hedge | "
            "both_failed)",
            ["outcome"],
            registry=self.registry,
        )
        self._breaker_state = Gauge(
            "vdt_router:breaker_state",
            "Per-replica circuit breaker state (0 closed, 1 half-open, "
            "2 open).  Open replicas are skipped by placement",
            ["replica_id"],
            registry=self.registry,
        )
        self._breaker_rejections = Counter(
            "vdt_router:breaker_rejections_total",
            "Outbound calls rejected by an open circuit breaker before "
            "any I/O (placement normally skips open replicas; these are "
            "the residual races plus breaker-filtered empty placements)",
            registry=self.registry,
        )
        self._kv_resumes = Counter(
            "vdt_router:kv_transfer_resumes",
            "Chunk-level resumes inside prefill->decode KV transfers: "
            "a dropped connection re-pulled only the missing chunks "
            "instead of aborting the hand-off to recompute",
            registry=self.registry,
        )
        self._placements = Counter(
            "vdt_router:placements",
            "Placement decisions by deciding policy (affinity | "
            "least_loaded | round_robin)",
            ["policy"],
            registry=self.registry,
        )
        self._replica_up = Gauge(
            "vdt_router:replica_up",
            "1 while the replica answers /health with 200",
            ["replica_id"],
            registry=self.registry,
        )
        self._replica_waiting = Gauge(
            "vdt_router:replica_waiting_requests",
            "Last-scraped vllm:num_requests_waiting per replica",
            ["replica_id"],
            registry=self.registry,
        )
        # ---- elastic fleet (ISSUE 13) ----
        self._fleet_size = Gauge(
            "vdt_router:fleet_size",
            "Managed replicas currently serving (health-gated ready)",
            registry=self.registry,
        )
        self._fleet_target = Gauge(
            "vdt_router:fleet_target",
            "Replica-count target the fleet supervisor converges to",
            registry=self.registry,
        )
        self._fleet_scale_events = Counter(
            "vdt_router:fleet_scale_events",
            "Fleet resizes by direction (up | down) and trigger "
            "(manual | autoscale:<reason>)",
            ["direction", "reason"],
            registry=self.registry,
        )
        self._fleet_restarts = Counter(
            "vdt_router:fleet_replica_restarts",
            "Managed-replica deaths by cause (crash | warmup_failed)",
            ["reason"],
            registry=self.registry,
        )
        # ---- fleet SLO/goodput (ISSUE 12): per-class gauges refreshed
        # from the associative merge of replica /slo views — the exact
        # series the autoscaler (ROADMAP item 5) scrapes.  slo_class is
        # a bounded label (sanitized + capped replica-side, VDT009).
        self._fleet_requests = Gauge(
            "vdt_router:fleet_slo_requests",
            "Fleet finished requests per SLO class (merged)",
            ["slo_class"],
            registry=self.registry,
        )
        self._fleet_goodput = Gauge(
            "vdt_router:fleet_goodput_requests",
            "Fleet goodput per SLO class: requests completed within "
            "both TTFT and ITL targets (merged)",
            ["slo_class"],
            registry=self.registry,
        )
        self._fleet_goodput_ratio = Gauge(
            "vdt_router:fleet_goodput_ratio",
            "Fleet goodput / finished requests per SLO class",
            ["slo_class"],
            registry=self.registry,
        )
        self._fleet_ttft_p99 = Gauge(
            "vdt_router:fleet_ttft_p99_ms",
            "Fleet p99 TTFT per SLO class from the merged log-bucket "
            "histograms (bucket-representative value)",
            ["slo_class"],
            registry=self.registry,
        )
        self._fleet_itl_p99 = Gauge(
            "vdt_router:fleet_itl_p99_ms",
            "Fleet p99 inter-token latency per SLO class (merged)",
            ["slo_class"],
            registry=self.registry,
        )
        # ---- fleet sentinel (ISSUE 20) ----
        self._burn_rate = Gauge(
            "vdt_router:fleet_slo_burn_rate",
            "Fleet SLO error-budget burn rate per class and window "
            "(1.0 = burning exactly at the sustainable rate; an alert "
            "fires when every window breaches the threshold at once)",
            ["slo_class", "window"],
            registry=self.registry,
        )
        self._burn_peak = Gauge(
            "vdt_router:fleet_slo_burn_rate_peak",
            "High-water fleet burn rate over any class/window since "
            "router start (the bench serve summary column)",
            registry=self.registry,
        )
        self._anomaly_score = Gauge(
            "vdt_router:replica_anomaly_score",
            "Robust z-score (median/MAD over the live pool) of one "
            "replica's condition signal; |z| past the threshold marks "
            "the replica degraded",
            ["replica_id", "signal"],
            registry=self.registry,
        )
        self._alerts = Counter(
            "vdt_router:alerts_total",
            "Sentinel alerts raised, by kind (slo_burn | "
            "replica_degraded | replica_unreachable)",
            ["kind"],
            registry=self.registry,
        )

    def record_request(self, kind: str, outcome: str) -> None:
        self.counts[f"requests.{kind}.{outcome}"] += 1
        if self.enabled:
            self._requests.labels(kind=kind, outcome=outcome).inc()

    def record_migration(self, reason: str) -> None:
        self.counts[f"migrations.{reason}"] += 1
        if self.enabled:
            self._migrations.labels(reason=reason).inc()

    def record_placement(self, policy: str) -> None:
        self.counts[f"placements.{policy}"] += 1
        if self.enabled:
            self._placements.labels(policy=policy).inc()

    def record_handoff(self, outcome: str) -> None:
        self.counts[f"handoffs.{outcome}"] += 1
        if self.enabled:
            self._handoffs.labels(outcome=outcome).inc()

    # ---- resilient data plane (ISSUE 19) ----
    def record_retry(self, outcome: str) -> None:
        self.counts[f"retries.{outcome}"] += 1
        if self.enabled:
            self._retries.labels(outcome=outcome).inc()

    def record_hedge(self, outcome: str) -> None:
        self.counts[f"hedges.{outcome}"] += 1
        if self.enabled:
            self._hedges.labels(outcome=outcome).inc()

    def set_breaker_state(self, replica_id: str, value: int) -> None:
        self.counts[f"breaker.state.{replica_id}"] = value
        if self.enabled:
            self._breaker_state.labels(replica_id=replica_id).set(value)

    def record_breaker_rejection(self) -> None:
        self.counts["breaker.rejections"] += 1
        if self.enabled:
            self._breaker_rejections.inc()

    def record_kv_resume(self) -> None:
        self.counts["kv.transfer_resumes"] += 1
        if self.enabled:
            self._kv_resumes.inc()

    # ---- elastic fleet (ISSUE 13) ----
    def record_scale(self, direction: str, reason: str) -> None:
        self.counts[f"fleet.scale.{direction}"] += 1
        if self.enabled:
            self._fleet_scale_events.labels(
                direction=direction, reason=reason
            ).inc()

    def record_fleet_restart(self, reason: str) -> None:
        self.counts[f"fleet.restarts.{reason}"] += 1
        if self.enabled:
            self._fleet_restarts.labels(reason=reason).inc()

    def update_fleet(self, manager) -> None:
        self.counts["fleet.size"] = manager.ready_count()
        self.counts["fleet.target"] = manager.target
        if self.enabled:
            self._fleet_size.set(manager.ready_count())
            self._fleet_target.set(manager.target)

    def forget_replica(self, replica_id: str) -> None:
        """Membership hygiene: drop the per-replica series when a
        replica leaves the pool, so a scaled-down id never lingers in
        the router's own exposition (the merged replica expositions
        drop out automatically — they iterate the live pool)."""
        self.counts.pop(f"breaker.state.{replica_id}", None)
        for key in [
            k
            for k in self.counts
            if k.startswith(f"anomaly.{replica_id}.")
        ]:
            self.counts.pop(key, None)
        if not self.enabled:
            return
        for gauge in (
            self._replica_up,
            self._replica_waiting,
            self._breaker_state,
        ):
            try:
                gauge.remove(replica_id)
            except KeyError:
                pass
        from vllm_distributed_tpu.router.sentinel import SIGNALS

        for signal in SIGNALS:
            try:
                self._anomaly_score.remove(replica_id, signal)
            except KeyError:
                pass

    def update_fleet_slo(self, classes: dict) -> None:
        """Refresh the fleet per-class gauges from one merged view
        (engine/slo.py merge_class_views output).  Mirrored into
        ``counts`` like everything else so tests and /router/state can
        read it without prometheus_client."""
        for cls, d in classes.items():
            self.counts[f"fleet.{cls}.requests"] = d.get("requests", 0)
            self.counts[f"fleet.{cls}.goodput"] = d.get("goodput", 0)
            if not self.enabled:
                continue
            self._fleet_requests.labels(slo_class=cls).set(
                d.get("requests", 0)
            )
            self._fleet_goodput.labels(slo_class=cls).set(
                d.get("goodput", 0)
            )
            ratio = d.get("goodput_ratio")
            if ratio is not None:
                self._fleet_goodput_ratio.labels(slo_class=cls).set(ratio)
            for gauge, key in (
                (self._fleet_ttft_p99, "ttft_p99_ms"),
                (self._fleet_itl_p99, "itl_p99_ms"),
            ):
                value = d.get(key)
                if value is not None:
                    gauge.labels(slo_class=cls).set(value)

    # ---- fleet sentinel (ISSUE 20) ----
    def record_alert(self, kind: str) -> None:
        self.counts[f"alerts.{kind}"] += 1
        if self.enabled:
            self._alerts.labels(kind=kind).inc()

    def set_anomaly_score(
        self, replica_id: str, signal: str, score: float
    ) -> None:
        self.counts[f"anomaly.{replica_id}.{signal}"] = score
        if self.enabled:
            self._anomaly_score.labels(
                replica_id=replica_id, signal=signal
            ).set(score)

    def update_burn(self, burn, now: float | None = None) -> None:
        """Refresh the per-class/window burn gauges and the high-water
        peak from one BurnRateTracker."""
        for cls, rates in burn.snapshot(now).items():
            for window, value in rates.items():
                self.counts[f"burn.{cls}.{window}"] = value
                if self.enabled:
                    self._burn_rate.labels(
                        slo_class=cls, window=window
                    ).set(value)
        self.counts["burn.peak"] = burn.peak
        if self.enabled:
            self._burn_peak.set(burn.peak)

    def update_replicas(self, pool) -> None:
        if not self.enabled:
            return
        for r in pool.replicas:
            self._replica_up.labels(replica_id=r.replica_id).set(
                1 if r.state == "healthy" else 0
            )
            self._replica_waiting.labels(replica_id=r.replica_id).set(
                r.waiting
            )

    def render(self) -> bytes:
        if self.registry is None:
            return b"# router metrics disabled (no prometheus_client)\n"
        from prometheus_client import generate_latest

        return generate_latest(self.registry)
