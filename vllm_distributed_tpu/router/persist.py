"""Durable router control-plane state (ISSUE 17 tentpole).

A bounded write-ahead log under ``VDT_ROUTER_STATE_DIR`` recording the
three things a restarted router cannot rebuild from thin air:

* **fleet membership** — replica id/port/role/pid and the launch
  template, so the new router can re-adopt still-running supervised
  children instead of leaking or double-spawning them;
* **in-flight request journals** — per-request :class:`RouterJournal`
  checkpoints (prompt ids + emitted tokens), so interrupted
  generations finish bit-identically when their clients reconnect;
* **QoS/placement config and fleet scale targets** — the knob snapshot
  the scheduling state was built under (so recovery can detect a config
  flip) and the operator's last runtime scale intent (so a crash does
  not undo a scale-up by reverting to the CLI default).

Format: one JSONL record per line, each line ``<crc32-hex8> <json>\n``
with the checksum taken over the JSON bytes.  Recovery replays
segments in sequence order and stops at the first record that fails
the checksum or JSON parse — a torn tail (router killed mid-write) is
truncated, never loaded.  The log stays bounded by compaction: when
the live segment passes ``VDT_ROUTER_STATE_SEGMENT_BYTES`` the current
state (live membership + config + live journals) is rewritten into a
fresh segment via write-to-temp / fsync / atomic rename, and old
segments are deleted.

Durability is tiered: membership records fsync immediately (losing one
means leaking a child), journal checkpoints fsync at a bounded cadence
(``VDT_ROUTER_STATE_FSYNC_INTERVAL_SECONDS``) — a crash can cost at
most that window of token progress, which the resumed stream simply
re-emits and the reconnecting client trims.

Everything here is synchronous file I/O on the router's event loop;
every operation is a bounded number of writes (no waits, no retries —
a failing disk surfaces as an exception, not a hang).
"""

from __future__ import annotations

import json
import os
import time
import zlib
from dataclasses import dataclass, field

from vllm_distributed_tpu import envs
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.router.journal import RouterJournal

logger = init_logger(__name__)

WAL_VERSION = 1
_SEG_PREFIX = "wal."
_SEG_SUFFIX = ".log"


# ---------------------------------------------------------------------------
# record codec — pure helpers, used directly by the torn-write tests
# ---------------------------------------------------------------------------


def encode_record(rec: dict) -> bytes:
    """``<crc32 of payload, 8 hex chars> <compact json>\n``."""
    payload = json.dumps(
        rec, separators=(",", ":"), sort_keys=True
    ).encode("utf-8")
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    return b"%08x " % crc + payload + b"\n"


def decode_segment(data: bytes) -> list[dict]:
    """Decode a WAL segment, stopping at the first torn or corrupt
    record.  A trailing line without a newline is by definition torn
    (the writer appends the newline in the same write), and any line
    whose checksum or JSON fails is treated as the start of garbage —
    nothing after it is trusted."""
    records: list[dict] = []
    start = 0
    n = len(data)
    while start < n:
        nl = data.find(b"\n", start)
        if nl < 0:
            break  # torn tail: no newline ever made it to disk
        line = data[start:nl]
        start = nl + 1
        if len(line) < 10 or line[8:9] != b" ":
            break
        try:
            crc = int(line[:8], 16)
        except ValueError:
            break
        payload = line[9:]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            break
        try:
            rec = json.loads(payload)
        except ValueError:
            break
        if not isinstance(rec, dict):
            break
        records.append(rec)
    return records


def _segment_seq(name: str) -> int | None:
    if not (name.startswith(_SEG_PREFIX) and name.endswith(_SEG_SUFFIX)):
        return None
    mid = name[len(_SEG_PREFIX) : -len(_SEG_SUFFIX)]
    try:
        return int(mid)
    except ValueError:
        return None


def _segment_name(seq: int) -> str:
    return f"{_SEG_PREFIX}{seq:08d}{_SEG_SUFFIX}"


def _list_segments(state_dir: str) -> list[tuple[int, str]]:
    out: list[tuple[int, str]] = []
    try:
        names = os.listdir(state_dir)
    except FileNotFoundError:
        return out
    for name in names:
        seq = _segment_seq(name)
        if seq is not None:
            out.append((seq, os.path.join(state_dir, name)))
    out.sort()
    return out


def _fsync_dir(path: str) -> None:
    """Make a rename/create in ``path`` durable.  Best-effort: some
    filesystems refuse directory fsync; the segment data itself is
    already fsync'd, so the worst case is replaying the prior segment."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


# ---------------------------------------------------------------------------
# recovered state
# ---------------------------------------------------------------------------


@dataclass
class RecoveredState:
    """What replaying the WAL yields: the mirrors a restarted router
    rebuilds its control plane from."""

    replicas: dict[str, dict] = field(default_factory=dict)
    journals: dict[str, dict] = field(default_factory=dict)
    config: dict | None = None
    # Fleet scale targets at crash time — the operator's last runtime
    # intent.  A restart must not undo a scale-up by reverting to the
    # CLI --fleet-size default.
    fleet_target: int | None = None
    fleet_role_targets: dict[str, int] | None = None

    @property
    def empty(self) -> bool:
        return not self.replicas and not self.journals


def _replay(records: list[dict], state: RecoveredState) -> None:
    for rec in records:
        t = rec.get("t")
        if t == "replica":
            rid = rec.get("id")
            if isinstance(rid, str) and rid:
                state.replicas[rid] = {
                    k: rec.get(k)
                    for k in ("id", "port", "pid", "role", "template")
                }
        elif t == "replica_gone":
            state.replicas.pop(rec.get("id"), None)
        elif t == "journal":
            rid = rec.get("rid")
            j = rec.get("j")
            if isinstance(rid, str) and isinstance(j, dict):
                state.journals[rid] = j  # latest checkpoint wins
        elif t == "journal_done":
            state.journals.pop(rec.get("rid"), None)
        elif t == "config":
            cfg = rec.get("cfg")
            if isinstance(cfg, dict):
                state.config = cfg
        elif t == "fleet":
            target = rec.get("target")
            if isinstance(target, int) and target >= 0:
                state.fleet_target = target
            roles = rec.get("roles")
            if isinstance(roles, dict):
                state.fleet_role_targets = {
                    str(k): int(v)
                    for k, v in roles.items()
                    if isinstance(v, int) and v >= 0
                }
        # unknown types skipped: forward-compatible replay


def load_state(state_dir: str) -> RecoveredState:
    """Read-only replay of every segment in sequence order.  Safe to
    call on a live or dead router's state dir (the chaos harness reads
    the WAL of a SIGKILLed router this way)."""
    state = RecoveredState()
    for _seq, path in _list_segments(state_dir):
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError as e:
            logger.warning("router WAL: cannot read %s: %s", path, e)
            continue
        _replay(decode_segment(data), state)
    return state


# ---------------------------------------------------------------------------
# writer
# ---------------------------------------------------------------------------


class RouterStateLog:
    """Append-side of the WAL.  One instance per router process;
    ``open()`` replays any prior state, compacts it into a fresh
    segment, and returns it — callers then feed membership / journal /
    config events as they happen."""

    def __init__(
        self,
        state_dir: str,
        *,
        segment_bytes: int | None = None,
        fsync_interval: float | None = None,
        ckpt_interval: float | None = None,
        clock=time.monotonic,
    ) -> None:
        self.state_dir = state_dir
        self.segment_bytes = int(
            segment_bytes
            if segment_bytes is not None
            else envs.VDT_ROUTER_STATE_SEGMENT_BYTES
        )
        self.fsync_interval = float(
            fsync_interval
            if fsync_interval is not None
            else envs.VDT_ROUTER_STATE_FSYNC_INTERVAL_SECONDS
        )
        self.ckpt_interval = float(
            ckpt_interval
            if ckpt_interval is not None
            else envs.VDT_ROUTER_STATE_CKPT_INTERVAL_SECONDS
        )
        self._clock = clock
        self.sentinel = None  # RouterSentinel (wired by app.attach_persist)
        self._f = None
        self._seq = 0
        self._size = 0
        self._last_fsync = 0.0
        self._dirty = False
        # In-memory mirrors of live state, for compaction snapshots.
        self._replicas: dict[str, dict] = {}
        self._journals: dict[str, dict] = {}
        self._config: dict | None = None
        self._fleet: dict | None = None
        self._last_ckpt: dict[str, float] = {}

    # ---- lifecycle ----
    def open(self) -> RecoveredState:
        os.makedirs(self.state_dir, exist_ok=True)
        segments = _list_segments(self.state_dir)
        recovered = load_state(self.state_dir)
        self._replicas = dict(recovered.replicas)
        self._journals = dict(recovered.journals)
        self._config = recovered.config
        if recovered.fleet_target is not None:
            self._fleet = {
                "target": recovered.fleet_target,
                "roles": dict(recovered.fleet_role_targets or {}),
            }
        self._seq = (segments[-1][0] + 1) if segments else 0
        # Start this incarnation on a freshly-compacted segment so a
        # crash loop can't accrete segments.
        self._write_snapshot_segment(self._seq)
        for _seq, path in segments:
            try:
                os.remove(path)
            except OSError:
                pass
        path = os.path.join(self.state_dir, _segment_name(self._seq))
        self._f = open(path, "ab")
        self._size = os.path.getsize(path)
        self._last_fsync = self._clock()
        return recovered

    def close(self) -> None:
        if self._f is None:
            return
        try:
            self._f.flush()
            os.fsync(self._f.fileno())
        except OSError:
            pass
        self._f.close()
        self._f = None

    @property
    def closed(self) -> bool:
        return self._f is None

    # ---- event surface ----
    def record_replica(
        self,
        replica_id: str,
        *,
        port: int,
        pid: int | None,
        role: str = "mixed",
        template: str | None = None,
    ) -> None:
        rec = {
            "t": "replica",
            "id": replica_id,
            "port": port,
            "pid": pid,
            "role": role,
            "template": template,
        }
        self._replicas[replica_id] = {
            k: rec[k] for k in ("id", "port", "pid", "role", "template")
        }
        self._append(rec, durable=True)

    def record_replica_gone(self, replica_id: str) -> None:
        self._replicas.pop(replica_id, None)
        self._append({"t": "replica_gone", "id": replica_id}, durable=True)

    def record_config(self, cfg: dict) -> None:
        self._config = dict(cfg)
        self._append({"t": "config", "cfg": self._config}, durable=True)

    def record_fleet_targets(
        self, target: int, role_targets: dict[str, int] | None = None
    ) -> None:
        """Durably record the fleet scale targets — control-plane state
        a restart must honor (a crash must not undo a scale-up)."""
        self._fleet = {
            "target": int(target),
            "roles": {k: int(v) for k, v in (role_targets or {}).items()},
        }
        self._append({"t": "fleet", **self._fleet}, durable=True)

    def checkpoint_journal(
        self, journal: RouterJournal, *, force: bool = False
    ) -> bool:
        """Record the request's cumulative progress.  Rate-limited per
        request (full-journal records per token would make the WAL
        quadratic in stream length); ``force`` bypasses the limiter for
        admission and terminal checkpoints."""
        rid = journal.request_id
        now = self._clock()
        if not force:
            last = self._last_ckpt.get(rid)
            if last is not None and now - last < self.ckpt_interval:
                return False
        self._last_ckpt[rid] = now
        j = journal.to_dict()
        self._journals[rid] = j
        self._append({"t": "journal", "rid": rid, "j": j}, durable=force)
        return True

    def journal_done(self, request_id: str) -> None:
        if request_id not in self._journals:
            return
        self._journals.pop(request_id, None)
        self._last_ckpt.pop(request_id, None)
        self._append({"t": "journal_done", "rid": request_id})

    # ---- write path ----
    def _append(self, rec: dict, durable: bool = False) -> None:
        if self._f is None:
            return
        buf = encode_record(rec)
        try:
            self._f.write(buf)
            self._f.flush()
        except OSError as e:
            logger.error("router WAL: append failed: %s", e)
            return
        self._size += len(buf)
        self._dirty = True
        now = self._clock()
        if durable or now - self._last_fsync >= self.fsync_interval:
            self._fsync(now)
        if self._size > self.segment_bytes:
            self._rotate()

    def _fsync(self, now: float) -> None:
        if self._f is None or not self._dirty:
            return
        try:
            os.fsync(self._f.fileno())
        except OSError as e:
            logger.error("router WAL: fsync failed: %s", e)
            return
        self._last_fsync = now
        self._dirty = False

    def _snapshot_records(self) -> list[dict]:
        recs: list[dict] = [{"t": "meta", "version": WAL_VERSION}]
        for r in self._replicas.values():
            recs.append({"t": "replica", **r})
        if self._config is not None:
            recs.append({"t": "config", "cfg": self._config})
        if self._fleet is not None:
            recs.append({"t": "fleet", **self._fleet})
        for rid, j in self._journals.items():
            recs.append({"t": "journal", "rid": rid, "j": j})
        return recs

    def _write_snapshot_segment(self, seq: int) -> None:
        """Compacted snapshot → ``.tmp`` → fsync → atomic rename.  A
        crash at any point leaves either the old segments or a complete
        new one, never a half-written replacement."""
        path = os.path.join(self.state_dir, _segment_name(seq))
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            for rec in self._snapshot_records():
                f.write(encode_record(rec))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(self.state_dir)

    def _rotate(self) -> None:
        old_seq, new_seq = self._seq, self._seq + 1
        try:
            self._write_snapshot_segment(new_seq)
        except OSError as e:
            # Keep appending to the oversized segment rather than lose
            # durability — rotation retries on the next append.
            logger.error("router WAL: rotation failed: %s", e)
            return
        if self._f is not None:
            self._f.close()
        old_path = os.path.join(self.state_dir, _segment_name(old_seq))
        try:
            os.remove(old_path)
        except OSError:
            pass
        new_path = os.path.join(self.state_dir, _segment_name(new_seq))
        self._f = open(new_path, "ab")
        self._seq = new_seq
        self._size = os.path.getsize(new_path)
        self._last_fsync = self._clock()
        self._dirty = False
        if self.sentinel is not None:
            try:
                self.sentinel.emit(
                    "wal_compaction",
                    from_seq=old_seq,
                    to_seq=new_seq,
                    snapshot_bytes=self._size,
                    replicas=len(self._replicas),
                    journals=len(self._journals),
                )
            except Exception:  # noqa: BLE001 — observability must not block the WAL
                logger.exception("sentinel wal_compaction event failed")
