"""Router-side QoS: per-class placement, goodput autoscale signal,
prefill-demand tracking (ISSUE 16).

The engine half (engine/qos.py) orders work *within* one replica; this
module orders work *across* the fleet:

- :class:`QosRouterPolicy` restricts which replicas a class may land
  on, composed in front of the PR 14 affinity walk (the policy filters
  the candidate set, affinity/least-loaded picks within it);
- :class:`GoodputTracker` turns the cumulative per-class counters of
  the ``/router/slo`` fleet merge into windowed goodput ratios and
  reports the worst class sagging below ``VDT_AUTOSCALE_GOODPUT_FLOOR``
  — DistServe's argument (Zhong et al. 2024) that scaling should chase
  goodput, not queue depth;
- :class:`PrefillDemand` keeps an EWMA of long-prompt arrival rate so
  the autoscaler can size the PR 15 disaggregated prefill pool to its
  phase (Splitwise, Patel et al. 2024) instead of a static count.

Everything is default-off: ``VDT_QOS_PLACEMENT=shared`` and an empty
class registry make ``filter`` a passthrough, and a zero goodput floor
/ prefill rate disable the autoscale signals.
"""

from __future__ import annotations

import math

from vllm_distributed_tpu.engine.qos import QosRegistry
from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)

PLACEMENT_MODES = ("shared", "segregate", "reserve")


class QosRouterPolicy:
    """Per-class replica placement.

    Modes (``VDT_QOS_PLACEMENT``):

    - ``shared``: no restriction — every class sees every replica
      (seed behaviour).
    - ``segregate``: replicas are deterministically partitioned into
      disjoint per-class sets, sized by admission share (zero-share
      classes split the leftover).  A batch burst cannot queue behind
      interactive traffic at all, at the cost of per-class capacity.
    - ``reserve``: the highest-priority class may use every replica;
      lower classes avoid its reserved headroom slice (the tail of the
      replica list, ``ceil(top_share * n)`` replicas) while any
      alternative exists.  Work-conserving flavour of segregation.

    Both restricted modes fall back to the full candidate set whenever
    the restriction would leave a class with zero routable replicas —
    placement never fails closed just because the fleet shrank.
    """

    def __init__(
        self, registry: QosRegistry, placement: str = "shared"
    ) -> None:
        if placement not in PLACEMENT_MODES:
            raise ValueError(
                f"VDT_QOS_PLACEMENT {placement!r} is not one of "
                f"{PLACEMENT_MODES}"
            )
        self.registry = registry
        self.placement = placement

    @classmethod
    def from_env(cls) -> QosRouterPolicy:
        from vllm_distributed_tpu import envs

        return cls(
            QosRegistry.parse(envs.VDT_QOS_CLASSES),
            envs.VDT_QOS_PLACEMENT,
        )

    @property
    def active(self) -> bool:
        return self.registry.enabled and self.placement != "shared"

    def config_fingerprint(self) -> dict:
        """Stable summary of the placement-relevant config, recorded in
        the router WAL (ISSUE 17) so a restart can detect that the
        scheduling state it recovered was built under different QoS
        knobs (recovery logs the flip instead of silently mixing)."""
        classes: dict[str, float] = {}
        if self.registry.enabled:
            for name in self.registry.class_names():
                classes[name] = self.registry.classes[name].admission_share
        return {"placement": self.placement, "classes": classes}

    def filter(self, replicas: list, slo_class: str | None) -> list:
        """Restrict ``replicas`` (routable candidates) for a class.
        Returns the input list object untouched when inactive."""
        if not self.active or len(replicas) <= 1:
            return replicas
        name = self.registry.resolve(slo_class).name
        ordered = sorted(replicas, key=lambda r: r.replica_id)
        if self.placement == "segregate":
            subset = self._segregate(ordered).get(name)
        else:
            subset = self._reserve(ordered, name)
        if not subset:
            return replicas
        return subset

    def _segregate(self, ordered: list) -> dict[str, list]:
        """Disjoint per-class slices of the replica-id-sorted list,
        sized by admission share via largest remainder.  Deterministic
        in fleet membership, so every router instance agrees."""
        names = self.registry.class_names()
        n = len(ordered)
        shares = [
            self.registry.classes[c].admission_share for c in names
        ]
        configured = sum(shares)
        zeros = sum(1 for s in shares if s <= 0.0)
        leftover = max(1.0 - configured, 0.0)
        weights = [
            s if s > 0.0 else (leftover / zeros if zeros else 0.0)
            for s in shares
        ]
        total = sum(weights) or 1.0
        quotas = [w / total * n for w in weights]
        counts = [int(q) for q in quotas]
        # Largest remainder, ties to higher priority (list order).
        for i in sorted(
            range(len(names)),
            key=lambda i: (quotas[i] - counts[i], -i),
            reverse=True,
        ):
            if sum(counts) >= n:
                break
            counts[i] += 1
        out: dict[str, list] = {}
        start = 0
        for name, count in zip(names, counts):
            out[name] = ordered[start : start + count]
            start += count
        return out

    def _reserve(self, ordered: list, name: str) -> list:
        names = self.registry.class_names()
        top = names[0]
        if name == top:
            return ordered
        share = self.registry.classes[top].admission_share
        headroom = math.ceil(share * len(ordered)) if share > 0.0 else 0
        open_set = ordered[: len(ordered) - headroom]
        return open_set if open_set else ordered


class GoodputTracker:
    """Windowed per-class goodput from cumulative ``/router/slo``
    counters.  ``update`` takes the fleet-merged class map, diffs it
    against the previous scrape, and returns the name of the worst
    class whose windowed goodput ratio sags below the floor (with at
    least ``min_requests`` finished in the window, so one unlucky
    request can't trigger a scale-up)."""

    def __init__(self, floor: float, min_requests: int) -> None:
        self.floor = floor
        self.min_requests = max(min_requests, 1)
        self._last: dict[str, tuple[int, int]] = {}
        # Last window's (d_requests, d_goodput) per class, for
        # /router/fleet introspection.
        self.window: dict[str, tuple[int, int]] = {}

    def update(self, classes: dict) -> str | None:
        worst: str | None = None
        worst_ratio = 0.0
        window: dict[str, tuple[int, int]] = {}
        for name, view in (classes or {}).items():
            requests = int(view.get("requests", 0))
            goodput = int(view.get("goodput", 0))
            prev_r, prev_g = self._last.get(name, (0, 0))
            d_r, d_g = requests - prev_r, goodput - prev_g
            if d_r < 0:
                # Cumulative counters went backwards: a replica left
                # the merge (restart/scale-down).  Restart the window.
                d_r, d_g = requests, goodput
            self._last[name] = (requests, goodput)
            window[name] = (d_r, d_g)
            if self.floor <= 0.0 or d_r < self.min_requests:
                continue
            ratio = d_g / d_r
            if ratio < self.floor and (
                worst is None or ratio < worst_ratio
            ):
                worst, worst_ratio = name, ratio
        self.window = window
        return worst


class PrefillDemand:
    """EWMA of long-prompt arrival rate (requests/s).

    The router calls :meth:`observe` on every request whose estimated
    prompt length crosses the disagg hand-off threshold; the autoscaler
    calls :meth:`sample` once per tick, which folds the interval's
    instantaneous rate into an exponentially-weighted average with a
    time-constant of ``ewma_seconds`` (irregular tick spacing handled
    via ``alpha = 1 - exp(-dt/tau)``)."""

    def __init__(self, ewma_seconds: float = 30.0) -> None:
        self.tau = max(ewma_seconds, 1e-6)
        self.rate = 0.0
        self._count = 0
        self._last_t: float | None = None

    def observe(self, n: int = 1) -> None:
        self._count += n

    def sample(self, now: float) -> float:
        if self._last_t is None:
            self._last_t = now
            self._count = 0
            return self.rate
        dt = now - self._last_t
        if dt <= 0.0:
            return self.rate
        inst = self._count / dt
        self._count = 0
        self._last_t = now
        alpha = 1.0 - math.exp(-dt / self.tau)
        self.rate += alpha * (inst - self.rate)
        return self.rate
