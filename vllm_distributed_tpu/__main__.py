from vllm_distributed_tpu.entrypoints.cli import main

if __name__ == "__main__":
    main()
