"""Worker-side XLA/device telemetry (ISSUE 12 tentpole, part 2).

PR 11's bucketed spec-decode shapes made compile behavior load-bearing:
a shape bucket that escapes warmup costs a multi-second mid-serve XLA
compile, and nothing surfaced it in steady state — only offline benches
noticed.  ``DeviceTelemetry`` is the worker-local ledger the model
runner writes:

- **compiles**: every first execution of a distinct (kind, static
  shape) jit key is counted and timed, tagged with the triggering
  bucket kind (``prefill``/``decode``/``spec``) — a recompile storm
  shows up as a climbing ``vllm:xla_compiles_total`` instead of
  mystery latency spikes;
- **HBM**: live/limit bytes from the runtime's ``memory_stats`` so
  memory creep is a gauge, not an OOM post-mortem;
- **step roofline**: estimated bytes-touched / step-time over the
  device's peak HBM bandwidth — the steady-state twin of the offline
  bench's ``roofline_frac``.

The driver pulls snapshots over the existing ``collective_rpc`` path
(``get_device_telemetry``) on ``/metrics`` scrapes; compile events
carry monotonically increasing sequence numbers so the engine folds
each event into its Prometheus instruments exactly once.
"""

from __future__ import annotations

import threading
from collections import deque

# Peak HBM bandwidth (bytes/s) by device-kind prefix, for the roofline
# gauge.  Rough public numbers; an unknown kind reports frac 0.0 (the
# gauge is a trend signal, not a benchmark).
_PEAK_BW_BY_KIND = (
    ("TPU v6", 1640e9),
    ("TPU v5p", 2765e9),
    ("TPU v5e", 819e9),
    ("TPU v5", 819e9),
    ("TPU v4", 1228e9),
    ("TPU v3", 900e9),
)


def peak_hbm_bandwidth(device_kind: str) -> float:
    for prefix, bw in _PEAK_BW_BY_KIND:
        if device_kind.startswith(prefix):
            return bw
    return 0.0


class DeviceTelemetry:
    """Thread-safe ledger of compile/memory/bandwidth observations on
    one worker.  All record paths are O(1); ``snapshot`` is called only
    on the (rare) driver pull."""

    def __init__(self, max_events: int = 256) -> None:
        self._lock = threading.Lock()
        self._seq = 0
        # (seq, kind, seconds, shape_key) — bounded; the cumulative
        # totals below survive ring eviction.
        self.compile_events: deque[tuple] = deque(maxlen=max_events)
        self.compiles: dict[str, int] = {}
        self.compile_seconds_total = 0.0
        self.last_step_seconds = 0.0
        self.last_step_bytes = 0
        self.roofline_frac = 0.0

    def record_compile(self, kind: str, seconds: float, key: str) -> None:
        with self._lock:
            self._seq += 1
            self.compile_events.append((self._seq, kind, seconds, key))
            self.compiles[kind] = self.compiles.get(kind, 0) + 1
            self.compile_seconds_total += seconds

    def record_step(
        self, seconds: float, est_bytes: int, peak_bw: float
    ) -> None:
        """One executed step: achieved-vs-roofline bandwidth."""
        if seconds <= 0:
            return
        with self._lock:
            self.last_step_seconds = seconds
            self.last_step_bytes = est_bytes
            self.roofline_frac = (
                (est_bytes / seconds) / peak_bw if peak_bw > 0 else 0.0
            )

    def _memory_stats(self) -> tuple[int, int]:
        """(live_bytes, limit_bytes) from the runtime; (0, 0) when the
        backend exposes none (CPU tests, mock workers)."""
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats()
            if stats:
                return (
                    int(stats.get("bytes_in_use", 0)),
                    int(stats.get("bytes_limit", 0)),
                )
        except Exception as e:  # noqa: BLE001 — telemetry only, never fatal
            import logging

            logging.getLogger(__name__).debug(
                "device memory_stats unavailable: %s", e
            )
        return 0, 0

    def snapshot(self, probe_memory: bool = True) -> dict:
        live, limit = self._memory_stats() if probe_memory else (0, 0)
        with self._lock:
            return {
                "compile_events": [list(e) for e in self.compile_events],
                "compiles": dict(self.compiles),
                "compile_seconds_total": self.compile_seconds_total,
                "hbm_live_bytes": live,
                "hbm_limit_bytes": limit,
                "last_step_seconds": self.last_step_seconds,
                "last_step_bytes": self.last_step_bytes,
                "roofline_frac": self.roofline_frac,
            }
