"""Worker: owns the chips of one host and runs the model step.

The TPU analog of the vLLM worker the reference drives through
WorkerWrapperBase with string-dispatched lifecycle methods —
init_worker/init_device/load_model/execute_model/check_health
(launch.py:290-292, 329-343, 387; SURVEY.md §2.3).  One process per TPU
host owning all local chips (SURVEY.md §7 design stance), vs. the
reference's process-per-GPU.

All methods here are reachable by string name via ``run_method`` — that
is the executor's collective_rpc contract.
"""

from __future__ import annotations

import queue
from typing import Any

import jax

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.engine.scheduler import SchedulerOutput
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.outputs import ModelRunnerOutput
from vllm_distributed_tpu.worker.model_runner import ModelRunner

logger = init_logger(__name__)


class Worker:
    def __init__(
        self,
        config: EngineConfig,
        rank: int = 0,
        local_rank: int = 0,
        distributed_init_method: str | None = None,
        is_driver_worker: bool = True,
    ) -> None:
        self.config = config
        self.rank = rank
        self.local_rank = local_rank
        self.distributed_init_method = distributed_init_method
        self.is_driver_worker = is_driver_worker
        self.mesh = None
        self.runner: ModelRunner | None = None
        # Dispatched-but-unresolved steps (cross-RPC pipelining): filled
        # by dispatch_model on the dispatch thread, drained FIFO by
        # fetch_results on the fetch thread.
        self._deferred: queue.Queue[tuple[int, Any]] = queue.Queue()

    # ---- lifecycle RPCs ----
    def init_device(self) -> None:
        """Join the distributed world (multi-host: jax.distributed over DCN,
        the analog of the torch/NCCL rendezvous at launch.py:94) and build
        the device mesh."""
        pc = self.config.parallel_config
        if pc.num_hosts > 1 and self.distributed_init_method:
            jax.distributed.initialize(
                coordinator_address=self.distributed_init_method,
                num_processes=pc.num_hosts,
                process_id=self.rank,
            )
        # After distributed init: the backend-scoped cache path touches
        # jax.default_backend(), which initializes the XLA backend.
        self._enable_compilation_cache()
        if pc.world_size > 1:
            from vllm_distributed_tpu.distributed.mesh import build_mesh

            self.mesh = build_mesh(pc)
        logger.info(
            "worker rank=%d devices=%d backend=%s",
            self.rank,
            jax.local_device_count(),
            jax.default_backend(),
        )

    def _enable_compilation_cache(self) -> None:
        """Persistent XLA compilation cache (the analog of the reference's
        per-container /root/.cache compiled-model volume,
        docker-compose.yml:24-25).  Makes restart-to-first-token fast —
        SURVEY.md §5.4 / hard part #4."""
        import os

        from vllm_distributed_tpu import envs

        cache_dir = envs.VDT_COMPILE_CACHE_DIR
        if not cache_dir:
            return
        # Scope by backend: CPU test/dryrun runs otherwise load the TPU
        # runs' XLA:CPU AOT entries compiled for a different host and
        # spam machine-feature-mismatch errors (VERDICT r4 weak #8) —
        # and vice versa.
        cache_dir = os.path.join(cache_dir, jax.default_backend())
        try:
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0
            )
        except (OSError, AttributeError) as e:  # read-only fs, old jax
            logger.warning("compilation cache disabled: %s", e)

    def load_model(self, load_format: str | None = None) -> None:
        from vllm_distributed_tpu import envs

        self.runner = ModelRunner(
            self.config, mesh=self.mesh, attn_backend=envs.VDT_USE_PALLAS
        )
        self.runner.load_model(
            load_format=load_format or self.config.model_config.load_format
        )

    def determine_num_pages(self) -> int:
        return self.runner.profile_num_pages()

    def initialize_cache(self, num_pages: int) -> None:
        self.runner.init_kv_cache(num_pages)

    def warmup_decode(self) -> int:
        return self.runner.warmup_decode()

    def warmup_prefill(self) -> int:
        return self.runner.warmup_prefill()

    def execute_model(
        self, scheduler_output: SchedulerOutput, defer: bool = False
    ) -> ModelRunnerOutput | None:
        """Run one step.  The runner may return a deferred resolver (fused
        decode: the dispatch is in flight, results fetched on resolve);
        over RPC the resolver cannot cross the wire, so it is resolved
        here unless the in-process caller asks to defer."""
        out = self.runner.execute_model(scheduler_output)
        if callable(out) and not defer:
            out = out()
        return out if self._replies() else None

    def _replies(self) -> bool:
        """Non-driver ranks reply too when a KV connector is configured
        (the aggregator needs every worker's KV-transfer progress;
        reference launch.py:338-349)."""
        return (
            self.is_driver_worker
            or self.config.kv_transfer_config is not None
        )

    # ---- two-phase step (cross-RPC pipelining, VERDICT r2 weak #4) ----
    def dispatch_model(self, scheduler_output: SchedulerOutput) -> int:
        """Issue the step to the device and return immediately; results
        come from a later fetch_results.  Lets the driver put dispatch
        N+1 on the wire while N is still computing — the remote analog
        of the engine's in-flight pipelining (launch.py:298-302)."""
        out = self.runner.execute_model(scheduler_output)
        self._deferred.put((scheduler_output.step_id, out))
        return scheduler_output.step_id

    def fetch_results(
        self, step_id: int, timeout: float = 300.0
    ) -> ModelRunnerOutput | None:
        """Resolve the oldest dispatched step (FIFO).  Blocks until its
        dispatch has been issued and the device results are ready; must
        run on a different thread than dispatch_model (the agent and the
        executor route the two verbs to separate ordered pools)."""
        sid, out = self._deferred.get(timeout=timeout)
        if sid != step_id:
            raise RuntimeError(
                f"fetch_results out of order: expected step {sid}, "
                f"got {step_id}"
            )
        if callable(out):
            out = out()
        return out if self._replies() else None

    def embed(self, token_ids: list[int]) -> list[float] | None:
        out = self.runner.embed(token_ids)
        return out if self.is_driver_worker else None

    def score(self, token_ids: list[int]) -> list[float | None] | None:
        out = self.runner.score(token_ids)
        return out if self.is_driver_worker else None

    def check_health(self) -> bool:
        return True

    def get_kv_tier_info(self) -> dict | None:
        """Tiered-KV telemetry RPC (ISSUE 14): per-page pool bytes (the
        driver's host_kv_bytes gauge scale) and this worker's live
        host-tier occupancy (leak assertions in the chaos harness)."""
        if self.runner is None or not self.is_driver_worker:
            return None
        info = {"page_bytes": self.runner.kv_cache_bytes_per_page()}
        info.update(self.runner.host_kv_stats())
        return info

    def export_kv_pages(
        self, page_ids: list[int], layer_start: int, layer_count: int
    ) -> dict | None:
        """Disaggregated-prefill hand-off (ISSUE 15): gather one
        per-layer chunk of held pages' KV with content checksums.  Only
        the reply rank answers (single-host replica topology)."""
        if self.runner is None or not self.is_driver_worker:
            return None
        return self.runner.export_kv_pages(
            page_ids, layer_start, layer_count
        )

    def import_kv_pages(
        self, page_ids: list[int], layers: list[dict]
    ) -> dict | None:
        """Hand-off import: checksum-verify and scatter received layer
        chunks into reserved pages (ISSUE 15)."""
        if self.runner is None or not self.is_driver_worker:
            return None
        return self.runner.import_kv_pages(page_ids, layers)

    def get_device_telemetry(self) -> dict | None:
        """XLA compile / HBM / roofline snapshot (ISSUE 12): the driver
        pulls this on /metrics scrapes and folds it into the engine's
        Prometheus instruments.  Non-reply ranks skip the snapshot (and
        its device memory probe) entirely — their reply is discarded."""
        if self.runner is None or not self.is_driver_worker:
            return None
        return self.runner.telemetry.snapshot()

    def shutdown(self) -> None:
        """Leave the jax.distributed world cleanly (both sides must reach
        the coordination-service shutdown barrier, or the survivor is
        killed by a barrier timeout)."""
        if self.config.parallel_config.num_hosts > 1:
            try:
                jax.distributed.shutdown()
            except Exception as e:  # noqa: BLE001 — already torn down
                logger.debug("jax.distributed.shutdown: %s", e)

    def profile(self, action: str, profile_dir: str | None = None) -> None:
        if action == "start":
            jax.profiler.start_trace(
                profile_dir
                or self.config.observability_config.profile_dir
                or "/tmp/vdt_profile"
            )
        else:
            jax.profiler.stop_trace()
