"""Persistent AOT program cache: serialized jax.export artifacts.

XLA's persistent compilation cache only removes the backend-compile
phase of a warm restart; re-tracing and lowering the 1B-class step
programs still costs ~6 s + ~3 s per program (measured on v5e, PERF.md),
which is the entire warm-TTFT story of SURVEY.md §5.4.  This cache
removes those phases too: at first compile the program is exported
(jax.export) over its FLAT argument leaves and serialized next to the
XLA cache; a later process deserializes (~0 s) and compiles the embedded
StableHLO (persistent-cache hit, sub-second) without ever tracing
Python.

Flat leaves are the boundary on purpose: jax.export's pytree
serialization needs per-type registration, and our QuantizedTensor
carries a Mesh in its auxdata, which does not serialize.  Flattening
at the call site sidesteps both (the artifact sees only arrays); the
output treedef is pickled alongside the artifact.

Scope: single-device programs (the runner gates on mesh is None).
Artifacts are keyed by a hash of the program description, every leaf's
shape/dtype, and the jax/jaxlib/device identity — any mismatch is a
clean miss, never a wrong program.  Every failure path falls back to
the normal jit call.
"""

from __future__ import annotations

import hashlib
import os
import pickle
from typing import Any, Callable

import jax

from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)


class AotCache:
    def __init__(self, cache_dir: str | None, context: str = "") -> None:
        self.dir = os.path.join(cache_dir, "aot") if cache_dir else None
        # Caller-supplied identity of everything traced into the
        # programs BEYOND leaf shapes/dtypes: model hyperparameters
        # (two checkpoints can share every tensor shape but differ in
        # rope_theta etc.), kernel-backend selection, package version.
        # Without it a warm restart could silently replay a stale or
        # wrong program.
        self.context = context
        # key -> ready-to-call compiled flat function.
        self._mem: dict[str, Callable] = {}
        self._env = None

    @property
    def enabled(self) -> bool:
        return self.dir is not None

    def _env_key(self) -> str:
        if self._env is None:
            dev = jax.devices()[0]
            self._env = (
                f"{jax.__version__}:{jax.lib.__version__}:"
                f"{dev.platform}:{getattr(dev, 'device_kind', '')}"
            )
        return self._env

    def _key(self, desc: str, leaves: list) -> str:
        shapes = ";".join(
            f"{x.shape}:{x.dtype}" for x in leaves
        )
        return hashlib.sha256(
            f"{self._env_key()}|{self.context}|{desc}|{shapes}".encode()
        ).hexdigest()[:32]

    @staticmethod
    def _donated_leaf_indices(args: tuple, donate_args: tuple) -> tuple:
        out, off = [], 0
        for i, a in enumerate(args):
            n = len(jax.tree.leaves(a))
            if i in donate_args:
                out.extend(range(off, off + n))
            off += n
        return tuple(out)

    def call(
        self,
        desc: str,
        fn: Callable,
        args: tuple,
        donate_args: tuple = (),
    ) -> Any:
        """Run ``fn(*args)`` through the artifact cache.

        ``fn`` must be a pure function of its positional pytree args
        (static configuration baked in via partial — and spelled into
        ``desc``, which keys the artifact together with all leaf
        shapes/dtypes).  ``donate_args`` are positional indices of args
        whose buffers are donated."""
        leaves, in_tree = jax.tree.flatten(args)
        key = self._key(desc, leaves)
        cached = self._mem.get(key)
        if cached is not None:
            return cached(leaves)
        dleaves = self._donated_leaf_indices(args, donate_args)
        path = os.path.join(self.dir, key)
        try:
            runner = self._load(path, dleaves)
        except FileNotFoundError:
            runner = None
        except Exception as e:  # noqa: BLE001 — stale/corrupt artifact
            logger.warning("AOT artifact %s unusable (%s); recompiling",
                           key, e)
            runner = None
        if runner is None:
            runner = self._build_and_save(
                desc, fn, in_tree, leaves, dleaves, path, key
            )
        self._mem[key] = runner
        return runner(leaves)

    @staticmethod
    def _runner_from_exported(exp, out_tree, dleaves) -> Callable:
        call = jax.jit(exp.call, donate_argnums=dleaves)

        def run(leaves):
            return jax.tree.unflatten(out_tree, call(*leaves))

        return run

    def _load(self, path: str, dleaves: tuple) -> Callable:
        with open(path + ".bin", "rb") as f:
            exp = jax.export.deserialize(bytearray(f.read()))
        with open(path + ".tree", "rb") as f:
            out_tree = pickle.load(f)
        return self._runner_from_exported(exp, out_tree, dleaves)

    def _build_and_save(
        self, desc, fn, in_tree, leaves, dleaves, path, key
    ) -> Callable:
        out_box = {}

        def flat_fn(*lv):
            out = fn(*jax.tree.unflatten(in_tree, list(lv)))
            out_leaves, out_box["tree"] = jax.tree.flatten(out)
            return out_leaves

        jitted = jax.jit(flat_fn, donate_argnums=dleaves)
        try:
            exp = jax.export.export(jitted)(*leaves)
            os.makedirs(self.dir, exist_ok=True)
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "wb") as f:
                f.write(exp.serialize())
            os.replace(tmp, path + ".bin")
            with open(tmp, "wb") as f:
                pickle.dump(out_box["tree"], f)
            os.replace(tmp, path + ".tree")
            logger.info("AOT artifact saved: %s (%s)", key, desc)
        except Exception as e:  # noqa: BLE001 — export is best-effort
            logger.warning("AOT export failed for %s (%s)", desc, e)

            def run(leaves):
                out_leaves = jitted(*leaves)
                return jax.tree.unflatten(out_box["tree"], out_leaves)

            return run
        # Execute through exp.call ON THE FIRST RUN TOO: the exported
        # wrapper is a different XLA module than the plain jitted one,
        # and whichever form runs first is what lands in the persistent
        # XLA cache — compiling the jitted form here would leave a warm
        # RESTART paying a full backend compile for the exp.call form
        # (measured: r5 bench warm probes at 12-47 s before this).
        return self._runner_from_exported(exp, out_box["tree"], dleaves)
