"""Persistent step-stream run loop (ISSUE 7 tentpole piece 1, worker
side).

Replaces the per-step ``dispatch_model``/``fetch_results`` RPC
round-trip pair with a long-lived pull loop: the driver pushes encoded
``StepFrame``s into this runner's bounded inbox (one ONE-WAY frame per
step), the dispatch thread decodes them against the host's
``StepStateMirror`` and issues them to the device, and the resolve
thread fetches results in FIFO order and hands them to ``deliver`` —
which, on a remote host, sends one one-way ack frame back to the
driver.  A step therefore costs two one-way frames total instead of two
request/reply pairs, and the driver never blocks a thread per step on
the wire.

Threading contract: both loop threads are daemon (named ``vdt-*`` so
the leak assertions in the fault suite see them) AND joined by
``stop()``; every queue wait is deadline-bounded (``timeout=`` + stop
flag — the step-queue wait pattern VDT003 enforces for this module).

Stall accounting: ``stalls`` counts the times the dispatch thread had
to WAIT for a frame while the device had nothing in flight — the
precise "scheduler idled between gather N and dispatch N+1" signal the
overlapped driver is built to eliminate (acceptance: 0 at steady
state).  The blocking driver protocol measures one stall per step by
construction.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable

from vllm_distributed_tpu.engine.step_delta import StepFrame, StepStateMirror
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.tracing import get_tracer

logger = init_logger(__name__)

# Poll granularity for the stop flag; every queue wait in this module is
# bounded by it.
_POLL_SECONDS = 0.5

# Deliver callback:
# (step_id, result, error_message|None, wire_spans, dispatch_span_ctx).
DeliverFn = Callable[[int, Any, str | None, list[dict], Any], None]


class StepStreamRunner:
    """One per worker host.  ``submit`` is called from the transport
    side (agent event loop, or the driver's engine thread for the local
    worker) and never blocks; execution happens on the two loop
    threads."""

    def __init__(
        self,
        worker: Any,
        deliver: DeliverFn,
        *,
        depth: int,
        name: str = "local",
    ) -> None:
        self.worker = worker
        self.deliver = deliver
        self.mirror = StepStateMirror()
        self._inbox: queue.Queue = queue.Queue(maxsize=max(depth, 1))
        self._resolve_q: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._lock = threading.Lock()
        # Stats (read via stats(), written on the loop threads).
        self._dispatched = 0
        self._resolved = 0
        self._stalls = 0
        self._inflight = 0
        self._max_queue_depth = 0
        self._dispatch_thread = threading.Thread(
            target=self._dispatch_loop,
            daemon=True,
            name=f"vdt-stepstream-dispatch-{name}",
        )
        self._resolve_thread = threading.Thread(
            target=self._resolve_loop,
            daemon=True,
            name=f"vdt-stepstream-resolve-{name}",
        )
        self._dispatch_thread.start()
        self._resolve_thread.start()

    def _deliver(self, step_id, result, error, spans, span_ctx) -> None:
        """Deliver guard: the callback crosses into transport territory
        (pickle + event-loop handoff on remote hosts) and an exception
        there must never kill a loop thread — a dead loop thread would
        silently wedge every queued step until the driver's deadline."""
        try:
            self.deliver(step_id, result, error, spans, span_ctx)
        except Exception:  # noqa: BLE001 — the stream must outlive a
            # failed ack; the driver's per-step deadline attributes it.
            logger.exception("step %d: result delivery failed", step_id)

    # ---- intake (transport side) ----
    def submit(self, frame: StepFrame, span_ctx: tuple | None = None) -> None:
        """Enqueue one decoded-on-arrival step.  Never blocks: the
        driver bounds in-flight steps well under the inbox depth, so a
        full inbox is a protocol violation and is surfaced as a step
        error instead of backpressure that could wedge the caller."""
        try:
            self._inbox.put_nowait((frame, span_ctx))
        except queue.Full:
            logger.error(
                "step stream inbox overflow at step %d", frame.step_id
            )
            self._deliver(
                frame.step_id, None, "step stream inbox overflow", [], None
            )
        else:
            with self._lock:
                self._max_queue_depth = max(
                    self._max_queue_depth, self._inbox.qsize()
                )

    # ---- loops ----
    def _next_frame(self):
        """Bounded pull with stall accounting: a wait that begins with
        nothing in flight on the device (and at least one step already
        served) is a stall window."""
        try:
            item = self._inbox.get_nowait()
            return None if item is None else item
        except queue.Empty:
            pass
        with self._lock:
            if self._dispatched > 0 and self._inflight == 0:
                self._stalls += 1
        while not self._stop.is_set():
            try:
                item = self._inbox.get(timeout=_POLL_SECONDS)
            except queue.Empty:
                continue
            if item is None:  # stop() wake sentinel
                return None
            return item
        return None

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            item = self._next_frame()
            if item is None:
                return  # stop flag (or wake sentinel) — exit
            frame, span_ctx = item
            try:
                so = self.mirror.decode(frame)
            except Exception as e:  # noqa: BLE001 — mirror desync is
                # fatal for the host; surface it as a step error.
                logger.exception("step %d: frame decode failed", frame.step_id)
                self._deliver(
                    frame.step_id, None, f"frame decode: {e}", [], span_ctx
                )
                continue
            with self._lock:
                self._dispatched += 1
                self._inflight += 1
            if frame.blocking:
                # Blocking steps (prefill/mixed) run inline: the driver
                # is waiting on this result before scheduling anything
                # else, so two-phase staging buys nothing.
                result, err, spans = self._run_step(
                    span_ctx, self.worker.execute_model, so
                )
                with self._lock:
                    self._inflight -= 1
                    self._resolved += 1
                self._deliver(
                    frame.step_id, result, err, spans, span_ctx
                )
                continue
            try:
                self.worker.dispatch_model(so)
            except Exception as e:  # noqa: BLE001 — device dispatch
                # failure fails the step, attributed by the driver.
                logger.exception(
                    "step %d: dispatch failed", frame.step_id
                )
                with self._lock:
                    self._inflight -= 1
                self._deliver(
                    frame.step_id, None, f"dispatch: {e}", [], span_ctx
                )
                continue
            self._resolve_q.put((frame.step_id, span_ctx))

    def _resolve_loop(self) -> None:
        while not self._stop.is_set():
            try:
                item = self._resolve_q.get(timeout=_POLL_SECONDS)
            except queue.Empty:
                continue
            if item is None:  # stop() wake sentinel
                return
            step_id, span_ctx = item
            result, err, spans = self._run_step(
                span_ctx, self.worker.fetch_results, step_id
            )
            with self._lock:
                self._inflight -= 1
                self._resolved += 1
            self._deliver(step_id, result, err, spans, span_ctx)

    def _run_step(self, span_ctx, fn, arg):
        """Run one worker call, wrapped in a ``worker.execute`` span
        when the driver attached a dispatch-span context (remote hosts
        with tracing on); the span ships back inside the ack so the
        step's trace keeps its worker-side chain under the one-way
        protocol."""
        spans: list[dict] = []
        tracer = get_tracer()
        if span_ctx is None or not tracer.enabled:
            try:
                return fn(arg), None, spans
            except Exception as e:  # noqa: BLE001 — worker errors are
                # delivered, not raised on the loop thread.
                logger.exception("step stream worker call failed")
                return None, f"{type(e).__name__}: {e}", spans
        sp = None
        try:
            try:
                with tracer.span(
                    "worker.execute",
                    parent=tuple(span_ctx),
                    record=False,
                    method=fn.__name__,
                ) as sp:
                    result = fn(arg)
            finally:
                if sp is not None:
                    spans.append(sp.to_wire())
            return result, None, spans
        except Exception as e:  # noqa: BLE001
            logger.exception("step stream worker call failed")
            return None, f"{type(e).__name__}: {e}", spans

    # ---- introspection / teardown ----
    def stats(self) -> dict:
        with self._lock:
            return {
                "dispatched": self._dispatched,
                "resolved": self._resolved,
                "stalls": self._stalls,
                "inflight": self._inflight,
                "max_queue_depth": self._max_queue_depth,
            }

    def stop(self, join_timeout: float = 5.0) -> None:
        self._stop.set()
        # Wake sentinels so idle loop threads exit immediately instead
        # of at their next poll tick (teardown latency matters: the
        # supervisor's rebuild waits on this join).
        for q in (self._inbox, self._resolve_q):
            try:
                q.put_nowait(None)
            except queue.Full:
                pass  # a busy queue means the thread isn't idle anyway
        self._dispatch_thread.join(timeout=join_timeout)
        self._resolve_thread.join(timeout=join_timeout)
        if self._dispatch_thread.is_alive() or self._resolve_thread.is_alive():
            logger.warning(
                "step stream loop thread(s) still running after %.1fs "
                "(wedged worker call?)",
                join_timeout,
            )
