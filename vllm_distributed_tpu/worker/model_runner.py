"""Model runner: SchedulerOutput → one fused, jitted device step.

Replaces the reference's GPU model runner (driven via
`collective_rpc("execute_model")`, launch.py:322-343) with a TPU-first
design (SURVEY.md §7): the whole step — embedding, every layer, paged KV
scatter, attention, and sampling — is ONE compiled XLA program with the KV
cache donated, so steady state is a single dispatch per scheduler step and
no per-layer host round trips.

Static-shape discipline (XLA compiles per shape): token count, sequence
count, pages-per-seq, and penalty-history lengths are padded to
power-of-two buckets, so the number of distinct compiled programs stays
logarithmic in batch size (SURVEY.md §7 hard part #2).

Workers mirror request state (token ids, page tables, cursors) so each
step's input is only the scheduler's delta — the control-plane economy the
reference gets by shipping SchedulerOutput, not tensors (SURVEY.md §2.5).
"""

from __future__ import annotations

import hashlib
import time
import zlib
from dataclasses import dataclass, field
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.engine.scheduler import SchedulerOutput
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.model_loader import get_model
from vllm_distributed_tpu.ops.attention import (
    AttentionMetadata,
    paged_attention_reference,
)
from vllm_distributed_tpu.ops.sampling import (
    SamplingMetadata,
    sample,
    spec_greedy_accept,
)
from vllm_distributed_tpu.outputs import ModelRunnerOutput
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.utils import cdiv, next_power_of_2
from vllm_distributed_tpu.worker.telemetry import (
    DeviceTelemetry,
    peak_hbm_bandwidth,
)

logger = init_logger(__name__)

_MIN_TOKEN_BUCKET = 16
_MIN_SEQ_BUCKET = 8
_MIN_PAGES_BUCKET = 8


def pack_host_arrays(arrays: list[np.ndarray]) -> tuple[np.ndarray, tuple]:
    """Concatenate 4-byte-dtype host arrays into ONE int32 buffer.

    The per-step host→device hop dominates decode latency when the device
    is reached over a network tunnel (each transfer pays a round trip), so
    every step ships exactly one buffer; `unpack_device_arrays` rebuilds
    the typed views inside the jitted program via static slicing +
    bitcasts.  Returns (buffer, spec) where spec is hashable (a static jit
    argument).
    """
    views: list[np.ndarray] = []
    spec: list[tuple] = []
    for a in arrays:
        a = np.ascontiguousarray(a)
        assert a.dtype.itemsize == 4, f"pack needs 4-byte dtypes, got {a.dtype}"
        v = a.view(np.int32).ravel()
        spec.append((a.shape, a.dtype.str, v.size))
        views.append(v)
    return np.concatenate(views), tuple(spec)


def unpack_device_arrays(packed: jax.Array, spec: tuple) -> list[jax.Array]:
    """Inverse of pack_host_arrays, inside jit (static offsets/shapes)."""
    out = []
    off = 0
    for shape, dtype_str, size in spec:
        seg = jax.lax.slice(packed, (off,), (off + size,))
        dt = np.dtype(dtype_str)
        if dt != np.int32:
            seg = jax.lax.bitcast_convert_type(seg, dt)
        out.append(seg.reshape(shape))
        off += size
    return out


@dataclass
class CachedReqState:
    req_id: str
    token_ids: list[int]  # prompt + everything sampled so far
    sampling_params: SamplingParams
    page_ids: list[int]
    num_computed: int
    prefill_target: int  # sample only once computed tokens reach this
    num_prompt: int  # true prompt/output boundary (stable across preemption)


def _needs_top_k_p(sp: SamplingParams) -> bool:
    return sp.top_k > 0 or sp.top_p < 1.0 or sp.min_p > 0.0


def _needs_penalties(sp: SamplingParams) -> bool:
    return (
        sp.repetition_penalty != 1.0
        or sp.presence_penalty != 0.0
        or sp.frequency_penalty != 0.0
    )


class ModelRunner:
    def __init__(
        self,
        config: EngineConfig,
        mesh: Any = None,
        attn_backend: str = "auto",
    ) -> None:
        self.config = config
        self.mesh = mesh
        self.page_size = config.cache_config.page_size
        self.global_seed = config.model_config.seed
        self.model = None
        self.params = None
        self.kv_caches: list | None = None
        self.requests: dict[str, CachedReqState] = {}
        self.attn_backend = attn_backend
        self._attn_fn = None
        # Device-resident decode carry: after a fused-K decode dispatch,
        # (request order, next base lens, last-token device array).  Lets
        # the next dispatch start from on-device tokens so the engine can
        # pipeline dispatches without waiting for results (SURVEY.md §3.3,
        # launch.py:298-302's max_concurrent_batches analog).
        self._decode_carry: tuple | None = None
        # Input sharding (set at load): step inputs shard their leading
        # dim over the mesh's "dp" axis; with dp=1 they are replicated.
        self._input_spec = None
        self._dp = 1
        from vllm_distributed_tpu.worker.aot_cache import AotCache

        self._aot = AotCache(None)  # armed in load_model (single-chip)
        # XLA/device telemetry (ISSUE 12): every first execution of a
        # distinct (kind, statics) shape key is counted and timed as a
        # compile; per-step achieved-vs-roofline bandwidth rides along.
        self.telemetry = DeviceTelemetry()
        self._compiled_keys: set[str] = set()
        self._param_bytes = 0
        self._kv_token_bytes = 0
        self._peak_bw = 0.0
        # Tiered KV cache (ISSUE 14): host-DRAM copies of spilled pages,
        # slot id -> per-layer pytree of [2, page, width] host arrays.
        # Bounded by the driver-side allocator's host pool — slots are
        # only reused after their restore shipped, so the dict can never
        # exceed the configured host page count (plus entries whose
        # slot the driver freed without a restore, until that slot's
        # next spill overwrites them).
        self._host_kv: dict[int, Any] = {}

    # ---- lifecycle (the collective_rpc verbs, launch.py:290-292) ----
    def load_model(self, load_format: str = "auto") -> None:
        self.model, self.params = get_model(
            self.config.model_config, load_format=load_format, mesh=self.mesh
        )
        # Single-chip fast path: fuse quantized Q|K|V and gate|up so
        # each layer issues one weight-streaming kernel call instead of
        # three/two (bit-identical results).  Self-gating: fusable()
        # requires w.mesh is None — a tp-sharded out-dim concat would
        # interleave shards of q|k|v instead of sharding the fused
        # tensor (llama.py fuse_quantized_projections).
        if hasattr(self.model, "fuse_quantized_projections"):
            self.params = self.model.fuse_quantized_projections(self.params)
        self._attn_fn = self._pick_attn_fn()
        # Two writers: prefill/mixed steps keep the functional XLA
        # scatter (batched, GSPMD-partitionable — the aliased Pallas
        # writer's grid=(T,) would issue T serialized per-token DMAs per
        # layer on a 2048-token chunk); the fused decode scan uses the
        # in-place Pallas writer, where XLA's non-aliased scatter copies
        # the whole pool per layer per micro-step.
        from vllm_distributed_tpu.ops.attention import write_kv_pages

        self._kv_write_fn = write_kv_pages
        # Staged decode writes (side buffer + per-dispatch flush) ride
        # the Pallas attention path (the flush kernel is its writer);
        # the XLA reference path keeps the in-loop functional scatter.
        self._kv_flush_fn = self._pick_kv_flush_fn()
        self._staged_decode = self._kv_flush_fn is not None
        if self.mesh is not None:
            self._dp = self.mesh.shape.get("dp", 1)
            if self._dp & (self._dp - 1):
                raise ValueError(
                    f"dp axis size must be a power of 2, got {self._dp} "
                    "(power-of-two shape buckets must stay divisible)"
                )
            if (
                self.config.scheduler_config.spec_ngram_k > 0
                and self._dp > 1
            ):
                raise ValueError(
                    "speculative decoding does not support dp>1 (the "
                    "verify pass ships one packed replicated buffer; a "
                    "dp-sharded variant would need per-shard verify "
                    "windows) — use dp=1 or --speculative-ngram-k 0"
                )
            tp = self.mesh.shape.get("tp", 1)
            if tp > 1 and self.model.num_kv_heads % tp:
                # The combined pool shards its flat head×dim lanes; a tp
                # that does not divide the head count would silently
                # split heads mid-lane instead of failing.
                raise ValueError(
                    f"tp={tp} must divide num_kv_heads="
                    f"{self.model.num_kv_heads} to shard the KV cache"
                )
            axis = "dp" if self._dp > 1 else None
            self._input_spec = NamedSharding(self.mesh, P(axis))
        self._shard_kernels()
        # Persistent AOT program cache (§5.4 warm restarts): skips
        # trace+lower on reboot, not just XLA compile.  Single-device
        # only — a meshed program's shardings don't round-trip through
        # the flat-leaf export boundary.  "auto" = TPU only (CPU test
        # runs would litter the cache with host-specific artifacts).
        from vllm_distributed_tpu import envs
        from vllm_distributed_tpu.worker.aot_cache import AotCache

        mode = envs.VDT_AOT_CACHE
        use_aot = self.mesh is None and (
            mode == "1" or (mode == "auto" and jax.default_backend() == "tpu")
        )
        # Everything traced into the programs that leaf shapes/dtypes
        # do NOT capture: model hyperparameters (rope/eps/soft-cap
        # constants can differ between same-shaped checkpoints), the
        # kernel backend, quantization scheme, cache dtype, and the
        # package version (so a kernel bugfix invalidates artifacts).
        from vllm_distributed_tpu.version import __version__

        mc = self.config.model_config
        try:
            hf_id = mc.hf_config.to_json_string(use_diff=False)
        except Exception:  # noqa: BLE001 — exotic config objects
            hf_id = repr(mc.hf_config.__dict__)
        context = "|".join(
            (
                __version__,
                hashlib.sha256(hf_id.encode()).hexdigest()[:16],
                str(mc.quantization),
                str(mc.dtype),
                self.config.cache_config.cache_dtype,
                self.attn_backend,
                str(self.page_size),
            )
        )
        self._aot = AotCache(
            envs.VDT_COMPILE_CACHE_DIR if use_aot else None,
            context=context,
        )
        # Device-telemetry constants (ISSUE 12): resident param bytes
        # and per-KV-token bytes for the step roofline estimate, peak
        # HBM bandwidth from the device kind.  All best-effort — the
        # gauges degrade to 0, never fail the load.
        try:
            self._param_bytes = sum(
                int(x.size) * x.dtype.itemsize
                for x in jax.tree_util.tree_leaves(self.params)
                if hasattr(x, "size") and hasattr(x, "dtype")
            )
        except Exception:  # noqa: BLE001 — telemetry only
            self._param_bytes = 0
        kv_itemsize = (
            1
            if self.config.cache_config.cache_dtype == "int8"
            else (4 if mc.dtype == "float32" else 2)
        )
        try:
            self._kv_token_bytes = (
                mc.get_num_layers()
                * 2  # K and V
                * mc.get_num_kv_heads()
                * mc.get_head_dim()
                * kv_itemsize
            )
        except Exception:  # noqa: BLE001 — telemetry only
            self._kv_token_bytes = 0
        try:
            self._peak_bw = peak_hbm_bandwidth(
                getattr(jax.local_devices()[0], "device_kind", "")
            )
        except Exception:  # noqa: BLE001 — telemetry only
            self._peak_bw = 0.0

    # ---- device telemetry helpers (ISSUE 12) ----
    def _observed_call(self, kind: str, shape_key: str, fn):
        """Run one jitted step program.  The FIRST execution of each
        distinct (kind, shape) key is timed and recorded as an XLA
        compile (trace+lower+compile dominate that call); later calls
        are passthrough.  AOT-cache hits still count: a warm artifact
        load is exactly the stall class the counter tracks, just
        cheaper — the histogram shows the difference."""
        key = f"{kind}:{shape_key}"
        if key in self._compiled_keys:
            return fn()
        t0 = time.perf_counter()
        out = fn()
        self._compiled_keys.add(key)
        self.telemetry.record_compile(kind, time.perf_counter() - t0, key)
        return out

    def _record_step_bw(
        self, seconds: float, kv_tokens: int, passes: int = 1
    ) -> None:
        """Achieved-vs-roofline gauge: weights + live-KV bytes per HBM
        pass over the measured step wall time (an estimate — exact DMA
        accounting would need a profiler, which /debug/profile is for)."""
        est = passes * (
            self._param_bytes + self._kv_token_bytes * max(kv_tokens, 0)
        )
        self.telemetry.record_step(seconds, int(est), self._peak_bw)

    def _shard_kernels(self) -> None:
        """Partition the Pallas kernels over the mesh "tp" axis.

        GSPMD cannot partition a Pallas custom call, so at tp>1 the
        kernels are wrapped in jax.shard_map to run on local head shards
        (ops/sharded.py).  The XLA reference path needs no wrapping —
        GSPMD partitions gather/scatter/einsum natively.
        """
        if self.mesh is None:
            return
        from vllm_distributed_tpu.ops import sharded
        from vllm_distributed_tpu.ops.attention import (
            paged_attention_reference,
            write_kv_pages,
        )

        uses_pallas = (
            self._attn_fn is not paged_attention_reference
            or self._staged_decode
        )
        if not uses_pallas:
            return
        # dp must be rejected regardless of tp (at tp==1 the kernels
        # would otherwise run unwrapped under a dp-sharded GSPMD mesh).
        if self._dp > 1:
            raise ValueError(
                "the Pallas backend does not support dp>1 (the KV pool is "
                "replicated over dp; per-shard in-place writes would "
                "diverge the replicas) — use dp=1 or attn_backend="
                "'reference'"
            )
        if self.mesh.shape.get("tp", 1) <= 1:
            return
        sharded._check_divisible(
            self.mesh, self.model.num_heads, self.model.num_kv_heads
        )
        if self._attn_fn is not paged_attention_reference:
            self._attn_fn = sharded.shard_attention(self._attn_fn, self.mesh)
        if self._kv_flush_fn is not None:
            self._kv_flush_fn = sharded.shard_kv_flush(
                self._kv_flush_fn, self.mesh
            )

    def _pick_attn_fn(self):
        backend = self.attn_backend
        if backend == "auto":
            backend = (
                "pallas" if jax.default_backend() == "tpu" else "reference"
            )
        if backend == "pallas":
            try:
                from vllm_distributed_tpu.ops.pallas.paged_attention import (
                    paged_attention,
                )

                return paged_attention
            except ImportError:
                logger.warning("pallas backend unavailable; using reference")
        if backend == "pallas_interpret":
            from vllm_distributed_tpu.ops.pallas.paged_attention import (
                paged_attention_cpu,
            )

            return paged_attention_cpu
        return paged_attention_reference

    def _pick_kv_flush_fn(self):
        """Per-dispatch flush of the staged decode side buffers (only
        used when _staged_decode)."""
        backend = self.attn_backend
        if backend == "auto":
            backend = (
                "pallas" if jax.default_backend() == "tpu" else "reference"
            )
        if backend == "pallas":
            from vllm_distributed_tpu.ops.pallas.kv_flush import kv_flush

            return kv_flush
        if backend == "pallas_interpret":
            from vllm_distributed_tpu.ops.pallas.kv_flush import (
                kv_flush_cpu,
            )

            return kv_flush_cpu
        return None

    @property
    def kv_cache_quantized(self) -> bool:
        """--kv-cache-dtype int8: pool stores int8 rows + per-(token,
        kv-head) f32 scales; staged side buffers stay in model dtype
        (quantized once per dispatch at flush, not per micro-step)."""
        return self.config.cache_config.cache_dtype == "int8"

    def kv_cache_dtype(self):
        """Pool dtype: cache_config.cache_dtype, "auto" = model dtype.
        A narrower cache (e.g. bfloat16 under a float32 model) doubles
        the KV capacity; kernels read/write the pool dtype directly."""
        name = self.config.cache_config.cache_dtype
        if name in (None, "auto"):
            return self.model.dtype
        return jnp.dtype(name)

    def kv_cache_bytes_per_page(self) -> int:
        from vllm_distributed_tpu.ops.attention import kv_pool_width

        m = self.model
        dtype_size = jnp.dtype(self.kv_cache_dtype()).itemsize
        per_token = kv_pool_width(m.num_kv_heads, m.head_dim) * dtype_size
        if self.kv_cache_quantized:
            per_token += m.num_kv_heads * 4  # f32 scale row
        return m.num_layers * 2 * self.page_size * per_token

    # Per-chip HBM by device-kind prefix, for runtimes that don't expose
    # memory_stats (e.g. tunneled/proxied devices).
    _HBM_BYTES_BY_KIND = (
        ("TPU v6", 32 * 2**30),
        ("TPU v5p", 95 * 2**30),
        ("TPU v5", 16 * 2**30),  # v5e
        ("TPU v4", 32 * 2**30),
        ("TPU v3", 32 * 2**30),
        ("TPU v2", 16 * 2**30),
    )

    def _pipeline_reserve_bytes(self) -> int:
        """HBM held by in-flight fused-decode dispatches beyond the pool:
        each concurrent dispatch's program keeps its staged side buffers
        ([S, 2, K, HD] per layer) live for the program's duration.  At
        7B/K=32/depth-6 this is ~3 GiB — unreserved, the allocator
        thrashes mid-serve (measured: multi-second stalls)."""
        if not getattr(self, "_staged_decode", False):
            return 0
        sc = self.config.scheduler_config
        m = self.model
        from vllm_distributed_tpu.ops.attention import kv_pool_width

        # Side buffers stay in MODEL dtype even for an int8 pool.
        side_dtype = (
            self.model.dtype
            if self.kv_cache_quantized
            else self.kv_cache_dtype()
        )
        side = (
            sc.max_num_seqs
            * 2
            * sc.num_decode_steps
            * kv_pool_width(m.num_kv_heads, m.head_dim)
            * jnp.dtype(side_dtype).itemsize
            * m.num_layers
        )
        return side * max(sc.max_concurrent_dispatches, 1)

    def profile_num_pages(self) -> int:
        """Derive the KV pool size from free HBM (the analog of
        gpu_memory_utilization profiling in the inherited engine)."""
        cc = self.config.cache_config
        if cc.num_pages is not None:
            return cc.num_pages
        dev = jax.local_devices()[0]
        stats = getattr(dev, "memory_stats", lambda: None)()
        if not stats or "bytes_limit" not in stats:
            if jax.default_backend() != "tpu":
                return 512  # CPU: small default for tests
            # Tunneled TPU runtimes return no stats; budget from the
            # chip's known HBM minus resident params, the pipelined
            # dispatches' side buffers, and a 1 GiB activation/XLA
            # reserve.
            kind = getattr(dev, "device_kind", "")
            hbm = next(
                (b for p, b in self._HBM_BYTES_BY_KIND if kind.startswith(p)),
                16 * 2**30,
            )
            shards = 1
            if self.mesh is not None and "tp" in self.mesh.shape:
                shards = self.mesh.shape["tp"]
            param_bytes = (
                sum(x.nbytes for x in jax.tree.leaves(self.params)) // shards
            )
            limit = int(hbm * cc.hbm_utilization)
            reserve = (1 << 30) + self._pipeline_reserve_bytes() // shards
            free = max(limit - param_bytes - reserve, 0)
            per_device_page = self.kv_cache_bytes_per_page() // shards
            num_pages = max(free // max(per_device_page, 1), 16)
            logger.info(
                "KV pool (no memory_stats, %s): %d pages × %d tokens "
                "(%.2f GiB of %.2f GiB HBM budget)",
                kind or "unknown TPU",
                num_pages,
                self.page_size,
                num_pages * per_device_page / 2**30,
                free / 2**30,
            )
            return int(num_pages)
        limit = int(stats["bytes_limit"] * cc.hbm_utilization)
        in_use = int(stats.get("bytes_in_use", 0))
        shards = 1
        if self.mesh is not None and "tp" in self.mesh.shape:
            shards = self.mesh.shape["tp"]
        # Stats are per device: the side-buffer reserve shards with tp.
        free = max(
            limit - in_use - self._pipeline_reserve_bytes() // shards, 0
        )
        per_device_page = self.kv_cache_bytes_per_page() // shards
        num_pages = max(free // max(per_device_page, 1), 16)
        logger.info(
            "KV pool: %d pages × %d tokens (%.2f GiB of %.2f GiB free HBM)",
            num_pages,
            self.page_size,
            num_pages * per_device_page / 2**30,
            free / 2**30,
        )
        return int(num_pages)

    def alloc_kv_pool(self, num_pages: int) -> list:
        """Allocate a paged KV pool: one combined [2, P, page, HD] array
        per layer (see ops/attention.py layout — K/V fused so a page is
        ONE DMA, flat head lanes unpadded), sharded per the model's
        kv_cache_spec.  Used for the serving cache and for aux-forward
        scratch pools — one definition of the layout."""
        from vllm_distributed_tpu.ops.attention import (
            kv_pool_shape,
            kv_scales_shape,
        )

        m = self.model
        shape = kv_pool_shape(
            num_pages, self.page_size, m.num_kv_heads, m.head_dim
        )
        sharding = None
        if self.mesh is not None:
            sharding = NamedSharding(self.mesh, m.kv_cache_spec())
        dtype = self.kv_cache_dtype()

        def put(z):
            return jax.device_put(z, sharding) if sharding is not None else z

        if self.kv_cache_quantized:
            # (int8 data, per-head f32 scales) — the scale plane's lane
            # axis is kv heads, sharding like the data plane's HD lanes.
            s_shape = kv_scales_shape(
                num_pages, self.page_size, m.num_kv_heads
            )

            def alloc():
                return (
                    put(jnp.zeros(shape, jnp.int8)),
                    put(jnp.zeros(s_shape, jnp.float32)),
                )

        else:

            def alloc():
                return put(jnp.zeros(shape, dtype))

        return [alloc() for _ in range(m.num_layers)]

    def init_kv_cache(self, num_pages: int) -> None:
        self.num_pages = num_pages
        self.kv_caches = self.alloc_kv_pool(num_pages)

    def warmup_decode(self) -> int:
        """Pre-compile the fused-decode program so serving never
        recompiles mid-stream — with the pinned sequence bucket
        (_seq_bucket), uniform K (scheduler), and the traced carry flag
        there is exactly ONE decode program per config.  Two
        back-to-back dispatches exercise both the host-token and
        device-carry paths through it.  Returns the number of dispatches
        issued.  Synthetic requests write into reserved page 0 (garbage
        by contract) and are removed after."""
        import time as _time

        from vllm_distributed_tpu.engine.scheduler import (
            CachedRequestData,
            SchedulerOutput,
        )

        sc = self.config.scheduler_config
        # Speculative verify programs are part of decode warmup too —
        # the first mid-serve verify compile is exactly the stall class
        # this warmup exists to remove.
        n_spec = self._warmup_spec() if sc.spec_ngram_k > 0 else 0
        # The exact K the scheduler will emit — warming any other scan
        # length is wasted.
        k = sc.fused_decode_steps()
        if k <= 1 or self.kv_caches is None:
            return n_spec
        t0 = _time.monotonic()
        buckets = [self._seq_bucket()]
        pages_pad = self._pages_bucket(cdiv(2 + 2 * k, self.page_size))
        n = 0
        for s_pad in buckets:
            ids = [f"__warm-{i}" for i in range(s_pad)]
            for i, rid in enumerate(ids):
                self.requests[rid] = CachedReqState(
                    req_id=rid,
                    token_ids=[1, 1],
                    sampling_params=SamplingParams(
                        temperature=0.0, max_tokens=2 * k + 2
                    ),
                    page_ids=[0] * pages_pad,
                    num_computed=1,
                    prefill_target=1,
                    num_prompt=1,
                )

            def so(step):
                return SchedulerOutput(
                    step_id=step,
                    cached_requests=[
                        CachedRequestData(
                            req_id=rid,
                            new_page_ids=[],
                            num_computed_tokens=1 + step * k,
                            num_new_tokens=k,
                        )
                        for rid in ids
                    ],
                    num_scheduled_tokens={rid: k for rid in ids},
                    total_num_scheduled_tokens=s_pad * k,
                    decode_steps=k,
                )

            # Two back-to-back dispatches without resolving: the carry
            # flag is traced (one program), but the second dispatch
            # still validates the device-carry handoff end to end.  The
            # scheduler deltas for the second dispatch must land first —
            # they advance num_computed past the host token list, which
            # is what flips the carry flag on.
            r1 = self._execute_decode_steps(so(0))
            self._apply_scheduler_deltas(so(1))
            assert self._decode_carry is not None
            r2 = self._execute_decode_steps(so(1))
            r1()
            r2()
            n += 2
            for rid in ids:
                self.requests.pop(rid, None)
            self._decode_carry = None
        logger.info(
            "decode warmup: %d dispatches over %s seq buckets in %.1fs",
            n,
            buckets,
            _time.monotonic() - t0,
        )
        return n + n_spec

    def _warmup_spec(self) -> int:
        """Pre-compile the speculative verify program for every token
        bucket a spec step can produce (ISSUE 11).  With the pinned
        sequence bucket and verify-window width the only dynamic shape
        is the power-of-2 token bucket, capped at s_bucket * (K+1) —
        log-many programs, each warmed by one synthetic dispatch whose
        KV writes land in reserved page 0 (garbage by contract)."""
        if self.kv_caches is None:
            return 0
        import time as _time

        from vllm_distributed_tpu.engine.scheduler import (
            CachedRequestData,
            SchedulerOutput,
        )

        t0 = _time.monotonic()
        kp1 = self._spec_kp1()
        s_pad = self._seq_bucket()
        pages_pad = self._pages_bucket(cdiv(2 + kp1, self.page_size))
        buckets = []
        b = _MIN_TOKEN_BUCKET
        cap = next_power_of_2(s_pad * kp1)
        while b <= cap:
            buckets.append(b)
            b *= 2
        n = 0
        for t_bucket in buckets:
            # Window sizes summing exactly to the bucket: full K+1
            # windows first, the remainder spread so every row keeps
            # at least its input token.
            n_live = min(max(cdiv(t_bucket, kp1), 1), s_pad)
            sizes = []
            remaining = t_bucket
            for i in range(n_live):
                take = min(kp1, remaining - (n_live - i - 1))
                sizes.append(take)
                remaining -= take
            ids = [f"__warms-{i}" for i in range(n_live)]
            for rid in ids:
                self.requests[rid] = CachedReqState(
                    req_id=rid,
                    token_ids=[1, 1],
                    sampling_params=SamplingParams(
                        temperature=0.0, max_tokens=kp1 + 2
                    ),
                    page_ids=[0] * pages_pad,
                    num_computed=1,
                    prefill_target=1,
                    num_prompt=1,
                )
            so = SchedulerOutput(
                step_id=0,
                cached_requests=[
                    CachedRequestData(
                        req_id=rid,
                        new_page_ids=[],
                        num_computed_tokens=1,
                        num_new_tokens=sizes[i],
                    )
                    for i, rid in enumerate(ids)
                ],
                num_scheduled_tokens={
                    rid: sizes[i] for i, rid in enumerate(ids)
                },
                total_num_scheduled_tokens=t_bucket,
                decode_steps=1,
                draft_token_ids={
                    rid: [1] * (sizes[i] - 1)
                    for i, rid in enumerate(ids)
                    if sizes[i] > 1
                },
            )
            self._execute_spec_step(so)
            for rid in ids:
                self.requests.pop(rid, None)
            n += 1
        logger.info(
            "spec-decode warmup: %d token buckets %s in %.1fs",
            n,
            buckets,
            _time.monotonic() - t0,
        )
        return n

    def warmup_prefill(self) -> int:
        """Pre-compile the single-step (prefill/mixed) program for each
        power-of-2 token bucket up to the step budget, so the FIRST
        request after boot pays execution time, not a trace+compile
        (r4's 21 s cold TTFT at 1B was exactly this compile).  One
        synthetic single-request prefill per bucket, written into
        reserved page 0.  Returns the number of buckets compiled."""
        import time as _time

        from vllm_distributed_tpu.engine.scheduler import (
            NewRequestData,
            SchedulerOutput,
        )

        if self.kv_caches is None:
            return 0
        t0 = _time.monotonic()
        sc = self.config.scheduler_config
        cap = min(
            next_power_of_2(sc.max_num_batched_tokens),
            next_power_of_2(max(sc.max_model_len - 1, 1)),
        )
        t = _MIN_TOKEN_BUCKET  # shortest prompts land in bucket 16
        buckets = []
        while t <= cap:
            buckets.append(t)
            t *= 2
        if not buckets:
            buckets = [cap]
        n = 0
        for t_pad in buckets:
            prompt_len = min(t_pad, sc.max_model_len - 1)
            pages_pad = self._pages_bucket(
                cdiv(prompt_len + 1, self.page_size)
            )
            so = SchedulerOutput(
                step_id=0,
                new_requests=[
                    NewRequestData(
                        req_id="__warmp",
                        prompt_token_ids=[1] * prompt_len,
                        num_prompt_tokens=prompt_len,
                        page_ids=[0] * pages_pad,
                        num_computed_tokens=0,
                        num_new_tokens=prompt_len,
                        sampling_params=SamplingParams(
                            temperature=0.0, max_tokens=2
                        ),
                    )
                ],
                num_scheduled_tokens={"__warmp": prompt_len},
                total_num_scheduled_tokens=prompt_len,
                decode_steps=1,
            )
            self.execute_model(so)
            self.requests.pop("__warmp", None)
            n += 1
        logger.info(
            "prefill warmup: %d token buckets %s in %.1fs",
            n,
            buckets,
            _time.monotonic() - t0,
        )
        return n

    # ---- auxiliary (non-scheduled) forwards: embeddings & scoring ----
    @partial(jax.jit, static_argnames=("self",))
    def _jit_aux_forward(self, params, kv, tokens, meta):
        from vllm_distributed_tpu.ops.attention import (
            paged_attention_reference,
            write_kv_pages,
        )

        return self.model.forward(
            params,
            tokens,
            kv,
            meta,
            attn_fn=paged_attention_reference,
            kv_write_fn=write_kv_pages,
            return_hidden=True,
        )

    def _aux_forward(self, token_ids: list[int]):
        """One-off teacher-forced forward over a scratch KV pool with
        logits/hidden at EVERY position (the scheduled path only emits
        last-position logits).  Off the hot path by design: serves
        /v1/embeddings and prompt-logprobs scoring."""
        from vllm_distributed_tpu.ops.attention import AttentionMetadata

        t = len(token_ids)
        t_pad = max(next_power_of_2(t), _MIN_TOKEN_BUCKET)
        pages = cdiv(t_pad, self.page_size) + 1  # +1: reserved dump page
        # Cached scratch pool, grown to the largest request seen: aux
        # calls must not allocate a fresh pool each time on a device
        # whose HBM the serving pool was sized to fill.
        cached = getattr(self, "_aux_pool", None)
        if cached is None or cached[0] < pages:
            self._aux_pool = (pages, self.alloc_kv_pool(pages))
        kv = self._aux_pool[1]
        # Previous contents are dead history for this single-sequence
        # teacher-forced pass (slots are overwritten; reads are bounded
        # by seq_lens=t), so reuse without zeroing.
        tokens = np.zeros(t_pad, np.int32)
        tokens[:t] = token_ids
        positions = np.zeros(t_pad, np.int32)
        positions[:t] = np.arange(t)
        seq_ids = np.full(t_pad, 1, np.int32)  # padding -> dropped row
        seq_ids[:t] = 0
        slots = np.full(t_pad, 0, np.int32)  # padding -> dump page 0
        slots[:t] = self.page_size + np.arange(t)  # data pages from 1
        meta = AttentionMetadata(
            q_seq_ids=jnp.asarray(seq_ids),
            q_positions=jnp.asarray(positions),
            slot_mapping=jnp.asarray(slots),
            block_tables=jnp.asarray(
                np.arange(1, pages + 1, dtype=np.int32)[None, :] % pages
            ),
            seq_lens=jnp.asarray([t], jnp.int32),
            logits_indices=jnp.arange(t_pad, dtype=jnp.int32),
            chunk_starts=jnp.zeros(1, jnp.int32),
        )
        args = (jnp.asarray(tokens), meta)
        if self.mesh is not None:
            args = jax.device_put(args, NamedSharding(self.mesh, P()))
        logits, kv_out, hidden = self._jit_aux_forward(
            self.params, kv, args[0], args[1]
        )
        self._aux_pool = (pages, kv_out)  # keep the written pool warm
        return np.asarray(logits)[:t], np.asarray(hidden)[:t]

    def embed(self, token_ids: list[int]) -> list[float]:
        """Mean-pooled, L2-normalized final hidden states (the pooling
        vLLM's embedding path applies to causal LMs)."""
        _, hidden = self._aux_forward(token_ids)
        vec = hidden.mean(axis=0)
        norm = float(np.linalg.norm(vec))
        return (vec / norm if norm > 0 else vec).astype(float).tolist()

    def score(self, token_ids: list[int]) -> list[float | None]:
        """Prompt logprobs: log p(token_i | tokens_<i); index 0 is None
        (no context).  Serves completions echo+logprobs."""
        logits, _ = self._aux_forward(token_ids)
        # Stable log_softmax: shift by max.
        shifted = logits - logits.max(-1, keepdims=True)
        logps = shifted - np.log(np.exp(shifted).sum(-1, keepdims=True))
        out: list[float | None] = [None]
        for i in range(1, len(token_ids)):
            out.append(float(logps[i - 1, token_ids[i]]))
        return out

    def _seq_bucket(self) -> int:
        """Fused-decode sequence bucket: PINNED to the max_num_seqs
        power-of-2 so batch growth/shrink never changes the compiled
        decode program — with uniform K (scheduler) and the traced carry
        flag, steady-state decode is ONE program per config.  Padded
        rows cost ~nothing in decode (seq_len 0 ⇒ the kernel skips them;
        the matmuls are weight-bandwidth-bound, not row-bound).  The
        single-step path keeps growth bucketing: its q-grouping scratch
        scales with s_pad × max_q, which decode's max_q=1 avoids."""
        sc = self.config.scheduler_config
        return max(
            next_power_of_2(sc.max_num_seqs), _MIN_SEQ_BUCKET, self._dp
        )

    def _pages_bucket(self, need: int) -> int:
        """Static pages-per-seq bucket.  For small max_model_len the bucket
        is floored at the model-length maximum so growing contexts never
        trigger a mid-serve recompile; for long-context configs (> 4096
        tokens of pages) it falls back to power-of-2 growth (log-many
        compiles, served from the compilation cache)."""
        floor = _MIN_PAGES_BUCKET
        ml_pages = next_power_of_2(
            cdiv(self.config.scheduler_config.max_model_len, self.page_size)
        )
        if ml_pages <= 256:
            floor = max(floor, ml_pages)
        return max(next_power_of_2(need), floor)

    # ---- tiered KV cache (ISSUE 14) ----
    @partial(jax.jit, static_argnames=("self",), donate_argnums=(1,))
    def _jit_write_kv_pages(self, leaf, idx, data):
        """Scatter restored page data back into the (donated) pool leaf
        in place — without donation XLA would copy the whole pool per
        layer.  ``idx`` is padded with the reserved page 0 (garbage by
        contract), so every restore batch of a size bucket shares one
        compiled program."""
        return leaf.at[:, idx].set(data)

    def _apply_kv_tier_ops(self, so: SchedulerOutput) -> float:
        """Apply a step's KV-tier spans BEFORE executing it: spills
        first (``jax.device_get`` the evicted pages to host DRAM before
        any step may overwrite them), then restores (``device_put`` the
        streamed-back pages into their freshly allocated homes before
        the step reads them).  Batched: one gather / one scatter per
        layer leaf per batch, off the jitted step itself.  Returns the
        wall seconds spent (the restore-stall observable)."""
        spills = getattr(so, "kv_spill_ops", None) or []
        restores = getattr(so, "kv_restore_ops", None) or []
        if (not spills and not restores) or self.kv_caches is None:
            return 0.0
        t0 = time.perf_counter()
        tree = jax.tree_util
        if spills:
            idx = jnp.asarray([p for p, _ in spills], jnp.int32)
            # device_get blocks until any in-flight step producing the
            # current pool has resolved — the page content captured is
            # exactly what the allocator registered.
            gathered = tree.tree_map(
                lambda leaf: np.asarray(jax.device_get(leaf[:, idx])),
                self.kv_caches,
            )
            for i, (_, slot) in enumerate(spills):
                self._host_kv[slot] = tree.tree_map(
                    lambda a: np.ascontiguousarray(a[:, i]), gathered
                )
        if restores:
            n = len(restores)
            npad = max(next_power_of_2(n), 1)
            pages = np.zeros(npad, np.int32)  # pad -> reserved page 0
            pages[:n] = [p for _, p in restores]
            idx = jnp.asarray(pages)
            # A missing slot is a protocol violation (the driver only
            # restores slots it spilled and never reuses one before its
            # restore shipped) — fail loudly, never serve garbage KV.
            datas = [self._host_kv.pop(s) for s, _ in restores]
            stacked = tree.tree_map(
                lambda *xs: np.stack(xs, axis=1), datas[0], *datas[1:]
            )
            kv_leaves, treedef = tree.tree_flatten(self.kv_caches)
            data_leaves, _ = tree.tree_flatten(stacked)
            new_leaves = []
            for leaf, dat in zip(kv_leaves, data_leaves):
                if npad > n:
                    pad = np.zeros(
                        (dat.shape[0], npad - n) + dat.shape[2:], dat.dtype
                    )
                    dat = np.concatenate([dat, pad], axis=1)
                new_leaves.append(
                    self._jit_write_kv_pages(leaf, idx, jnp.asarray(dat))
                )
            self.kv_caches = tree.tree_unflatten(treedef, new_leaves)
        return time.perf_counter() - t0

    # ---- KV-page export/import (disaggregated prefill, ISSUE 15) ----
    def export_kv_pages(
        self, page_ids: list[int], layer_start: int, layer_count: int
    ) -> dict:
        """Gather the KV content of ``page_ids`` for one per-layer chunk
        of the prefill→decode hand-off: the same batched
        ``jax.device_get`` the spill path uses (one gather per layer
        leaf, blocking until in-flight writes resolve, so content is
        exact), serialized with a per-layer sha256 so the receiving
        replica can verify every chunk before scattering it.

        Layer indexing is the flattened ``kv_caches`` leaf order — the
        exact inverse of ``import_kv_pages``.  Validated on single-host
        replicas (the standard disagg topology: one replica per
        host/slice, where ``device_get`` materializes the full logical
        array across local devices); multi-process meshes would need
        shard-aware reassembly.
        """
        import hashlib

        tree = jax.tree_util
        leaves, _ = tree.tree_flatten(self.kv_caches)
        num_layers = len(leaves)
        start = max(int(layer_start), 0)
        end = min(start + max(int(layer_count), 0), num_layers)
        idx = jnp.asarray(page_ids, jnp.int32)
        layers: list[dict] = []
        for i in range(start, end):
            arr = np.ascontiguousarray(
                np.asarray(jax.device_get(leaves[i][:, idx]))
            )
            data = arr.tobytes()
            layers.append(
                {
                    "index": i,
                    "num_layers": num_layers,
                    "shape": list(arr.shape),
                    "data": data,
                    "checksum": hashlib.sha256(data).hexdigest(),
                }
            )
        return {"num_layers": num_layers, "layers": layers}

    def import_kv_pages(self, page_ids: list[int], layers: list[dict]) -> dict:
        """Scatter received layer chunks into freshly reserved pages —
        the donated in-place write the restore path uses, with the
        page-content checksum verified BEFORE any byte lands.  The
        target pages are outside every index until the driver commits
        the transfer, so no step can be reading (or writing) them."""
        import hashlib

        tree = jax.tree_util
        leaves, treedef = tree.tree_flatten(self.kv_caches)
        n = len(page_ids)
        npad = max(next_power_of_2(n), 1)
        pages = np.zeros(npad, np.int32)  # pad -> reserved page 0
        pages[:n] = page_ids
        idx = jnp.asarray(pages)
        for layer in layers:
            data = layer["data"]
            if hashlib.sha256(data).hexdigest() != layer["checksum"]:
                return {
                    "ok": False,
                    "error": (
                        f"kv transfer checksum mismatch on layer "
                        f"{layer.get('index')}"
                    ),
                }
            i = int(layer["index"])
            leaf = leaves[i]
            arr = np.frombuffer(data, dtype=np.dtype(leaf.dtype)).reshape(
                tuple(layer["shape"])
            )
            if npad > n:
                pad = np.zeros(
                    (arr.shape[0], npad - n) + arr.shape[2:], arr.dtype
                )
                arr = np.concatenate([arr, pad], axis=1)
            leaves[i] = self._jit_write_kv_pages(
                leaf, idx, jnp.asarray(arr)
            )
        self.kv_caches = tree.tree_unflatten(treedef, leaves)
        return {"ok": True}

    def host_kv_stats(self) -> dict:
        """Host-tier occupancy (driver telemetry + leak assertions)."""
        total = 0
        for entry in self._host_kv.values():
            for leaf in jax.tree_util.tree_leaves(entry):
                total += leaf.nbytes
        return {"host_slots": len(self._host_kv), "host_bytes": total}

    # ---- per-step state mirroring ----
    def _apply_scheduler_deltas(self, so: SchedulerOutput) -> None:
        for req_id in so.finished_req_ids:
            self.requests.pop(req_id, None)
        for req_id in so.preempted_req_ids:
            self.requests.pop(req_id, None)
        for new in so.new_requests:
            self.requests[new.req_id] = CachedReqState(
                req_id=new.req_id,
                token_ids=list(new.prompt_token_ids),
                sampling_params=new.sampling_params,
                page_ids=list(new.page_ids),
                num_computed=new.num_computed_tokens,
                prefill_target=len(new.prompt_token_ids),
                num_prompt=new.num_prompt_tokens,
            )
        for cached in so.cached_requests:
            state = self.requests[cached.req_id]
            state.page_ids.extend(cached.new_page_ids)
            state.num_computed = cached.num_computed_tokens

    # ---- the step ----
    def execute_model(self, so: SchedulerOutput) -> ModelRunnerOutput:
        self._apply_scheduler_deltas(so)
        # KV-tier spans land before ANY path may touch their pages
        # (spills before the evicted page is rewritten, restores before
        # the attached chain is read).
        tier_s = self._apply_kv_tier_ops(so)
        if so.is_empty:
            return ModelRunnerOutput()
        if so.draft_token_ids:
            return self._execute_spec_step(so)
        if so.decode_steps > 1:
            return self._execute_decode_steps(so)
        self._decode_carry = None

        order = [c.req_id for c in so.cached_requests] + [
            n.req_id for n in so.new_requests
        ]
        states = [self.requests[r] for r in order]
        num_new = [so.num_scheduled_tokens[r] for r in order]

        t_real = sum(num_new)
        s_real = len(order)
        # dp is a power of two (validated at load), so power-of-two buckets
        # at least dp wide stay divisible for the dp input sharding.
        t_pad = max(next_power_of_2(t_real), _MIN_TOKEN_BUCKET, self._dp)
        s_pad = max(next_power_of_2(s_real), _MIN_SEQ_BUCKET, self._dp)
        max_pages = max(
            max((len(st.page_ids) for st in states), default=1), 1
        )
        pages_pad = self._pages_bucket(max_pages)

        tokens = np.zeros(t_pad, np.int32)
        positions = np.zeros(t_pad, np.int32)
        # Padding tokens point one past the last seq row: identifiable as
        # padding (kernels drop them); OOB gathers clip under jit.
        seq_ids = np.full(t_pad, s_pad, np.int32)
        slots = np.zeros(t_pad, np.int32)
        block_tables = np.zeros((s_pad, pages_pad), np.int32)
        seq_lens = np.zeros(s_pad, np.int32)
        logits_idx = np.zeros(s_pad, np.int32)
        chunk_starts = np.zeros(s_pad, np.int32)
        needs_sample = [False] * s_real

        cursor = 0
        for s, (state, n) in enumerate(zip(states, num_new)):
            lo, hi = state.num_computed, state.num_computed + n
            ids = state.token_ids[lo:hi]
            tokens[cursor : cursor + n] = ids
            pos = np.arange(lo, hi, dtype=np.int32)
            positions[cursor : cursor + n] = pos
            seq_ids[cursor : cursor + n] = s
            page_arr = np.asarray(state.page_ids, np.int32)
            slots[cursor : cursor + n] = (
                page_arr[pos // self.page_size] * self.page_size
                + pos % self.page_size
            )
            block_tables[s, : len(state.page_ids)] = page_arr
            seq_lens[s] = hi
            logits_idx[s] = cursor + n - 1
            chunk_starts[s] = lo
            needs_sample[s] = hi >= state.prefill_target
            cursor += n

        max_q_pad = max(next_power_of_2(max(num_new)), 1)
        smeta_np, flags = self._build_sampling_metadata(states, s_pad)

        if self._dp == 1:
            # One packed host→device transfer per step (see
            # pack_host_arrays).  Replicated across the mesh under tp.
            packed, pack_spec = pack_host_arrays(
                [
                    tokens, seq_ids, positions, slots, block_tables,
                    seq_lens, logits_idx, chunk_starts,
                    smeta_np.temperature, smeta_np.top_k, smeta_np.top_p,
                    smeta_np.min_p, smeta_np.repetition_penalty,
                    smeta_np.presence_penalty, smeta_np.frequency_penalty,
                    smeta_np.keys, smeta_np.prompt_tokens,
                    smeta_np.output_tokens,
                ]
            )
            if self.mesh is not None:
                packed = jax.device_put(
                    packed, NamedSharding(self.mesh, P())
                )
            statics = dict(spec=pack_spec, max_q_pad=max_q_pad, **flags)

            def _run_step():
                if self._aot.enabled:
                    return self._aot.call(
                        f"step:{sorted(statics.items())}",
                        partial(
                            type(self)._jit_step_packed.__wrapped__,
                            self,
                            **statics,
                        ),
                        (self.params, self.kv_caches, packed),
                        donate_args=(1,),
                    )
                return self._jit_step_packed(
                    self.params, self.kv_caches, packed, **statics
                )

            t_step0 = time.perf_counter()
            sampled, logprobs, self.kv_caches = self._observed_call(
                "prefill", f"{sorted(statics.items())}", _run_step
            )
        else:
            meta = AttentionMetadata(
                q_seq_ids=jnp.asarray(seq_ids),
                q_positions=jnp.asarray(positions),
                slot_mapping=jnp.asarray(slots),
                block_tables=jnp.asarray(block_tables),
                seq_lens=jnp.asarray(seq_lens),
                logits_indices=jnp.asarray(logits_idx),
                chunk_starts=jnp.asarray(chunk_starts),
            )
            token_ids = jnp.asarray(tokens)
            smeta = smeta_np
            spec = self._input_spec
            token_ids = jax.device_put(token_ids, spec)
            meta = jax.tree.map(lambda x: jax.device_put(x, spec), meta)
            smeta = jax.tree.map(lambda x: jax.device_put(x, spec), smeta)
            t_step0 = time.perf_counter()
            sampled, logprobs, self.kv_caches = self._observed_call(
                "prefill",
                f"t={t_pad},s={s_pad},p={pages_pad},q={max_q_pad},"
                f"{sorted(flags.items())}",
                lambda: self._jit_step(
                    self.params,
                    self.kv_caches,
                    token_ids,
                    meta,
                    smeta,
                    max_q_pad=max_q_pad,
                    **flags,
                ),
            )

        if logprobs is not None:
            sampled, logprobs = jax.device_get((sampled, logprobs))
            sampled = np.asarray(sampled)
            logprobs = np.asarray(logprobs)
        else:
            sampled = np.asarray(jax.device_get(sampled))
        self._record_step_bw(
            time.perf_counter() - t_step0, int(seq_lens.sum())
        )

        out = ModelRunnerOutput()
        # Restore-bearing steps are always admission (blocking) steps,
        # so the stall lands on the output the engine actually reads.
        out.kv_tier_seconds = tier_s
        for s, (state, n) in enumerate(zip(states, num_new)):
            state.num_computed += n
            if not needs_sample[s]:
                out.num_prompt_tokens_processed[state.req_id] = n
                continue
            tok = int(sampled[s])
            state.token_ids.append(tok)
            out.sampled_token_ids[state.req_id] = [tok]
            nlp = state.sampling_params.logprobs
            if nlp is not None and logprobs is not None:
                row = logprobs[s]
                nlp = min(nlp, row.shape[-1] - 1)
                top = np.argpartition(row, -max(nlp, 1))[-max(nlp, 1) :]
                d = {int(i): float(row[i]) for i in top}
                d[tok] = float(row[tok])
                out.logprobs[state.req_id] = [d]
        return out

    def _build_sampling_metadata(
        self,
        states: list[CachedReqState],
        s_pad: int,
        extra_output_len: int = 1,
    ) -> tuple[SamplingMetadata, dict]:
        vocab = self.model.vocab_size
        temp = np.zeros(s_pad, np.float32)
        top_k = np.full(s_pad, vocab, np.int32)
        top_p = np.ones(s_pad, np.float32)
        min_p = np.zeros(s_pad, np.float32)
        rep = np.ones(s_pad, np.float32)
        pres = np.zeros(s_pad, np.float32)
        freq = np.zeros(s_pad, np.float32)
        keys = np.zeros((s_pad, 2), np.uint32)
        do_pen = False
        do_tkp = False
        want_lp = False
        for s, st in enumerate(states):
            sp = st.sampling_params
            temp[s] = sp.temperature
            if sp.top_k > 0:
                top_k[s] = sp.top_k
            top_p[s] = sp.top_p
            min_p[s] = sp.min_p
            rep[s] = sp.repetition_penalty
            pres[s] = sp.presence_penalty
            freq[s] = sp.frequency_penalty
            seed = sp.seed if sp.seed is not None else self.global_seed
            keys[s, 0] = np.uint32(
                (seed ^ zlib.crc32(st.req_id.encode())) & 0xFFFFFFFF
            )
            keys[s, 1] = np.uint32(len(st.token_ids))
            do_pen |= _needs_penalties(sp)
            do_tkp |= _needs_top_k_p(sp)
            want_lp |= sp.logprobs is not None

        if do_pen:
            lp = max(
                next_power_of_2(max(st.num_prompt for st in states)),
                _MIN_TOKEN_BUCKET,
            )
            lo = max(
                next_power_of_2(
                    max(len(st.token_ids) - st.num_prompt for st in states)
                    + extra_output_len
                ),
                _MIN_TOKEN_BUCKET,
            )
            prompt_toks = np.full((s_pad, lp), -1, np.int32)
            output_toks = np.full((s_pad, lo), -1, np.int32)
            for s, st in enumerate(states):
                p = st.token_ids[: st.num_prompt][:lp]
                o = st.token_ids[st.num_prompt :][:lo]
                prompt_toks[s, : len(p)] = p
                output_toks[s, : len(o)] = o
        else:
            prompt_toks = np.full((s_pad, 1), -1, np.int32)
            output_toks = np.full((s_pad, 1), -1, np.int32)

        # Numpy leaves: the packed path ships them in one fused buffer;
        # the unpacked path converts at the jit boundary.
        smeta = SamplingMetadata(
            temperature=temp,
            top_k=top_k,
            top_p=top_p,
            min_p=min_p,
            repetition_penalty=rep,
            presence_penalty=pres,
            frequency_penalty=freq,
            keys=keys,
            prompt_tokens=prompt_toks,
            output_tokens=output_toks,
        )
        flags = dict(
            do_penalties=do_pen,
            do_top_k_p=do_tkp,
            return_logprobs=want_lp,
        )
        return smeta, flags

    def _step_core(
        self,
        params,
        kv_caches,
        token_ids,
        meta: AttentionMetadata,
        smeta: SamplingMetadata,
        max_q_pad: int,
        do_penalties: bool,
        do_top_k_p: bool,
        return_logprobs: bool,
    ):
        attn_fn = self._attn_fn
        if getattr(attn_fn, "needs_max_q", False):
            attn_fn = partial(attn_fn, max_q=max_q_pad)
        logits, kv_caches = self.model.forward(
            params,
            token_ids,
            kv_caches,
            meta,
            attn_fn=attn_fn,
            kv_write_fn=self._kv_write_fn,
        )
        tokens, logprobs = sample(
            logits,
            smeta,
            do_penalties=do_penalties,
            do_top_k_p=do_top_k_p,
            return_logprobs=return_logprobs,
        )
        return tokens, logprobs, kv_caches

    @partial(
        jax.jit,
        static_argnames=(
            "self",
            "max_q_pad",
            "do_penalties",
            "do_top_k_p",
            "return_logprobs",
        ),
        donate_argnums=(2,),
    )
    def _jit_step(
        self,
        params,
        kv_caches,
        token_ids,
        meta: AttentionMetadata,
        smeta: SamplingMetadata,
        *,
        max_q_pad: int,
        do_penalties: bool,
        do_top_k_p: bool,
        return_logprobs: bool,
    ):
        return self._step_core(
            params, kv_caches, token_ids, meta, smeta,
            max_q_pad, do_penalties, do_top_k_p, return_logprobs,
        )

    @partial(
        jax.jit,
        static_argnames=(
            "self",
            "spec",
            "max_q_pad",
            "do_penalties",
            "do_top_k_p",
            "return_logprobs",
        ),
        donate_argnums=(2,),
    )
    def _jit_step_packed(
        self,
        params,
        kv_caches,
        packed,
        *,
        spec: tuple,
        max_q_pad: int,
        do_penalties: bool,
        do_top_k_p: bool,
        return_logprobs: bool,
    ):
        (
            tokens, seq_ids, positions, slots, block_tables, seq_lens,
            logits_idx, chunk_starts, temp, top_k, top_p, min_p, rep,
            pres, freq, keys, prompt_toks, out_toks,
        ) = unpack_device_arrays(packed, spec)
        meta = AttentionMetadata(
            q_seq_ids=seq_ids,
            q_positions=positions,
            slot_mapping=slots,
            block_tables=block_tables,
            seq_lens=seq_lens,
            logits_indices=logits_idx,
            chunk_starts=chunk_starts,
        )
        smeta = SamplingMetadata(
            temperature=temp,
            top_k=top_k,
            top_p=top_p,
            min_p=min_p,
            repetition_penalty=rep,
            presence_penalty=pres,
            frequency_penalty=freq,
            keys=keys,
            prompt_tokens=prompt_toks,
            output_tokens=out_toks,
        )
        return self._step_core(
            params, kv_caches, tokens, meta, smeta,
            max_q_pad, do_penalties, do_top_k_p, return_logprobs,
        )

    # ---- speculative verify pass (SchedulerOutput.draft_token_ids) ----
    def _spec_kp1(self) -> int:
        """Static verify-window width: the configured max drafts + 1
        bonus column, padded to a power of two, so every spec step of a
        config shares ONE compiled gather/accept shape regardless of
        how many drafts each request actually found."""
        return max(
            next_power_of_2(
                self.config.scheduler_config.spec_ngram_k + 1
            ),
            2,
        )

    def _execute_spec_step(self, so: SchedulerOutput) -> ModelRunnerOutput:
        """Verify every request's drafted tokens in ONE fused dispatch
        (ISSUE 11): feed ``[input token, d_1..d_d]`` per sequence
        through the single-pass forward (teacher-forced; causal within
        the window exactly like a prefill chunk), gather logits at
        EVERY window position, and let the greedy accept kernel keep
        the longest draft prefix matching the argmax chain plus one
        bonus token.  One weight+KV HBM pass buys up to K+1 tokens
        instead of one.  KV rows written for rejected drafts sit past
        the reconciled cursor and are overwritten in place by the next
        window — never registered by the prefix cache, never read by
        later attention (seq_lens follows the accepted cursor)."""
        self._decode_carry = None
        order = [c.req_id for c in so.cached_requests]
        states = [self.requests[r] for r in order]
        num_new = [so.num_scheduled_tokens[r] for r in order]
        drafts = so.draft_token_ids

        t_real = sum(num_new)
        s_real = len(order)
        # Sequence bucket PINNED like the fused-decode path (batch
        # growth/shrink never recompiles) and max_q pinned to the
        # verify-window width (per-request draft counts never
        # recompile): the only dynamic shape left is the power-of-2
        # token bucket — log-many programs, all pre-compiled by
        # warmup_decode when spec is on.
        t_pad = max(next_power_of_2(t_real), _MIN_TOKEN_BUCKET)
        s_pad = self._seq_bucket()
        kp1 = self._spec_kp1()
        max_pages = max(max(len(st.page_ids) for st in states), 1)
        pages_pad = self._pages_bucket(max_pages)

        tokens = np.zeros(t_pad, np.int32)
        positions = np.zeros(t_pad, np.int32)
        seq_ids = np.full(t_pad, s_pad, np.int32)
        slots = np.zeros(t_pad, np.int32)
        block_tables = np.zeros((s_pad, pages_pad), np.int32)
        seq_lens = np.zeros(s_pad, np.int32)
        chunk_starts = np.zeros(s_pad, np.int32)
        # Logits rows gathered per (sequence, window column); columns
        # past a short window re-gather its last row — the accept
        # kernel masks them via n_drafts.
        verify_idx = np.zeros((s_pad, kp1), np.int32)
        draft_mat = np.full((s_pad, kp1 - 1), -1, np.int32)
        n_drafts = np.zeros(s_pad, np.int32)

        cursor = 0
        for s, (state, n) in enumerate(zip(states, num_new)):
            lo = state.num_computed
            assert lo == len(state.token_ids) - 1, (
                "spec verify dispatched without the host-current last "
                "token (pipeline must be drained)"
            )
            d = drafts.get(state.req_id, [])
            window = [state.token_ids[lo], *d]
            assert len(window) == n, (state.req_id, len(window), n)
            tokens[cursor : cursor + n] = window
            pos = np.arange(lo, lo + n, dtype=np.int32)
            positions[cursor : cursor + n] = pos
            seq_ids[cursor : cursor + n] = s
            page_arr = np.asarray(state.page_ids, np.int32)
            slots[cursor : cursor + n] = (
                page_arr[pos // self.page_size] * self.page_size
                + pos % self.page_size
            )
            block_tables[s, : len(state.page_ids)] = page_arr
            seq_lens[s] = lo + n
            chunk_starts[s] = lo
            verify_idx[s, :] = cursor + np.minimum(np.arange(kp1), n - 1)
            draft_mat[s, : len(d)] = d
            n_drafts[s] = len(d)
            cursor += n

        max_q_pad = kp1
        packed, pack_spec = pack_host_arrays(
            [
                tokens, seq_ids, positions, slots, block_tables,
                seq_lens, chunk_starts, verify_idx, draft_mat, n_drafts,
            ]
        )
        if self.mesh is not None:
            packed = jax.device_put(packed, NamedSharding(self.mesh, P()))
        statics = dict(spec=pack_spec, max_q_pad=max_q_pad)

        def _run_spec():
            if self._aot.enabled:
                return self._aot.call(
                    f"spec_step:{sorted(statics.items())}",
                    partial(
                        type(self)._jit_spec_step.__wrapped__,
                        self,
                        **statics,
                    ),
                    (self.params, self.kv_caches, packed),
                    donate_args=(1,),
                )
            return self._jit_spec_step(
                self.params, self.kv_caches, packed, **statics
            )

        t_step0 = time.perf_counter()
        toks, n_emit, self.kv_caches = self._observed_call(
            "spec", f"{sorted(statics.items())}", _run_spec
        )
        toks, n_emit = jax.device_get((toks, n_emit))
        # A verify window streams weights+KV ONCE for up to K+1 tokens —
        # the roofline asymmetry spec decode exists to exploit.
        self._record_step_bw(
            time.perf_counter() - t_step0, int(seq_lens.sum())
        )
        toks = np.asarray(toks)
        n_emit = np.asarray(n_emit)

        out = ModelRunnerOutput()
        for s, (state, n) in enumerate(zip(states, num_new)):
            m = min(int(n_emit[s]), n)
            seq_toks = [int(t) for t in toks[s, :m]]
            # The deltas set num_computed to the window base; advance by
            # the EMITTED count (input + accepted drafts), mirroring the
            # scheduler's update_from_output reconciliation.
            state.num_computed += m
            state.token_ids.extend(seq_toks)
            out.sampled_token_ids[state.req_id] = seq_toks
        return out

    @partial(
        jax.jit,
        static_argnames=("self", "spec", "max_q_pad"),
        donate_argnums=(2,),
    )
    def _jit_spec_step(
        self,
        params,
        kv_caches,
        packed,
        *,
        spec: tuple,
        max_q_pad: int,
    ):
        (
            tokens, seq_ids, positions, slots, block_tables, seq_lens,
            chunk_starts, verify_idx, draft_mat, n_drafts,
        ) = unpack_device_arrays(packed, spec)
        s_pad, kp1 = verify_idx.shape
        meta = AttentionMetadata(
            q_seq_ids=seq_ids,
            q_positions=positions,
            slot_mapping=slots,
            block_tables=block_tables,
            seq_lens=seq_lens,
            logits_indices=verify_idx.reshape(-1),
            chunk_starts=chunk_starts,
        )
        attn_fn = self._attn_fn
        if getattr(attn_fn, "needs_max_q", False):
            attn_fn = partial(attn_fn, max_q=max_q_pad)
        logits, kv_caches = self.model.forward(
            params,
            tokens,
            kv_caches,
            meta,
            attn_fn=attn_fn,
            kv_write_fn=self._kv_write_fn,
        )
        toks, n_emit = spec_greedy_accept(
            logits.reshape(s_pad, kp1, -1), draft_mat, n_drafts
        )
        return toks, n_emit, kv_caches

    # ---- fused multi-step decode (SchedulerOutput.decode_steps > 1) ----
    def _execute_decode_steps(self, so: SchedulerOutput) -> ModelRunnerOutput:
        """Run `so.decode_steps` decode micro-steps in ONE device dispatch
        (a lax.scan feeding each sampled token back in).  Amortizes the
        host round trip the reference pays per scheduler step
        (launch.py:322-343) by K — the TPU-first redesign SURVEY.md §3.3
        calls for."""
        k_steps = so.decode_steps
        order = tuple(c.req_id for c in so.cached_requests)
        states = [self.requests[r] for r in order]
        # Per-sequence scheduled token counts: a request whose remaining
        # budget is under k_steps runs its first n micro-steps and is
        # MASKED for the rest (queries dropped, KV writes routed to the
        # dump page, sampled tokens discarded) — the scan length stays
        # the single compiled k_steps program (see Scheduler.schedule).
        num_new = {c.req_id: c.num_new_tokens for c in so.cached_requests}
        # Thread-interleaving invariant (engine thread here vs a prior
        # dispatch's resolve() on the executor's resolver thread): both
        # may touch CachedReqState concurrently, which is safe because
        # (a) resolve() writes num_computed as an ABSOLUTE value equal
        # to its base_lens + k, which the host_current check below
        # treats identically whether it reads the pre- or post-resolve
        # value (both outcomes converge to the same dispatched token:
        # either token_ids[-1] already holds it or the device carry
        # does), and (b) penalties/logprobs — the only consumers of
        # token_ids contents — are excluded by _pipeline_safe when a
        # dispatch is in flight.  CPython's GIL makes each individual
        # list/int access atomic.  Do not add reads of st.token_ids
        # beyond the patterns below without revisiting this.
        s_real = len(order)
        s_pad = self._seq_bucket()
        max_pages = max(max(len(st.page_ids) for st in states), 1)
        pages_pad = self._pages_bucket(max_pages)

        tokens = np.zeros(s_pad, np.int32)
        base_lens = np.zeros(s_pad, np.int32)
        valid = np.zeros(s_pad, np.int32)
        n_active = np.zeros(s_pad, np.int32)
        block_tables = np.zeros((s_pad, pages_pad), np.int32)
        out_lens = np.zeros(s_pad, np.int32)
        host_current = True
        for s, st in enumerate(states):
            base_lens[s] = st.num_computed
            valid[s] = 1
            n_active[s] = num_new[st.req_id]
            block_tables[s, : len(st.page_ids)] = st.page_ids
            out_lens[s] = len(st.token_ids) - st.num_prompt
            if st.num_computed == len(st.token_ids) - 1:
                tokens[s] = st.token_ids[-1]
            else:
                # Results of a previous dispatch are still in flight; the
                # real token values live in the device carry.
                host_current = False

        use_carry = False
        if not host_current:
            carry = self._decode_carry
            assert (
                carry is not None
                and carry[0] == order
                and np.array_equal(carry[1][:s_real], base_lens[:s_real])
            ), "pipelined decode dispatch without a matching device carry"
            use_carry = True
        carry_tok = (
            self._decode_carry[2]
            if use_carry
            else jnp.zeros(s_pad, jnp.int32)
        )
        # Traced (not static) so both carry variants share ONE compiled
        # program — the r4 static use_carry doubled every warmup/compile.
        use_carry_flag = np.full(1, int(use_carry), np.int32)

        smeta_np, flags = self._build_sampling_metadata(
            states, s_pad, extra_output_len=k_steps + 1
        )
        assert not flags["return_logprobs"], (
            "scheduler must not fuse decode steps when logprobs are on"
        )
        assert not (use_carry and flags["do_penalties"]), (
            "pipelined decode cannot run with penalties (stale host state)"
        )
        # PRNG stream position must follow the device-side token count,
        # which host token_ids may lag behind under pipelining.
        smeta_np.keys[:s_real, 1] = (base_lens[:s_real] + 1).astype(np.uint32)
        packed, pack_spec = pack_host_arrays(
            [
                tokens, base_lens, valid, n_active, use_carry_flag,
                block_tables, out_lens,
                smeta_np.temperature, smeta_np.top_k, smeta_np.top_p,
                smeta_np.min_p, smeta_np.repetition_penalty,
                smeta_np.presence_penalty, smeta_np.frequency_penalty,
                smeta_np.keys, smeta_np.prompt_tokens,
                smeta_np.output_tokens,
            ]
        )
        if self.mesh is not None:
            packed = jax.device_put(packed, NamedSharding(self.mesh, P()))
        statics = dict(
            spec=pack_spec,
            k_steps=k_steps,
            do_penalties=flags["do_penalties"],
            do_top_k_p=flags["do_top_k_p"],
        )
        def _run_decode():
            if self._aot.enabled:
                return self._aot.call(
                    f"decode_steps:{sorted(statics.items())}",
                    partial(
                        type(self)._jit_decode_steps.__wrapped__,
                        self,
                        **statics,
                    ),
                    (self.params, self.kv_caches, packed, carry_tok),
                    donate_args=(1,),
                )
            return self._jit_decode_steps(
                self.params, self.kv_caches, packed, carry_tok, **statics
            )

        t_step0 = time.perf_counter()
        toks, carry_out, self.kv_caches = self._observed_call(
            "decode", f"{sorted(statics.items())}", _run_decode
        )
        # Each sequence's LAST VALID token stays on device as the next
        # dispatch's input (under-K tails: token n_active-1, not K-1).
        self._decode_carry = (order, base_lens + n_active, carry_out)
        kv_tokens_scanned = int(base_lens.sum())

        def resolve() -> ModelRunnerOutput:
            host_toks = np.asarray(jax.device_get(toks))  # [K, s_pad]
            # K micro-steps = K weights+KV HBM passes.  Wall time spans
            # dispatch→resolve (includes any pipeline overlap — an
            # estimate, like the byte count).
            self._record_step_bw(
                time.perf_counter() - t_step0, kv_tokens_scanned, k_steps
            )
            out = ModelRunnerOutput()
            for s, st in enumerate(states):
                n = int(n_active[s])
                seq_toks = [int(t) for t in host_toks[:n, s]]
                # Absolute (not +=): scheduler deltas for a pipelined
                # next dispatch may already have advanced num_computed.
                st.num_computed = int(base_lens[s]) + n
                st.token_ids.extend(seq_toks)
                out.sampled_token_ids[st.req_id] = seq_toks
            return out

        return resolve

    @partial(
        jax.jit,
        static_argnames=(
            "self",
            "spec",
            "k_steps",
            "do_penalties",
            "do_top_k_p",
        ),
        donate_argnums=(2,),
    )
    def _jit_decode_steps(
        self,
        params,
        kv_caches,
        packed,
        carry_tok,
        *,
        spec: tuple,
        k_steps: int,
        do_penalties: bool,
        do_top_k_p: bool,
    ):
        (
            tokens, base_lens, valid, n_active, use_carry_flag,
            block_tables, out_lens, temp, top_k,
            top_p, min_p, rep, pres, freq, keys, prompt_toks, out_toks,
        ) = unpack_device_arrays(packed, spec)
        tokens = jnp.where(use_carry_flag[0] > 0, carry_tok, tokens)
        s_pad = tokens.shape[0]
        rows = jnp.arange(s_pad, dtype=jnp.int32)
        page_size = self.page_size
        attn_fn = self._attn_fn
        if getattr(attn_fn, "needs_max_q", False):
            attn_fn = partial(attn_fn, max_q=1)
        staged = self._staged_decode
        if staged:
            # Staged decode writes: micro-step K/V rows go to a dense
            # per-layer side buffer (one in-place DUS per layer per
            # step); attention reads pool (positions < base) + side
            # (positions base..base+i); the pool is flushed once after
            # the scan.  Removes the per-row pool writes (~1.8 µs each)
            # from the micro-step path.
            base_valid = jnp.where(valid > 0, base_lens, 0)

            def make_entry(kv, side, i):
                return (kv, side, i)

            def staged_write(entry, k, v, slot_mapping):
                kv, side, i = entry
                t = k.shape[0]
                hd = side.shape[-1]
                rows_kv = jnp.stack(
                    [k.reshape(t, -1), v.reshape(t, -1)], axis=1
                ).astype(side.dtype)
                if rows_kv.shape[-1] < hd:
                    rows_kv = jnp.pad(
                        rows_kv,
                        [(0, 0), (0, 0), (0, hd - rows_kv.shape[-1])],
                    )
                side = jax.lax.dynamic_update_slice(
                    side, rows_kv[:, :, None, :], (0, 0, i, 0)
                )
                return (kv, side, i)

            def staged_attn(q, entry, meta, **kw):
                kv, side, i = entry
                return attn_fn(
                    q, kv, meta,
                    side_kv=side,
                    side_len=jnp.reshape(i + 1, (1,)),
                    **kw,
                )

        def body(carry, i):
            kv, sides, tok, out_buf = carry
            pos = base_lens + i
            # Micro-step i runs only sequences with i < n_active: under-K
            # tails drop their queries (id == s_pad, the kernels' drop
            # convention, like padding rows) and route their KV writes to
            # the reserved dump page 0.  pos for a masked row may step
            # past the sequence's page allocation, so the page index is
            # masked BEFORE the table gather (jit clips OOB gathers to
            # the last column — a real page).
            live = (valid > 0) & (i < n_active)
            page_idx = jnp.where(live, pos // page_size, 0)
            meta = AttentionMetadata(
                q_seq_ids=jnp.where(live, rows, s_pad),
                q_positions=pos,
                slot_mapping=jnp.where(
                    live,
                    block_tables[rows, page_idx] * page_size
                    + pos % page_size,
                    0,
                ),
                block_tables=block_tables,
                # Staged: seq_lens is the POOL-resident length (base);
                # this dispatch's rows are covered by the side buffer.
                seq_lens=(
                    base_valid
                    if staged
                    else jnp.where(live, pos + 1, 0)
                ),
                logits_indices=rows,
                chunk_starts=pos,
            )
            smeta = SamplingMetadata(
                temperature=temp,
                top_k=top_k,
                top_p=top_p,
                min_p=min_p,
                repetition_penalty=rep,
                presence_penalty=pres,
                frequency_penalty=freq,
                # Per-token PRNG stream: low word advances with position,
                # matching the single-step path's keys[s,1]=len(tokens).
                keys=jnp.stack(
                    [keys[:, 0], keys[:, 1] + i.astype(jnp.uint32)], axis=1
                ),
                prompt_tokens=prompt_toks,
                output_tokens=out_buf,
            )
            # Barrier blocks XLA's loop-invariant code motion on the
            # params: without it, quantized weights get dequantized ONCE
            # outside the scan — materializing the full bf16 model in
            # HBM (OOM at serving pool sizes) and erasing the int8
            # bandwidth win.  With it, the int8 bytes stream per
            # micro-step and the dequant fuses into the matmuls.
            params_i = jax.lax.optimization_barrier(params)
            if staged:
                entries = [
                    make_entry(kv_l, side_l, i)
                    for kv_l, side_l in zip(kv, sides)
                ]
                logits, new_entries = self.model.forward(
                    params_i,
                    tok,
                    entries,
                    meta,
                    attn_fn=staged_attn,
                    kv_write_fn=staged_write,
                )
                kv = [e[0] for e in new_entries]
                sides = [e[1] for e in new_entries]
            else:
                logits, kv = self.model.forward(
                    params_i,
                    tok,
                    kv,
                    meta,
                    attn_fn=attn_fn,
                    kv_write_fn=self._kv_write_fn,
                )
            new_tok, _ = sample(
                logits,
                smeta,
                do_penalties=do_penalties,
                do_top_k_p=do_top_k_p,
                return_logprobs=False,
            )
            if do_penalties:
                # Masked rows scatter out of bounds (dropped).
                out_buf = out_buf.at[
                    rows,
                    jnp.where(live, out_lens + i, out_buf.shape[1]),
                ].set(new_tok, mode="drop")
            return (kv, sides, new_tok, out_buf), new_tok

        if staged:
            # Model dtype even for int8 pools: rows quantize ONCE per
            # dispatch at flush, not per micro-step.
            def side0(kv_l):
                data = kv_l[0] if isinstance(kv_l, tuple) else kv_l
                dt = (
                    self.model.dtype
                    if isinstance(kv_l, tuple)
                    else data.dtype
                )
                return jnp.zeros((s_pad, 2, k_steps, data.shape[-1]), dt)

            sides0 = [side0(kv_l) for kv_l in kv_caches]
        else:
            sides0 = [jnp.zeros((), jnp.int32) for _ in kv_caches]
        (kv_caches, sides_out, _, _), toks = jax.lax.scan(
            body,
            (kv_caches, sides0, tokens, out_toks),
            jnp.arange(k_steps, dtype=jnp.int32),
        )
        if staged:
            # Per-sequence flush lengths: under-K tails staged only
            # n_active rows; columns past that are garbage.
            kv_caches = [
                self._kv_flush_fn(
                    kv_l, side_l, block_tables, base_valid, n_active
                )
                for kv_l, side_l in zip(kv_caches, sides_out)
            ]
        # Next dispatch's input token: each sequence's last VALID one.
        carry_out = toks[
            jnp.clip(n_active - 1, 0, k_steps - 1), rows
        ]
        return toks, carry_out, kv_caches
