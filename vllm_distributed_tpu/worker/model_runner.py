"""Model runner: SchedulerOutput → one fused, jitted device step.

Replaces the reference's GPU model runner (driven via
`collective_rpc("execute_model")`, launch.py:322-343) with a TPU-first
design (SURVEY.md §7): the whole step — embedding, every layer, paged KV
scatter, attention, and sampling — is ONE compiled XLA program with the KV
cache donated, so steady state is a single dispatch per scheduler step and
no per-layer host round trips.

Static-shape discipline (XLA compiles per shape): token count, sequence
count, pages-per-seq, and penalty-history lengths are padded to
power-of-two buckets, so the number of distinct compiled programs stays
logarithmic in batch size (SURVEY.md §7 hard part #2).

Workers mirror request state (token ids, page tables, cursors) so each
step's input is only the scheduler's delta — the control-plane economy the
reference gets by shipping SchedulerOutput, not tensors (SURVEY.md §2.5).
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass, field
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.engine.scheduler import SchedulerOutput
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.model_loader import get_model
from vllm_distributed_tpu.ops.attention import (
    AttentionMetadata,
    paged_attention_reference,
)
from vllm_distributed_tpu.ops.sampling import SamplingMetadata, sample
from vllm_distributed_tpu.outputs import ModelRunnerOutput
from vllm_distributed_tpu.sampling_params import SamplingParams
from vllm_distributed_tpu.utils import cdiv, next_power_of_2, round_up

logger = init_logger(__name__)

_MIN_TOKEN_BUCKET = 16
_MIN_SEQ_BUCKET = 8
_MIN_PAGES_BUCKET = 8


@dataclass
class CachedReqState:
    req_id: str
    token_ids: list[int]  # prompt + everything sampled so far
    sampling_params: SamplingParams
    page_ids: list[int]
    num_computed: int
    prefill_target: int  # sample only once computed tokens reach this
    num_prompt: int  # true prompt/output boundary (stable across preemption)


def _needs_top_k_p(sp: SamplingParams) -> bool:
    return sp.top_k > 0 or sp.top_p < 1.0 or sp.min_p > 0.0


def _needs_penalties(sp: SamplingParams) -> bool:
    return (
        sp.repetition_penalty != 1.0
        or sp.presence_penalty != 0.0
        or sp.frequency_penalty != 0.0
    )


class ModelRunner:
    def __init__(
        self,
        config: EngineConfig,
        mesh: Any = None,
        attn_backend: str = "auto",
    ) -> None:
        self.config = config
        self.mesh = mesh
        self.page_size = config.cache_config.page_size
        self.global_seed = config.model_config.seed
        self.model = None
        self.params = None
        self.kv_caches: list | None = None
        self.requests: dict[str, CachedReqState] = {}
        self.attn_backend = attn_backend
        self._attn_fn = None
        # Input sharding (set at load): step inputs shard their leading
        # dim over the mesh's "dp" axis; with dp=1 they are replicated.
        self._input_spec = None
        self._dp = 1

    # ---- lifecycle (the collective_rpc verbs, launch.py:290-292) ----
    def load_model(self, load_format: str = "auto") -> None:
        self.model, self.params = get_model(
            self.config.model_config, load_format=load_format, mesh=self.mesh
        )
        self._attn_fn = self._pick_attn_fn()
        if self.mesh is not None:
            self._dp = self.mesh.shape.get("dp", 1)
            if self._dp & (self._dp - 1):
                raise ValueError(
                    f"dp axis size must be a power of 2, got {self._dp} "
                    "(power-of-two shape buckets must stay divisible)"
                )
            axis = "dp" if self._dp > 1 else None
            self._input_spec = NamedSharding(self.mesh, P(axis))

    def _pick_attn_fn(self):
        backend = self.attn_backend
        if backend == "auto":
            backend = (
                "pallas" if jax.default_backend() == "tpu" else "reference"
            )
        if backend == "pallas":
            try:
                from vllm_distributed_tpu.ops.pallas.paged_attention import (
                    paged_attention,
                )

                return paged_attention
            except ImportError:
                logger.warning("pallas backend unavailable; using reference")
        return paged_attention_reference

    def kv_cache_bytes_per_page(self) -> int:
        m = self.model
        dtype_size = jnp.dtype(m.dtype).itemsize
        return (
            m.num_layers
            * 2
            * self.page_size
            * m.num_kv_heads
            * round_up(m.head_dim, 128)  # pool lane padding
            * dtype_size
        )

    def profile_num_pages(self) -> int:
        """Derive the KV pool size from free HBM (the analog of
        gpu_memory_utilization profiling in the inherited engine)."""
        cc = self.config.cache_config
        if cc.num_pages is not None:
            return cc.num_pages
        dev = jax.local_devices()[0]
        stats = getattr(dev, "memory_stats", lambda: None)()
        if not stats or "bytes_limit" not in stats:
            return 512  # CPU / no stats: small default for tests
        limit = int(stats["bytes_limit"] * cc.hbm_utilization)
        in_use = int(stats.get("bytes_in_use", 0))
        free = max(limit - in_use, 0)
        shards = 1
        if self.mesh is not None and "tp" in self.mesh.shape:
            shards = self.mesh.shape["tp"]
        per_device_page = self.kv_cache_bytes_per_page() // shards
        num_pages = max(free // max(per_device_page, 1), 16)
        logger.info(
            "KV pool: %d pages × %d tokens (%.2f GiB of %.2f GiB free HBM)",
            num_pages,
            self.page_size,
            num_pages * per_device_page / 2**30,
            free / 2**30,
        )
        return int(num_pages)

    def init_kv_cache(self, num_pages: int) -> None:
        m = self.model
        self.num_pages = num_pages
        # Head-major pool: [Hkv, P, page, D] (see ops/attention.py layout);
        # head dim lane-padded to 128 for DMA-aligned Pallas page copies.
        d_pad = round_up(m.head_dim, 128)
        shape = (m.num_kv_heads, num_pages, self.page_size, d_pad)
        sharding = None
        if self.mesh is not None:
            sharding = NamedSharding(self.mesh, m.kv_cache_spec())

        def alloc():
            z = jnp.zeros(shape, m.dtype)
            return jax.device_put(z, sharding) if sharding is not None else z

        self.kv_caches = [(alloc(), alloc()) for _ in range(m.num_layers)]

    # ---- per-step state mirroring ----
    def _apply_scheduler_deltas(self, so: SchedulerOutput) -> None:
        for req_id in so.finished_req_ids:
            self.requests.pop(req_id, None)
        for req_id in so.preempted_req_ids:
            self.requests.pop(req_id, None)
        for new in so.new_requests:
            self.requests[new.req_id] = CachedReqState(
                req_id=new.req_id,
                token_ids=list(new.prompt_token_ids),
                sampling_params=new.sampling_params,
                page_ids=list(new.page_ids),
                num_computed=new.num_computed_tokens,
                prefill_target=len(new.prompt_token_ids),
                num_prompt=new.num_prompt_tokens,
            )
        for cached in so.cached_requests:
            state = self.requests[cached.req_id]
            state.page_ids.extend(cached.new_page_ids)
            state.num_computed = cached.num_computed_tokens

    # ---- the step ----
    def execute_model(self, so: SchedulerOutput) -> ModelRunnerOutput:
        self._apply_scheduler_deltas(so)
        if so.is_empty:
            return ModelRunnerOutput()

        order = [c.req_id for c in so.cached_requests] + [
            n.req_id for n in so.new_requests
        ]
        states = [self.requests[r] for r in order]
        num_new = [so.num_scheduled_tokens[r] for r in order]

        t_real = sum(num_new)
        s_real = len(order)
        # dp is a power of two (validated at load), so power-of-two buckets
        # at least dp wide stay divisible for the dp input sharding.
        t_pad = max(next_power_of_2(t_real), _MIN_TOKEN_BUCKET, self._dp)
        s_pad = max(next_power_of_2(s_real), _MIN_SEQ_BUCKET, self._dp)
        max_pages = max(
            max((len(st.page_ids) for st in states), default=1), 1
        )
        pages_pad = max(next_power_of_2(max_pages), _MIN_PAGES_BUCKET)

        tokens = np.zeros(t_pad, np.int32)
        positions = np.zeros(t_pad, np.int32)
        # Padding tokens point one past the last seq row: identifiable as
        # padding (kernels drop them); OOB gathers clip under jit.
        seq_ids = np.full(t_pad, s_pad, np.int32)
        slots = np.zeros(t_pad, np.int32)
        block_tables = np.zeros((s_pad, pages_pad), np.int32)
        seq_lens = np.zeros(s_pad, np.int32)
        logits_idx = np.zeros(s_pad, np.int32)
        chunk_starts = np.zeros(s_pad, np.int32)
        needs_sample = [False] * s_real

        cursor = 0
        for s, (state, n) in enumerate(zip(states, num_new)):
            lo, hi = state.num_computed, state.num_computed + n
            ids = state.token_ids[lo:hi]
            tokens[cursor : cursor + n] = ids
            pos = np.arange(lo, hi, dtype=np.int32)
            positions[cursor : cursor + n] = pos
            seq_ids[cursor : cursor + n] = s
            page_arr = np.asarray(state.page_ids, np.int32)
            slots[cursor : cursor + n] = (
                page_arr[pos // self.page_size] * self.page_size
                + pos % self.page_size
            )
            block_tables[s, : len(state.page_ids)] = page_arr
            seq_lens[s] = hi
            logits_idx[s] = cursor + n - 1
            chunk_starts[s] = lo
            needs_sample[s] = hi >= state.prefill_target
            cursor += n

        meta = AttentionMetadata(
            q_seq_ids=jnp.asarray(seq_ids),
            q_positions=jnp.asarray(positions),
            slot_mapping=jnp.asarray(slots),
            block_tables=jnp.asarray(block_tables),
            seq_lens=jnp.asarray(seq_lens),
            logits_indices=jnp.asarray(logits_idx),
            chunk_starts=jnp.asarray(chunk_starts),
        )
        max_q_pad = max(next_power_of_2(max(num_new)), 1)

        smeta, flags = self._build_sampling_metadata(states, s_pad)
        token_ids = jnp.asarray(tokens)

        if self.mesh is not None:
            spec = self._input_spec
            token_ids = jax.device_put(token_ids, spec)
            meta = jax.tree.map(lambda x: jax.device_put(x, spec), meta)
            smeta = jax.tree.map(lambda x: jax.device_put(x, spec), smeta)

        sampled, logprobs, self.kv_caches = self._jit_step(
            self.params,
            self.kv_caches,
            token_ids,
            meta,
            smeta,
            max_q_pad=max_q_pad,
            **flags,
        )

        sampled = np.asarray(jax.device_get(sampled))
        if logprobs is not None:
            logprobs = np.asarray(jax.device_get(logprobs))

        out = ModelRunnerOutput()
        for s, (state, n) in enumerate(zip(states, num_new)):
            state.num_computed += n
            if not needs_sample[s]:
                out.num_prompt_tokens_processed[state.req_id] = n
                continue
            tok = int(sampled[s])
            state.token_ids.append(tok)
            out.sampled_token_ids[state.req_id] = [tok]
            nlp = state.sampling_params.logprobs
            if nlp is not None and logprobs is not None:
                row = logprobs[s]
                nlp = min(nlp, row.shape[-1] - 1)
                top = np.argpartition(row, -max(nlp, 1))[-max(nlp, 1) :]
                d = {int(i): float(row[i]) for i in top}
                d[tok] = float(row[tok])
                out.logprobs[state.req_id] = [d]
        return out

    def _build_sampling_metadata(
        self, states: list[CachedReqState], s_pad: int
    ) -> tuple[SamplingMetadata, dict]:
        vocab = self.model.vocab_size
        temp = np.zeros(s_pad, np.float32)
        top_k = np.full(s_pad, vocab, np.int32)
        top_p = np.ones(s_pad, np.float32)
        min_p = np.zeros(s_pad, np.float32)
        rep = np.ones(s_pad, np.float32)
        pres = np.zeros(s_pad, np.float32)
        freq = np.zeros(s_pad, np.float32)
        keys = np.zeros((s_pad, 2), np.uint32)
        do_pen = False
        do_tkp = False
        want_lp = False
        for s, st in enumerate(states):
            sp = st.sampling_params
            temp[s] = sp.temperature
            if sp.top_k > 0:
                top_k[s] = sp.top_k
            top_p[s] = sp.top_p
            min_p[s] = sp.min_p
            rep[s] = sp.repetition_penalty
            pres[s] = sp.presence_penalty
            freq[s] = sp.frequency_penalty
            seed = sp.seed if sp.seed is not None else self.global_seed
            keys[s, 0] = np.uint32(
                (seed ^ zlib.crc32(st.req_id.encode())) & 0xFFFFFFFF
            )
            keys[s, 1] = np.uint32(len(st.token_ids))
            do_pen |= _needs_penalties(sp)
            do_tkp |= _needs_top_k_p(sp)
            want_lp |= sp.logprobs is not None

        if do_pen:
            lp = max(
                next_power_of_2(max(st.num_prompt for st in states)),
                _MIN_TOKEN_BUCKET,
            )
            lo = max(
                next_power_of_2(
                    max(len(st.token_ids) - st.num_prompt for st in states)
                    + 1
                ),
                _MIN_TOKEN_BUCKET,
            )
            prompt_toks = np.full((s_pad, lp), -1, np.int32)
            output_toks = np.full((s_pad, lo), -1, np.int32)
            for s, st in enumerate(states):
                p = st.token_ids[: st.num_prompt][:lp]
                o = st.token_ids[st.num_prompt :][:lo]
                prompt_toks[s, : len(p)] = p
                output_toks[s, : len(o)] = o
        else:
            prompt_toks = np.full((s_pad, 1), -1, np.int32)
            output_toks = np.full((s_pad, 1), -1, np.int32)

        smeta = SamplingMetadata(
            temperature=jnp.asarray(temp),
            top_k=jnp.asarray(top_k),
            top_p=jnp.asarray(top_p),
            min_p=jnp.asarray(min_p),
            repetition_penalty=jnp.asarray(rep),
            presence_penalty=jnp.asarray(pres),
            frequency_penalty=jnp.asarray(freq),
            keys=jnp.asarray(keys),
            prompt_tokens=jnp.asarray(prompt_toks),
            output_tokens=jnp.asarray(output_toks),
        )
        flags = dict(
            do_penalties=do_pen,
            do_top_k_p=do_tkp,
            return_logprobs=want_lp,
        )
        return smeta, flags

    @partial(
        jax.jit,
        static_argnames=(
            "self",
            "max_q_pad",
            "do_penalties",
            "do_top_k_p",
            "return_logprobs",
        ),
        donate_argnums=(2,),
    )
    def _jit_step(
        self,
        params,
        kv_caches,
        token_ids,
        meta: AttentionMetadata,
        smeta: SamplingMetadata,
        *,
        max_q_pad: int,
        do_penalties: bool,
        do_top_k_p: bool,
        return_logprobs: bool,
    ):
        attn_fn = self._attn_fn
        if getattr(attn_fn, "needs_max_q", False):
            attn_fn = partial(attn_fn, max_q=max_q_pad)
        logits, kv_caches = self.model.forward(
            params, token_ids, kv_caches, meta, attn_fn=attn_fn
        )
        tokens, logprobs = sample(
            logits,
            smeta,
            do_penalties=do_penalties,
            do_top_k_p=do_top_k_p,
            return_logprobs=return_logprobs,
        )
        return tokens, logprobs, kv_caches
