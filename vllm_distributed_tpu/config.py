"""Engine configuration.

Capability parity with the config surface the reference drives
(SURVEY.md §2.3): ``AsyncEngineArgs.from_cli_args`` (launch.py:29,399) →
``EngineArgs.from_cli_args`` here; vLLM's VllmConfig with model / cache /
parallel / scheduler sub-configs → ``EngineConfig``.  The
``distributed_executor_backend`` field is pluggable with an executor class,
which is exactly how the reference injects its CustomExecutor
(launch.py:400-405).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any

from vllm_distributed_tpu import envs
from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)

_STR_DTYPE_TO_JAX = {
    "float32": "float32",
    "fp32": "float32",
    "bfloat16": "bfloat16",
    "bf16": "bfloat16",
    "float16": "bfloat16",  # TPUs have no fp16 MXU path; promote to bf16.
    "half": "bfloat16",
}


def _load_hf_config(model: str, trust_remote_code: bool = False):
    """Load a HuggingFace config.json for `model` (local dir or hub id)."""
    from transformers import AutoConfig

    return AutoConfig.from_pretrained(model, trust_remote_code=trust_remote_code)


@dataclass
class ModelConfig:
    model: str
    tokenizer: str | None = None
    dtype: str = "auto"
    seed: int = 0
    max_model_len: int | None = None
    trust_remote_code: bool = False
    hf_config: Any = None  # transformers PretrainedConfig, loaded lazily
    quantization: str | None = None
    skip_tokenizer_init: bool = False
    load_format: str = "auto"  # "auto" (safetensors) | "dummy"
    # Mirrored from ParallelConfig so MoE models can pick the expert
    # sharding layout (experts whole over "tp" vs split like dense MLPs).
    enable_expert_parallel: bool = False

    def __post_init__(self) -> None:
        if self.quantization is not None:
            from vllm_distributed_tpu.ops.quant import METHODS

            if self.quantization not in METHODS:
                raise ValueError(
                    f"unsupported quantization {self.quantization!r}; "
                    f"supported: {METHODS} (weight-only, quantized on load)"
                )
        if self.tokenizer is None:
            self.tokenizer = self.model
        if self.hf_config is None:
            self.hf_config = _load_hf_config(self.model, self.trust_remote_code)
        if self.dtype == "auto":
            torch_dtype = getattr(self.hf_config, "torch_dtype", None)
            name = str(torch_dtype).replace("torch.", "") if torch_dtype else "bfloat16"
            self.dtype = _STR_DTYPE_TO_JAX.get(name, "bfloat16")
        else:
            self.dtype = _STR_DTYPE_TO_JAX[self.dtype]
        derived_max = getattr(self.hf_config, "max_position_embeddings", 2048)
        if self.max_model_len is None:
            self.max_model_len = derived_max
        elif self.max_model_len > derived_max and not _supports_rope_scaling(
            self.hf_config
        ):
            logger.warning(
                "max_model_len %d exceeds the model's max_position_embeddings %d",
                self.max_model_len,
                derived_max,
            )

    # --- architecture helpers used by the engine/runner ---
    @property
    def architecture(self) -> str:
        archs = getattr(self.hf_config, "architectures", None) or []
        return archs[0] if archs else self.hf_config.model_type

    def get_num_layers(self) -> int:
        return getattr(
            self.hf_config,
            "num_hidden_layers",
            getattr(self.hf_config, "n_layer", None),
        )

    def get_hidden_size(self) -> int:
        return getattr(
            self.hf_config, "hidden_size", getattr(self.hf_config, "n_embd", None)
        )

    def get_num_attention_heads(self) -> int:
        return getattr(
            self.hf_config,
            "num_attention_heads",
            getattr(self.hf_config, "n_head", None),
        )

    def get_num_kv_heads(self) -> int:
        return getattr(
            self.hf_config, "num_key_value_heads", self.get_num_attention_heads()
        )

    def get_head_dim(self) -> int:
        head_dim = getattr(self.hf_config, "head_dim", None)
        if head_dim is not None:
            return head_dim
        return self.get_hidden_size() // self.get_num_attention_heads()

    def get_vocab_size(self) -> int:
        return self.hf_config.vocab_size


def _supports_rope_scaling(hf_config: Any) -> bool:
    return getattr(hf_config, "rope_scaling", None) is not None


@dataclass
class CacheConfig:
    """Paged KV cache configuration.

    `page_size` is tokens per page.  `num_pages` may be given explicitly
    (tests, CPU) or derived from free HBM at engine init
    (hbm_utilization, the analog of gpu_memory_utilization).
    """

    page_size: int = 16
    num_pages: int | None = None
    hbm_utilization: float = 0.9
    # Automatic prefix caching: content-addressed full pages with
    # ref-counted LRU reuse (block_manager.PrefixCachingAllocator).
    # Default off — the seed allocator path is byte-for-byte unchanged.
    enable_prefix_caching: bool = False
    # Prefix index structure (ISSUE 14): "radix" = radix tree over
    # token sequences with leaf-first cache-aware LRU eviction and the
    # optional host-DRAM spill tier; "flat" = the PR 1 hash-chain map
    # (the ablation baseline).  Ignored unless enable_prefix_caching.
    prefix_cache_index: str = "radix"
    # Host-DRAM spill tier (ISSUE 14): pages evicted from HBM spill to
    # a bounded host pool of this many pages and stream back ahead of a
    # prefill resume.  0 = off; radix index only.
    kv_spill_host_pages: int = 0
    # Restore-vs-recompute crossover in tokens: shorter host runs are
    # recomputed rather than restored.
    kv_spill_restore_min_tokens: int = 32
    # "auto" follows model dtype; "int8" quantizes the pool per (token,
    # kv head) — ~2x capacity, ~2x less attention HBM traffic; staged
    # decode rows quantize at flush, numerics run f32 in-kernel.
    cache_dtype: str = "auto"

    _CACHE_DTYPES = ("auto", "bfloat16", "float16", "float32", "int8")

    def __post_init__(self) -> None:
        if self.page_size & (self.page_size - 1):
            raise ValueError(f"page_size must be a power of 2, got {self.page_size}")
        if self.prefix_cache_index not in ("radix", "flat"):
            raise ValueError(
                f"unsupported prefix_cache_index "
                f"{self.prefix_cache_index!r}; supported: radix | flat"
            )
        if self.kv_spill_host_pages < 0:
            raise ValueError("kv_spill_host_pages must be >= 0")
        if self.kv_spill_host_pages > 0 and self.prefix_cache_index != "radix":
            raise ValueError(
                "the host-DRAM spill tier needs the radix prefix index "
                "(--prefix-cache-index radix)"
            )
        if self.cache_dtype == "fp8":
            raise ValueError(
                "fp8 KV cache is not supported on TPU (no fp8 VPU "
                "path on v5e) — use --kv-cache-dtype int8"
            )
        if self.cache_dtype not in self._CACHE_DTYPES:
            raise ValueError(
                f"unsupported kv-cache dtype {self.cache_dtype!r}; "
                f"supported: {self._CACHE_DTYPES}"
            )


@dataclass
class ParallelConfig:
    """Parallelism layout.

    The reference asserts world == tp × pp (launch.py:85-92).  Here the
    world is a JAX mesh with named axes; TP/EP/DP are sharding annotations
    over it (SURVEY.md §7 design stance), and world_size counts chips.
    """

    tensor_parallel_size: int = 1
    pipeline_parallel_size: int = 1
    data_parallel_size: int = 1
    enable_expert_parallel: bool = False
    # Pluggable executor class or name — the injection point the reference
    # uses for CustomExecutor (launch.py:400-405).
    distributed_executor_backend: Any = None
    # Multi-host topology
    num_hosts: int = 1
    host_id: int = 0
    coordinator_address: str | None = None

    @property
    def world_size(self) -> int:
        return (
            self.tensor_parallel_size
            * self.pipeline_parallel_size
            * self.data_parallel_size
        )

    def __post_init__(self) -> None:
        if self.pipeline_parallel_size != 1:
            raise ValueError(
                "pipeline parallelism is deliberately not supported on "
                "TPU: one jitted SPMD program spans every mesh device, so "
                "stage-level overlap between in-flight batches cannot "
                "happen inside a single program, and ICI bandwidth makes "
                "pure tensor parallelism scale to pod slices without PP's "
                "pipeline bubbles (the reference needed PP because its "
                "data plane was NCCL over a LAN, launch.py:211-314).  Use "
                "-tp across chips/hosts instead; see README.md."
            )


@dataclass
class SchedulerConfig:
    max_num_seqs: int = 64
    max_num_batched_tokens: int = 2048
    enable_chunked_prefill: bool = True
    max_model_len: int = 2048
    # Decode steps fused into one device dispatch when every running
    # request is decoding (a lax.scan on device).  Amortizes the per-step
    # host round trip — the TPU answer to SURVEY.md §3.3's "push the
    # steady-state loop into a compiled while-loop".  1 disables.
    num_decode_steps: int = 8
    # Fused-decode dispatches kept in flight before the engine blocks on
    # results (the reference's max_concurrent_batches, launch.py:298-302,
    # generalized).  The device carry makes dispatch N+1 independent of
    # N's results, so depth only trades token-delivery latency for
    # host/transport-latency hiding; raise it when the chip is reached
    # over a high-RTT link.
    max_concurrent_dispatches: int = 2
    # Pre-compile the fused-decode programs for every batch bucket at
    # boot (adds startup time; removes mid-serve recompile stalls).
    warmup_decode: bool = False
    # Pre-compile the prefill/mixed single-step program per token
    # bucket at boot (first-request TTFT becomes execution time).
    warmup_prefill: bool = False
    # ---- overload resilience (ISSUE 8; every knob defaults OFF so the
    # seed behavior is unchanged until an operator opts in) ----
    # Caps on the admission queue: waiting requests / queued prompt
    # tokens.  0 = unbounded.  Enforced at the AsyncLLM surface (typed
    # EngineOverloadedError -> HTTP 429 + Retry-After).
    max_waiting_requests: int = 0
    max_queued_tokens: int = 0
    # Reject admission when the prompt's estimated page demand would
    # leave less than this fraction of usable KV pages free.  0 = off.
    kv_admission_watermark: float = 0.0
    # Server-default per-request deadline (ms); 0 = none.
    default_deadline_ms: int = 0
    # Preemptions per request (while others wait) before the scheduler
    # sheds it with finish_reason="overloaded" instead of recompute
    # thrash.  0 = off.
    preempt_shed_threshold: int = 0
    # ---- QoS control plane (ISSUE 16; default OFF = seed behavior) ----
    # SLO class registry spec, "name:priority[:share[:weight]]" comma
    # list (engine/qos.py).  Empty disables class-aware admission,
    # priority admission ordering, and class-weighted preemption.
    qos_classes: str = ""
    # Chunked-prefill fairness budget: max fraction of the per-step
    # token budget prefill chunks may take while a decode-bound request
    # of higher-or-equal class is running.  0 = off.
    qos_prefill_share: float = 0.0
    # ---- speculative decoding (ISSUE 11; default OFF) ----
    # Max tokens the n-gram prompt-lookup proposer drafts per request
    # per step (engine/spec_decode.py); the model runner verifies all
    # drafts in one fused pass and greedy accept/reject keeps the
    # matching prefix + one bonus token.  Greedy outputs stay
    # bit-identical to the non-speculative path.  0 = off.
    spec_ngram_k: int = 0
    # Tail n-gram match lengths the proposer tries (longest first).
    spec_ngram_max: int = 3
    spec_ngram_min: int = 1

    def fused_decode_steps(self) -> int:
        """The uniform fused-scan length K the scheduler emits: the
        configured num_decode_steps clamped by the token budget at the
        FULL batch size (so K never varies with batch growth — every
        distinct K compiles its own scan) and floored to a power of 2.
        The single source of truth for schedule() and warmup_decode."""
        k = min(
            self.num_decode_steps,
            max(self.max_num_batched_tokens // self.max_num_seqs, 1),
        )
        return 1 << (k.bit_length() - 1)

    def __post_init__(self) -> None:
        if self.max_num_batched_tokens < self.max_num_seqs:
            raise ValueError(
                "max_num_batched_tokens must be >= max_num_seqs "
                f"({self.max_num_batched_tokens} < {self.max_num_seqs})"
            )
        if self.num_decode_steps < 1:
            raise ValueError("num_decode_steps must be >= 1")
        if self.max_concurrent_dispatches < 1:
            raise ValueError("max_concurrent_dispatches must be >= 1")
        if not 0.0 <= self.kv_admission_watermark < 1.0:
            raise ValueError(
                "kv_admission_watermark must be in [0, 1), got "
                f"{self.kv_admission_watermark}"
            )
        if self.spec_ngram_k < 0:
            raise ValueError(
                f"spec_ngram_k must be >= 0 (0 disables), got "
                f"{self.spec_ngram_k}"
            )
        if self.spec_ngram_k and not (
            1 <= self.spec_ngram_min <= self.spec_ngram_max
        ):
            raise ValueError(
                "need 1 <= spec_ngram_min <= spec_ngram_max, got "
                f"min={self.spec_ngram_min} max={self.spec_ngram_max}"
            )
        if self.spec_ngram_k and (
            self.spec_ngram_k + 1 > self.max_num_batched_tokens
        ):
            raise ValueError(
                f"spec_ngram_k={self.spec_ngram_k} needs a verify window "
                f"of {self.spec_ngram_k + 1} tokens but the step budget "
                f"is {self.max_num_batched_tokens}"
            )
        for name in (
            "max_waiting_requests",
            "max_queued_tokens",
            "default_deadline_ms",
            "preempt_shed_threshold",
        ):
            if getattr(self, name) < 0:
                raise ValueError(
                    f"{name} must be >= 0 (0 disables), got "
                    f"{getattr(self, name)}"
                )
        if not 0.0 <= self.qos_prefill_share <= 1.0:
            raise ValueError(
                "qos_prefill_share must be in [0, 1] (0 disables), got "
                f"{self.qos_prefill_share}"
            )
        # Malformed class specs fail at config time, not mid-overload.
        from vllm_distributed_tpu.engine.qos import parse_qos_classes

        parse_qos_classes(self.qos_classes)
        if 1 < self.num_decode_steps and (
            self.fused_decode_steps() < self.num_decode_steps
        ):
            budget_k = max(
                self.max_num_batched_tokens // self.max_num_seqs, 1
            )
            if budget_k < self.num_decode_steps:
                hint = (
                    "raise max_num_batched_tokens "
                    f"(budget allows only {budget_k} steps at full "
                    f"batch max_num_seqs={self.max_num_seqs})"
                )
            else:
                hint = "use a power-of-2 num_decode_steps"
            logger.warning(
                "num_decode_steps=%d runs as %d (uniform fused scan "
                "length); %s to keep the configured depth",
                self.num_decode_steps,
                self.fused_decode_steps(),
                hint,
            )


@dataclass
class DeviceConfig:
    # "auto" picks tpu if available else cpu.
    device: str = "auto"

    def resolved(self) -> str:
        if self.device != "auto":
            return self.device
        import jax

        platform = jax.default_backend()
        return "cpu" if platform == "cpu" else "tpu"


@dataclass
class ObservabilityConfig:
    collect_metrics: bool = True
    # Server-side jax.profiler captures: artifact directory for the
    # worker `profile` verb AND the gated POST /debug/profile endpoint
    # (None/empty = endpoint answers 404).  --profile-dir wins over
    # VDT_PROFILE_DIR.
    profile_dir: str | None = None
    # Per-request tracing (tracing.py): root span per API request,
    # queue/prefill/decode spans, per-step schedule/dispatch/gather
    # spans, and worker-side RPC spans merged across hosts.  Default
    # off: the engine loop runs the no-op tracer path.
    enable_tracing: bool = False
    # Completed traces kept in the in-memory ring (/debug/traces).
    trace_ring_size: int = 256
    # Flight recorder (engine/flight_recorder.py): per-step records
    # kept in the always-on bounded ring (0 disables recording and the
    # automatic failure/drain dumps).
    flight_recorder_size: int = 512


@dataclass
class EngineConfig:
    """Bundle of all sub-configs (the analog of vllm_config, which the
    reference passes whole to workers at launch.py:162, 238, 284)."""

    model_config: ModelConfig
    cache_config: CacheConfig
    parallel_config: ParallelConfig
    scheduler_config: SchedulerConfig
    device_config: DeviceConfig
    observability_config: ObservabilityConfig = field(
        default_factory=ObservabilityConfig
    )
    # KV transfer / disaggregated-prefill hook (SURVEY.md §3.4); None = off.
    kv_transfer_config: Any = None

    def to_json(self) -> str:
        def _default(o):
            if dataclasses.is_dataclass(o):
                return dataclasses.asdict(o)
            return str(o)

        d = {
            k: v
            for k, v in dataclasses.asdict(self).items()
            if k not in ("model_config",)
        }
        d["model"] = self.model_config.model
        return json.dumps(d, default=_default)


@dataclass
class RouterArgs:
    """CLI-buildable config for the multi-replica router front-end
    (ISSUE 10, router/app.py) — `vdt router`.  None fields resolve late
    from the VDT_ROUTER_* env registry so every knob works on both the
    CLI and the programmatic path."""

    replicas: list[str] = field(default_factory=list)
    policy: str | None = None  # affinity | least_loaded | round_robin
    max_migrations: int | None = None
    affinity_block_tokens: int | None = None
    affinity_capacity: int | None = None
    affinity_min_tokens: int | None = None
    health_interval: float | None = None
    connect_timeout: float | None = None
    read_timeout: float | None = None
    api_key: str | None = None
    # Elastic fleet (ISSUE 13; default off — static --replica URLs
    # behave exactly as before): spawn and supervise this many managed
    # `vdt serve` replicas from the --fleet-cmd template.
    fleet_size: int = 0
    fleet_cmd: str | None = None  # None -> $VDT_FLEET_CMD
    # Disaggregated pools (ISSUE 15): fixed per-role replica counts
    # spawned alongside the mixed fleet from the same --fleet-cmd
    # template (the launcher sets VDT_ROUTER_ROLE and substitutes a
    # {role} placeholder when present).  0 = no role-separated pools.
    fleet_prefill: int = 0
    fleet_decode: int = 0
    # Arm the autoscaler control loop over the managed fleet
    # (min/max None -> $VDT_AUTOSCALE_MIN/MAX_REPLICAS).
    autoscale: bool = False
    autoscale_min: int | None = None
    autoscale_max: int | None = None
    # Crash-safe router (ISSUE 17; default off): directory for the
    # durable control-plane WAL.  A router restarted against the same
    # dir re-adopts its still-running managed replicas and replays
    # journaled in-flight requests when their clients reconnect.
    state_dir: str | None = None  # None -> $VDT_ROUTER_STATE_DIR

    @staticmethod
    def add_cli_args(parser: argparse.ArgumentParser) -> argparse.ArgumentParser:
        parser.add_argument(
            "--replica",
            dest="replicas",
            action="append",
            default=None,
            metavar="URL",
            help="replica base URL (repeatable); defaults to "
            "$VDT_ROUTER_REPLICAS (comma-separated)",
        )
        parser.add_argument(
            "--policy",
            type=str,
            default=None,
            choices=["affinity", "least_loaded", "round_robin"],
            help="placement policy (default: $VDT_ROUTER_POLICY or "
            "affinity)",
        )
        parser.add_argument(
            "--max-migrations",
            type=int,
            default=None,
            help="live migrations allowed per request (default: "
            "$VDT_ROUTER_MAX_MIGRATIONS or 3)",
        )
        parser.add_argument(
            "--affinity-block-tokens", type=int, default=None,
            help="prefix-chain block size in tokens (default: "
            "$VDT_ROUTER_AFFINITY_BLOCK_TOKENS or 16; match the engine "
            "page size)",
        )
        parser.add_argument(
            "--affinity-capacity", type=int, default=None,
            help="blocks remembered per replica, LRU beyond (default: "
            "$VDT_ROUTER_AFFINITY_CAPACITY or 8192)",
        )
        parser.add_argument(
            "--affinity-min-tokens", type=int, default=None,
            help="matched tokens before affinity outranks least-loaded "
            "(default: $VDT_ROUTER_AFFINITY_MIN_TOKENS or 16)",
        )
        parser.add_argument(
            "--health-interval", type=float, default=None,
            help="replica health-poll interval in seconds (default: "
            "$VDT_ROUTER_HEALTH_INTERVAL_SECONDS or 2)",
        )
        parser.add_argument(
            "--connect-timeout", type=float, default=None,
            help="router→replica TCP connect deadline in seconds "
            "(default: $VDT_ROUTER_CONNECT_TIMEOUT_SECONDS or 5)",
        )
        parser.add_argument(
            "--read-timeout", type=float, default=None,
            help="router→replica per-read (SSE) deadline in seconds — "
            "bounds how long a silent replica stalls a stream before "
            "migration (default: $VDT_ROUTER_READ_TIMEOUT_SECONDS or "
            "600)",
        )
        parser.add_argument(
            "--fleet-size", type=int, default=0,
            help="spawn and supervise this many managed `vdt serve` "
            "replicas as child processes (health-gated warmup, "
            "drain-before-terminate scale-down, crash-loop restarts); "
            "0 = static --replica URLs only",
        )
        parser.add_argument(
            "--fleet-cmd", type=str, default=None,
            help="command template for managed replicas with {port} "
            "(and optional {replica_id}) placeholders, e.g. "
            "'vdt serve MODEL --host 127.0.0.1 --port {port}' "
            "(default: $VDT_FLEET_CMD)",
        )
        parser.add_argument(
            "--fleet-prefill", type=int, default=0,
            help="spawn this many PREFILL-role managed replicas "
            "(disaggregated prefill/decode, ISSUE 15): long prompts "
            "prefill here and hand their KV pages off to the "
            "decode/mixed pool at first token; 0 = no prefill pool",
        )
        parser.add_argument(
            "--fleet-decode", type=int, default=0,
            help="spawn this many DECODE-role managed replicas "
            "alongside the mixed fleet; 0 = none (mixed replicas "
            "already decode)",
        )
        parser.add_argument(
            "--autoscale", action="store_true", default=False,
            help="arm the autoscaler control loop: hold the managed "
            "replica count to the traffic (queue-depth watermarks "
            "with hysteresis, optional 429-rate and fleet-ITL-p99 "
            "triggers, per-direction cooldowns) within "
            "[--autoscale-min, --autoscale-max]",
        )
        parser.add_argument(
            "--autoscale-min", type=int, default=None,
            help="autoscaler floor (default: "
            "$VDT_AUTOSCALE_MIN_REPLICAS or 1)",
        )
        parser.add_argument(
            "--autoscale-max", type=int, default=None,
            help="autoscaler ceiling (default: "
            "$VDT_AUTOSCALE_MAX_REPLICAS or 4)",
        )
        parser.add_argument(
            "--state-dir", type=str, default=None,
            help="durable control-plane state directory: a bounded "
            "write-ahead log of fleet membership, in-flight request "
            "journals, and QoS config; a router restarted against it "
            "re-adopts still-running managed replicas and finishes "
            "interrupted streams bit-identically when clients "
            "reconnect (default: $VDT_ROUTER_STATE_DIR; empty = off)",
        )
        return parser

    @classmethod
    def from_cli_args(cls, args: argparse.Namespace) -> "RouterArgs":
        attrs = [f.name for f in dataclasses.fields(cls)]
        kwargs = {a: getattr(args, a) for a in attrs if hasattr(args, a)}
        if kwargs.get("replicas") is None:
            kwargs["replicas"] = []
        return cls(**kwargs)

    def resolved_replicas(self) -> list[str]:
        urls = [u.rstrip("/") for u in self.replicas if u]
        if not urls:
            urls = list(envs.VDT_ROUTER_REPLICAS)
        return urls

    def resolved_state_dir(self) -> str:
        """--state-dir over $VDT_ROUTER_STATE_DIR; "" = durable state
        off (the seed behavior)."""
        if self.state_dir is not None:
            return self.state_dir
        return envs.VDT_ROUTER_STATE_DIR


@dataclass
class EngineArgs:
    """CLI-buildable engine args (parity: AsyncEngineArgs.from_cli_args,
    launch.py:29, 399)."""

    model: str = "facebook/opt-125m"
    tokenizer: str | None = None
    dtype: str = "auto"
    seed: int = 0
    max_model_len: int | None = None
    trust_remote_code: bool = False
    quantization: str | None = None
    skip_tokenizer_init: bool = False
    load_format: str = "auto"

    page_size: int = 16
    num_kv_pages: int | None = None
    # None -> resolved late from VDT_HBM_UTILIZATION (default 0.9), so the
    # env var works on both the CLI and the programmatic path.
    hbm_utilization: float | None = None
    kv_cache_dtype: str = "auto"
    enable_prefix_caching: bool = False
    prefix_cache_index: str = "radix"
    # Tiered KV spill knobs (None -> resolved late from VDT_KV_SPILL_*
    # so the env vars work on both the CLI and programmatic paths).
    kv_spill_host_pages: int | None = None
    kv_spill_restore_min_tokens: int | None = None

    tensor_parallel_size: int = 1
    pipeline_parallel_size: int = 1
    data_parallel_size: int = 1
    enable_expert_parallel: bool = False
    distributed_executor_backend: Any = None
    num_hosts: int = 1
    host_id: int = 0
    coordinator_address: str | None = None

    max_num_seqs: int = 64
    max_num_batched_tokens: int | None = None
    enable_chunked_prefill: bool = True
    num_decode_steps: int = 8
    max_concurrent_dispatches: int = 2
    warmup_decode: bool = False
    warmup_prefill: bool = False

    # Overload resilience (None -> resolved late from the VDT_* env
    # vars, so the knobs work on both the CLI and programmatic paths).
    max_waiting_requests: int | None = None
    max_queued_tokens: int | None = None
    kv_admission_watermark: float | None = None
    default_deadline_ms: int | None = None
    preempt_shed_threshold: int | None = None

    # Speculative decoding (None -> resolved late from VDT_SPEC_NGRAM_*).
    speculative_ngram_k: int | None = None
    speculative_ngram_max: int | None = None
    speculative_ngram_min: int | None = None

    # QoS control plane (None -> resolved late from VDT_QOS_*).
    qos_classes: str | None = None
    qos_prefill_share: float | None = None

    # JSON dict (or dict) configuring a KV connector (disaggregated
    # prefill hook, SURVEY.md §3.4); None = off.
    kv_transfer_config: Any = None

    device: str = "auto"
    profile_dir: str | None = None
    disable_log_stats: bool = False
    # None -> resolved late from VDT_TRACING so the env var works on
    # both the CLI and the programmatic path.
    enable_tracing: bool | None = None

    @staticmethod
    def add_cli_args(parser: argparse.ArgumentParser) -> argparse.ArgumentParser:
        parser.add_argument("--model", type=str, default=EngineArgs.model)
        parser.add_argument("--tokenizer", type=str, default=None)
        parser.add_argument(
            "--dtype",
            type=str,
            default="auto",
            choices=["auto", *sorted(_STR_DTYPE_TO_JAX)],
        )
        parser.add_argument("--seed", type=int, default=0)
        parser.add_argument("--max-model-len", type=int, default=None)
        parser.add_argument("--trust-remote-code", action="store_true")
        parser.add_argument("--quantization", "-q", type=str, default=None)
        parser.add_argument("--skip-tokenizer-init", action="store_true")
        parser.add_argument(
            "--load-format",
            type=str,
            default="auto",
            choices=["auto", "dummy"],
        )
        parser.add_argument("--page-size", "--block-size", type=int, default=16)
        parser.add_argument("--num-kv-pages", type=int, default=None)
        parser.add_argument(
            "--hbm-utilization",
            "--gpu-memory-utilization",
            type=float,
            default=None,
            help="fraction of free HBM given to the KV cache "
            "(default: $VDT_HBM_UTILIZATION or 0.9)",
        )
        parser.add_argument("--kv-cache-dtype", type=str, default="auto")
        parser.add_argument(
            "--enable-prefix-caching",
            action="store_true",
            help="reuse KV pages across requests sharing a prompt "
            "prefix (content-addressed pages, ref-counted LRU "
            "eviction)",
        )
        parser.add_argument(
            "--prefix-cache-index",
            type=str,
            default="radix",
            choices=["radix", "flat"],
            help="prefix index structure: radix tree with leaf-first "
            "cache-aware eviction + optional host-DRAM spill tier, or "
            "the flat hash-chain map (ablation baseline)",
        )
        parser.add_argument(
            "--kv-spill-host-pages",
            type=int,
            default=None,
            help="host-DRAM spill tier size in KV pages: evicted pages "
            "spill to host memory and stream back ahead of prefill "
            "resume (default: $VDT_KV_SPILL_HOST_PAGES or 0 = off)",
        )
        parser.add_argument(
            "--kv-spill-restore-min-tokens",
            type=int,
            default=None,
            help="restore-vs-recompute crossover: host runs shorter "
            "than this many tokens are recomputed instead of restored "
            "(default: $VDT_KV_SPILL_RESTORE_MIN_TOKENS or 32)",
        )
        parser.add_argument(
            "--tensor-parallel-size", "-tp", type=int, default=1
        )
        parser.add_argument(
            "--pipeline-parallel-size", "-pp", type=int, default=1
        )
        parser.add_argument("--data-parallel-size", "-dp", type=int, default=1)
        parser.add_argument("--enable-expert-parallel", action="store_true")
        parser.add_argument(
            "--distributed-executor-backend", type=str, default=None
        )
        parser.add_argument("--num-hosts", type=int, default=1)
        parser.add_argument("--host-id", type=int, default=0)
        parser.add_argument("--coordinator-address", type=str, default=None)
        parser.add_argument("--max-num-seqs", type=int, default=64)
        parser.add_argument("--max-num-batched-tokens", type=int, default=None)
        parser.add_argument(
            "--num-decode-steps",
            type=int,
            default=8,
            help="decode steps fused into one device dispatch (1 disables)",
        )
        parser.add_argument(
            "--max-concurrent-dispatches",
            type=int,
            default=2,
            help="fused-decode dispatches kept in flight before the "
            "engine blocks on results (raise over high-RTT links)",
        )
        parser.add_argument(
            "--warmup-decode",
            action="store_true",
            help="pre-compile fused-decode programs for every batch "
            "bucket at boot (no mid-serve recompile stalls)",
        )
        parser.add_argument(
            "--warmup-prefill",
            action="store_true",
            help="pre-compile the prefill program per token bucket at "
            "boot (first-request TTFT becomes execution time)",
        )
        parser.add_argument(
            "--no-enable-chunked-prefill",
            dest="enable_chunked_prefill",
            action="store_false",
        )
        parser.add_argument(
            "--max-waiting-requests",
            type=int,
            default=None,
            help="admission cap on waiting requests; excess rejected "
            "with HTTP 429 (default: $VDT_MAX_WAITING_REQUESTS or "
            "0 = unbounded)",
        )
        parser.add_argument(
            "--max-queued-tokens",
            type=int,
            default=None,
            help="admission cap on queued prompt tokens (default: "
            "$VDT_MAX_QUEUED_TOKENS or 0 = unbounded)",
        )
        parser.add_argument(
            "--kv-admission-watermark",
            type=float,
            default=None,
            help="reject admission when the prompt's estimated KV page "
            "demand would leave less than this fraction of pages free "
            "(default: $VDT_KV_ADMISSION_WATERMARK or 0 = off)",
        )
        parser.add_argument(
            "--default-deadline-ms",
            type=int,
            default=None,
            help="server-default per-request deadline in ms; expired "
            "requests are shed (waiting) or finish with "
            'finish_reason="timeout" (running) (default: '
            "$VDT_DEFAULT_DEADLINE_MS or 0 = none)",
        )
        parser.add_argument(
            "--preempt-shed-threshold",
            type=int,
            default=None,
            help="preemptions per request before it is shed with "
            'finish_reason="overloaded" instead of recompute thrash '
            "(default: $VDT_PREEMPT_SHED_THRESHOLD or 0 = off)",
        )
        parser.add_argument(
            "--qos-classes",
            type=str,
            default=None,
            help="SLO class registry, one entry per class "
            '"name:priority[:share[:weight]]" comma-separated: priority '
            "orders admission/preemption, share is the class's "
            "guaranteed-minimum fraction of the admission caps, weight "
            "scales the preempt-to-shed budget (default: "
            "$VDT_QOS_CLASSES or empty = QoS off, seed scheduling)",
        )
        parser.add_argument(
            "--qos-prefill-share",
            type=float,
            default=None,
            help="chunked-prefill fairness budget: max fraction of the "
            "per-step token budget prefill may take while a "
            "decode-bound request of higher-or-equal class runs "
            "(default: $VDT_QOS_PREFILL_SHARE or 0 = off)",
        )
        parser.add_argument(
            "--speculative-ngram-k",
            type=int,
            default=None,
            help="speculative decoding: max tokens the n-gram "
            "prompt-lookup proposer drafts per request per step, "
            "verified in one fused pass; greedy outputs stay "
            "bit-identical (default: $VDT_SPEC_NGRAM_K or 0 = off)",
        )
        parser.add_argument(
            "--speculative-ngram-max",
            type=int,
            default=None,
            help="longest tail n-gram the proposer matches (default: "
            "$VDT_SPEC_NGRAM_MAX or 3)",
        )
        parser.add_argument(
            "--speculative-ngram-min",
            type=int,
            default=None,
            help="shortest tail n-gram the proposer matches (default: "
            "$VDT_SPEC_NGRAM_MIN or 1)",
        )
        parser.add_argument("--device", type=str, default="auto")
        parser.add_argument("--profile-dir", type=str, default=None)
        parser.add_argument("--disable-log-stats", action="store_true")
        parser.add_argument(
            "--enable-tracing",
            action="store_true",
            default=None,
            help="per-request tracing: /debug/traces (JSON + Perfetto), "
            "per-stage latency histograms, cross-host RPC spans "
            "(default: $VDT_TRACING or off)",
        )
        parser.add_argument(
            "--kv-transfer-config",
            type=str,
            default=None,
            help="JSON KV-connector config (disaggregated prefill hook): "
            "all workers reply per step and KV-transfer progress is "
            "merged by KVOutputAggregator",
        )
        return parser

    @classmethod
    def from_cli_args(cls, args: argparse.Namespace) -> "EngineArgs":
        attrs = [f.name for f in dataclasses.fields(cls)]
        return cls(
            **{a: getattr(args, a) for a in attrs if hasattr(args, a)}
        )

    def create_engine_config(self) -> EngineConfig:
        model_config = ModelConfig(
            model=self.model,
            tokenizer=self.tokenizer,
            dtype=self.dtype,
            seed=self.seed,
            max_model_len=self.max_model_len,
            trust_remote_code=self.trust_remote_code,
            quantization=self.quantization,
            skip_tokenizer_init=self.skip_tokenizer_init,
            load_format=self.load_format,
            enable_expert_parallel=self.enable_expert_parallel,
        )
        max_batched = self.max_num_batched_tokens
        if max_batched is None:
            max_batched = max(2048, self.max_num_seqs)
        hbm_utilization = self.hbm_utilization
        if hbm_utilization is None:
            hbm_utilization = envs.VDT_HBM_UTILIZATION
        kv_spill_host_pages = self.kv_spill_host_pages
        if kv_spill_host_pages is None:
            kv_spill_host_pages = envs.VDT_KV_SPILL_HOST_PAGES
        kv_spill_restore_min = self.kv_spill_restore_min_tokens
        if kv_spill_restore_min is None:
            kv_spill_restore_min = envs.VDT_KV_SPILL_RESTORE_MIN_TOKENS
        cache_config = CacheConfig(
            page_size=self.page_size,
            num_pages=self.num_kv_pages,
            hbm_utilization=hbm_utilization,
            cache_dtype=self.kv_cache_dtype,
            enable_prefix_caching=self.enable_prefix_caching,
            prefix_cache_index=self.prefix_cache_index,
            kv_spill_host_pages=kv_spill_host_pages,
            kv_spill_restore_min_tokens=kv_spill_restore_min,
        )
        parallel_config = ParallelConfig(
            tensor_parallel_size=self.tensor_parallel_size,
            pipeline_parallel_size=self.pipeline_parallel_size,
            data_parallel_size=self.data_parallel_size,
            enable_expert_parallel=self.enable_expert_parallel,
            distributed_executor_backend=self.distributed_executor_backend,
            num_hosts=self.num_hosts,
            host_id=self.host_id,
            coordinator_address=self.coordinator_address,
        )
        def _env_default(value, env_name):
            return getattr(envs, env_name) if value is None else value

        scheduler_config = SchedulerConfig(
            max_num_seqs=self.max_num_seqs,
            max_num_batched_tokens=max_batched,
            enable_chunked_prefill=self.enable_chunked_prefill,
            max_model_len=model_config.max_model_len,
            num_decode_steps=self.num_decode_steps,
            max_concurrent_dispatches=self.max_concurrent_dispatches,
            warmup_decode=self.warmup_decode,
            warmup_prefill=self.warmup_prefill,
            max_waiting_requests=_env_default(
                self.max_waiting_requests, "VDT_MAX_WAITING_REQUESTS"
            ),
            max_queued_tokens=_env_default(
                self.max_queued_tokens, "VDT_MAX_QUEUED_TOKENS"
            ),
            kv_admission_watermark=_env_default(
                self.kv_admission_watermark, "VDT_KV_ADMISSION_WATERMARK"
            ),
            default_deadline_ms=_env_default(
                self.default_deadline_ms, "VDT_DEFAULT_DEADLINE_MS"
            ),
            preempt_shed_threshold=_env_default(
                self.preempt_shed_threshold, "VDT_PREEMPT_SHED_THRESHOLD"
            ),
            spec_ngram_k=_env_default(
                self.speculative_ngram_k, "VDT_SPEC_NGRAM_K"
            ),
            spec_ngram_max=_env_default(
                self.speculative_ngram_max, "VDT_SPEC_NGRAM_MAX"
            ),
            spec_ngram_min=_env_default(
                self.speculative_ngram_min, "VDT_SPEC_NGRAM_MIN"
            ),
            qos_classes=_env_default(
                self.qos_classes, "VDT_QOS_CLASSES"
            ),
            qos_prefill_share=_env_default(
                self.qos_prefill_share, "VDT_QOS_PREFILL_SHARE"
            ),
        )
        kv_transfer = self.kv_transfer_config
        if isinstance(kv_transfer, str):
            kv_transfer = json.loads(kv_transfer)
        return EngineConfig(
            model_config=model_config,
            cache_config=cache_config,
            parallel_config=parallel_config,
            scheduler_config=scheduler_config,
            device_config=DeviceConfig(device=self.device),
            observability_config=ObservabilityConfig(
                collect_metrics=not self.disable_log_stats,
                profile_dir=self.profile_dir or envs.VDT_PROFILE_DIR or None,
                enable_tracing=(
                    envs.VDT_TRACING
                    if self.enable_tracing is None
                    else self.enable_tracing
                ),
                trace_ring_size=envs.VDT_TRACE_RING_SIZE,
                flight_recorder_size=envs.VDT_FLIGHT_RECORDER_SIZE,
            ),
            kv_transfer_config=kv_transfer,
        )
