"""Remote-host agent ("client" mode): dial the server, offer this host's
chips, host one worker for the life of the deployment.

The per-host rebuild of the reference's remote-node agent
(launch.py:543-632, SURVEY.md §2 C2), with the per-GPU process fan-out
collapsed to one agent per TPU host (§2.5).  Behavior contract kept:

- connect-retry with jittered exponential backoff while unused (the
  reference retries on a fixed 10 s, launch.py:583-586; backoff avoids
  thundering-herd redials when a large deployment's server restarts);
- once a worker exists, any disconnect is fatal — exit(1) and let the
  supervisor restart the host (launch.py:579-581);
- symmetric liveness: the driver heartbeats every agent, and a deployed
  agent that stops hearing the driver fail-fasts too, so an orphaned
  TPU host releases its devices instead of holding them forever;
- the agent's ``print`` is exposed as an RPC param so the driver can log
  remotely (launch.py:556 — genuinely useful, kept);
- GC pacing every 10 s on the event loop to bound pause times
  (launch.py:589-594; wired *before* the loop runs, unlike the
  reference's dead-code path at :597-605).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import gc
import os
import random
import sys
import time
from typing import Any

from vllm_distributed_tpu import envs
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.utils import run_method

logger = init_logger(__name__)

RETRY_BASE_SECONDS = 1.0
RETRY_CAP_SECONDS = 30.0
GC_INTERVAL_SECONDS = 10.0


def reconnect_delay(attempt: int) -> float:
    """Jittered exponential backoff: full cap ~30 s, never synchronized
    across a fleet of agents redialing a restarted server."""
    ceiling = min(RETRY_CAP_SECONDS, RETRY_BASE_SECONDS * 2**attempt)
    return ceiling * random.uniform(0.5, 1.0)


class WorkerHost:
    """The object proxied back to the driver: one worker on this host,
    every lifecycle verb reachable via ``run`` (the executor's
    collective_rpc contract; cf. WorkerWrapper.run_worker,
    launch.py:523-541), plus the persistent step-stream verbs
    (``start_step_stream``/``stream_step``): per-step work arrives as
    one-way frames pulled by a long-lived run loop instead of
    request/reply pairs."""

    __rpc_proxy__ = True

    def __init__(self, worker: Any) -> None:
        self.worker = worker
        self.runner = None  # StepStreamRunner, once the driver starts it
        # Device work blocks; keep RPC handling responsive and calls
        # ordered with a single-thread pool.  fetch_results gets its OWN
        # ordered pool: it blocks until a dispatched step's results are
        # ready, and must not stall the next dispatch_model behind it
        # (cross-RPC pipelining: dispatch N+1 overlaps fetch N).
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="vdt-worker"
        )
        self._fetch_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="vdt-worker-fetch"
        )

    async def run(self, method: str, args: tuple, kwargs: dict) -> Any:
        loop = asyncio.get_running_loop()
        pool = self._fetch_pool if method == "fetch_results" else self._pool
        return await loop.run_in_executor(
            pool, run_method, self.worker, method, args, kwargs or {}
        )

    # ---- persistent step stream (ISSUE 7) ----
    def start_step_stream(self, deliver: Any, depth: int) -> bool:
        """Spin up this host's run loop.  ``deliver`` is a driver-side
        callable proxied over the connection; each finished step sends
        ONE one-way ack frame back through it — result bytes are
        pre-pickled off the event loop (inside the worker.serialize
        span) so the transport ships them sideband without re-walking
        the payload."""
        import cloudpickle

        from vllm_distributed_tpu.distributed.rpc import apply_oneway
        from vllm_distributed_tpu.tracing import get_tracer
        from vllm_distributed_tpu.worker.step_stream import StepStreamRunner

        loop = asyncio.get_running_loop()

        def _send_ack(step_id: int, result, error, spans, span_ctx) -> None:
            tracer = get_tracer()
            if span_ctx is not None and tracer.enabled:
                ctx = tuple(span_ctx)
                sp = None
                try:
                    with tracer.span(
                        "worker.serialize", parent=ctx, record=False
                    ) as sp:
                        payload = cloudpickle.dumps(result)
                finally:
                    if sp is not None:
                        spans.append(sp.to_wire())
                spans.append(tracer.stamp("worker.reply", ctx))
            else:
                payload = cloudpickle.dumps(result)
            fut = asyncio.run_coroutine_threadsafe(
                apply_oneway(
                    deliver, None, step_id, payload, error, spans
                ),
                loop,
            )
            fut.add_done_callback(_log_ack_error)

        self.runner = StepStreamRunner(
            self.worker, _send_ack, depth=depth, name="agent"
        )
        return True

    def stream_step(self, frame_bytes: bytes, span_ctx: Any = None) -> None:
        """One-way per-step push from the driver.  Unpickling the
        O(batch) delta frame here is microseconds; the mirror decode
        (full SchedulerOutput reconstruction) runs on the runner's
        dispatch thread, never on the event loop."""
        import cloudpickle

        runner = self.runner
        if runner is None:
            # Raced a teardown (stop_step_stream already ran) or an
            # out-of-order start: drop the frame — the driver's
            # per-step deadline attributes the missing ack, and an
            # AttributeError here would die unobserved on the one-way
            # path anyway.
            logger.warning("step frame arrived with no active stream")
            return
        frame = cloudpickle.loads(frame_bytes)
        runner.submit(
            frame, tuple(span_ctx) if span_ctx is not None else None
        )

    def stop_step_stream(self) -> dict:
        runner, self.runner = self.runner, None
        if runner is None:
            return {}
        stats = runner.stats()
        runner.stop()
        return stats

    def get_step_stream_stats(self) -> dict:
        return self.runner.stats() if self.runner is not None else {}


def _log_ack_error(fut) -> None:
    e = fut.exception()
    if e is not None:
        logger.debug("step ack send failed: %s", e)


def _resolve_worker_cls(worker_cls: str | None):
    if worker_cls is None:
        from vllm_distributed_tpu.worker.worker import Worker

        return Worker
    import importlib

    mod, cls = worker_cls.rsplit(".", 1)
    return getattr(importlib.import_module(mod), cls)


async def _gc_pacer() -> None:
    while True:
        await asyncio.sleep(GC_INTERVAL_SECONDS)
        gc.collect()


async def server_silence_watchdog(hb: dict) -> None:
    """Returns (normally) once the driver has been silent for more than
    ``interval * (miss_threshold + 1)`` seconds while this host is
    deployed — the caller treats that as fatal.  ``hb`` carries
    ``last_contact`` (monotonic seconds, None until deployed); the
    driver's heartbeat pings refresh it.  Env knobs are read lazily each
    tick because the driver replicates them at create_worker time."""
    while True:
        interval = envs.VDT_HEARTBEAT_INTERVAL_SECONDS
        threshold = envs.VDT_HEARTBEAT_MISS_THRESHOLD
        if interval <= 0:  # liveness disabled deployment-wide
            await asyncio.sleep(GC_INTERVAL_SECONDS)
            continue
        await asyncio.sleep(interval)
        last = hb.get("last_contact")
        if last is None:
            continue  # not deployed yet
        silent = time.monotonic() - last
        if silent > interval * (threshold + 1):
            logger.error(
                "server silent for %.1fs (> %d×%.1fs heartbeat budget) "
                "while deployed",
                silent,
                threshold + 1,
                interval,
            )
            return


async def agent_async_main(server_ip: str, port: int | None = None) -> None:
    from vllm_distributed_tpu.distributed.rpc_transport import (
        FaultInjector,
        StreamRpcTransport,
        prepare_peer_readloop,
        set_global_injector,
    )

    port = port or envs.VDT_SERVER_PORT
    state: dict[str, Any] = {"worker_host": None}
    hb: dict[str, Any] = {"last_contact": None}
    gc_task = asyncio.ensure_future(_gc_pacer())

    # Test harness hooks (inert in production): a process-global fault
    # injector the mock-worker layer can arm over RPC, and a
    # deterministic pre-dial delay.
    injector = None
    if envs.VDT_FAULT_INJECTION:
        injector = FaultInjector()
        set_global_injector(injector)
    connect_delay = envs.VDT_FAULT_CONNECT_DELAY_SECONDS

    info_cache: dict[str, Any] = {}

    async def host_info() -> dict:
        """Chips this host offers.  Probed in a SUBPROCESS: initializing
        jax here would pin the agent's backend before the worker's
        jax.distributed.initialize (which must run first).  Env
        overrides let operators/tests pin the advertisement."""
        env_chips = envs.VDT_ADVERTISE_NUM_CHIPS
        env_platform = envs.VDT_ADVERTISE_PLATFORM
        if env_chips and env_platform:
            return {"num_chips": int(env_chips), "platform": env_platform}
        if not info_cache:
            proc = await asyncio.create_subprocess_exec(
                sys.executable,
                "-c",
                "import jax; print(jax.local_device_count(), "
                "jax.default_backend())",
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.DEVNULL,
            )
            try:
                # Deadline on the probe: a wedged TPU runtime must not
                # hang the agent forever.  Must stay under the driver's
                # 60s host_info budget (multihost._handle_agent) so the
                # 0-chip fallback reply reaches the driver before its
                # wait_for fires and it drops the connection.
                out, _ = await asyncio.wait_for(proc.communicate(), 45)
            except asyncio.TimeoutError:
                proc.kill()
                # vdt-lint: disable=unbounded-wait — just SIGKILL'd:
                # the child exits promptly and only needs reaping.
                await proc.wait()
                # Deliberately NOT cached: a transient wedge (cold TPU
                # runtime) must not mis-advertise this host for the
                # agent's lifetime — the next host_info call re-probes.
                return {"num_chips": 0, "platform": "unknown"}
            try:
                chips, platform = out.decode().split()[-2:]
                info_cache.update(
                    num_chips=int(chips), platform=platform
                )
            except (ValueError, IndexError):
                info_cache.update(num_chips=0, platform="unknown")
        return {
            "num_chips": int(env_chips or info_cache["num_chips"]),
            "platform": env_platform or info_cache["platform"],
        }

    def ping(payload: Any = None) -> Any:
        """Driver liveness probe: echoes the payload (RTT measurement)
        plus this host's wall clock, so the driver can estimate the
        per-host clock offset used to place worker-side trace spans on
        its own timeline; refreshes the server-silence watchdog."""
        hb["last_contact"] = time.monotonic()
        return (payload, time.time())

    async def create_worker(
        config, rank, num_hosts, distributed_init_method, env, worker_cls
    ):
        for key, value in (env or {}).items():
            os.environ[key] = value
        # The driver's tracing config just arrived with the replicated
        # env: spans recorded while serving its RPCs are labeled with
        # this host's rank and shipped back inside reply frames.
        from vllm_distributed_tpu.tracing import configure_from_env

        configure_from_env(host=f"host{rank}")
        cls = _resolve_worker_cls(worker_cls)
        worker = cls(
            config,
            rank=rank,
            distributed_init_method=distributed_init_method,
            is_driver_worker=False,
        )
        state["worker_host"] = WorkerHost(worker)
        # Deployed: arm the server-silence watchdog from "now".
        hb["last_contact"] = time.monotonic()
        logger.info("worker created: host rank %d/%d", rank, num_hosts)
        return state["worker_host"]

    # Pre-warm the chip probe so the driver's host_info call answers
    # from cache instead of paying the cold jax import inline.
    warm_task = asyncio.ensure_future(host_info())
    attempt = 0
    try:
        if connect_delay > 0:
            logger.info("fault: delaying connect by %.1fs", connect_delay)
            await asyncio.sleep(connect_delay)
        while True:
            try:
                # Bounded dial: a SYN that never answers (blackholed
                # server) must fall into the retry/backoff path, not
                # wedge the agent in connect forever.
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(server_ip, port), 30
                )
            except (OSError, asyncio.TimeoutError) as e:
                delay = reconnect_delay(attempt)
                attempt += 1
                logger.info(
                    "server %s:%d unreachable (%s); retry in %.1fs",
                    server_ip,
                    port,
                    e,
                    delay,
                )
                await asyncio.sleep(delay)
                continue
            attempt = 0
            transport = StreamRpcTransport(reader, writer, injector=injector)
            peer, readloop = prepare_peer_readloop(transport, "server")
            peer.params["host_info"] = host_info
            peer.params["create_worker"] = create_worker
            peer.params["ping"] = ping
            peer.params["print"] = print  # driver's remote console
            logger.info("connected to %s:%d; serving", server_ip, port)
            readloop_task = asyncio.ensure_future(readloop())
            watchdog_task = asyncio.ensure_future(
                server_silence_watchdog(hb)
            )
            try:
                # vdt-lint: disable=unbounded-wait — serve-until-disconnect
                # by contract; the watchdog task in the set IS the deadline.
                await asyncio.wait(
                    {readloop_task, watchdog_task},
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if watchdog_task.done() and not readloop_task.done():
                    # Server wedged: the socket is open but the driver's
                    # heartbeats stopped.  Release this host's devices.
                    logger.error(
                        "driver heartbeats stopped while deployed — "
                        "exiting to release TPU devices"
                    )
                    sys.exit(1)
                # vdt-lint: disable=unbounded-wait — FIRST_COMPLETED above
                # guarantees this task is already done; the await only
                # re-raises its exception.
                await readloop_task
            except Exception as e:  # noqa: BLE001
                logger.warning("connection lost: %s", e)
            finally:
                watchdog_task.cancel()
            if state["worker_host"] is not None:
                # Fail-fast: this host was part of a live deployment.
                logger.error(
                    "disconnected while deployed — exiting for restart"
                )
                sys.exit(1)
            hb["last_contact"] = None
            await asyncio.sleep(reconnect_delay(attempt))
            attempt += 1
    finally:
        warm_task.cancel()
        gc_task.cancel()


def remote_main(server_ip: str, port: int | None = None) -> None:
    """Blocking entry: `vdt remote <server_ip>` (launch.py:668-675)."""
    asyncio.run(agent_async_main(server_ip, port))
