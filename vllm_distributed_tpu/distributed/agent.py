"""Remote-host agent ("client" mode): dial the server, offer this host's
chips, host one worker for the life of the deployment.

The per-host rebuild of the reference's remote-node agent
(launch.py:543-632, SURVEY.md §2 C2), with the per-GPU process fan-out
collapsed to one agent per TPU host (§2.5).  Behavior contract kept:

- connect-retry every 10 s while unused (launch.py:583-586);
- once a worker exists, any disconnect is fatal — exit(1) and let the
  supervisor restart the host (launch.py:579-581);
- the agent's ``print`` is exposed as an RPC param so the driver can log
  remotely (launch.py:556 — genuinely useful, kept);
- GC pacing every 10 s on the event loop to bound pause times
  (launch.py:589-594; wired *before* the loop runs, unlike the
  reference's dead-code path at :597-605).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import gc
import os
import sys
from typing import Any

from vllm_distributed_tpu import envs
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.utils import run_method

logger = init_logger(__name__)

RETRY_SECONDS = 10.0
GC_INTERVAL_SECONDS = 10.0


class WorkerHost:
    """The object proxied back to the driver: one worker on this host,
    every lifecycle verb reachable via ``run`` (the executor's
    collective_rpc contract; cf. WorkerWrapper.run_worker,
    launch.py:523-541)."""

    __rpc_proxy__ = True

    def __init__(self, worker: Any) -> None:
        self.worker = worker
        # Device work blocks; keep RPC handling responsive and calls
        # ordered with a single-thread pool.  fetch_results gets its OWN
        # ordered pool: it blocks until a dispatched step's results are
        # ready, and must not stall the next dispatch_model behind it
        # (cross-RPC pipelining: dispatch N+1 overlaps fetch N).
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="vdt-worker"
        )
        self._fetch_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="vdt-worker-fetch"
        )

    async def run(self, method: str, args: tuple, kwargs: dict) -> Any:
        loop = asyncio.get_running_loop()
        pool = self._fetch_pool if method == "fetch_results" else self._pool
        return await loop.run_in_executor(
            pool, run_method, self.worker, method, args, kwargs or {}
        )


def _resolve_worker_cls(worker_cls: str | None):
    if worker_cls is None:
        from vllm_distributed_tpu.worker.worker import Worker

        return Worker
    import importlib

    mod, cls = worker_cls.rsplit(".", 1)
    return getattr(importlib.import_module(mod), cls)


async def _gc_pacer() -> None:
    while True:
        await asyncio.sleep(GC_INTERVAL_SECONDS)
        gc.collect()


async def agent_async_main(server_ip: str, port: int | None = None) -> None:
    from vllm_distributed_tpu.distributed.rpc_transport import (
        StreamRpcTransport,
        prepare_peer_readloop,
    )

    port = port or envs.VDT_SERVER_PORT
    state: dict[str, Any] = {"worker_host": None}
    gc_task = asyncio.ensure_future(_gc_pacer())

    info_cache: dict[str, Any] = {}

    async def host_info() -> dict:
        """Chips this host offers.  Probed in a SUBPROCESS: initializing
        jax here would pin the agent's backend before the worker's
        jax.distributed.initialize (which must run first).  Env
        overrides let operators/tests pin the advertisement."""
        env_chips = os.environ.get("VDT_ADVERTISE_NUM_CHIPS")
        env_platform = os.environ.get("VDT_ADVERTISE_PLATFORM")
        if env_chips and env_platform:
            return {"num_chips": int(env_chips), "platform": env_platform}
        if not info_cache:
            proc = await asyncio.create_subprocess_exec(
                sys.executable,
                "-c",
                "import jax; print(jax.local_device_count(), "
                "jax.default_backend())",
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.DEVNULL,
            )
            out, _ = await proc.communicate()
            try:
                chips, platform = out.decode().split()[-2:]
                info_cache.update(
                    num_chips=int(chips), platform=platform
                )
            except (ValueError, IndexError):
                info_cache.update(num_chips=0, platform="unknown")
        return {
            "num_chips": int(env_chips or info_cache["num_chips"]),
            "platform": env_platform or info_cache["platform"],
        }

    async def create_worker(
        config, rank, num_hosts, distributed_init_method, env, worker_cls
    ):
        for key, value in (env or {}).items():
            os.environ[key] = value
        cls = _resolve_worker_cls(worker_cls)
        worker = cls(
            config,
            rank=rank,
            distributed_init_method=distributed_init_method,
            is_driver_worker=False,
        )
        state["worker_host"] = WorkerHost(worker)
        logger.info("worker created: host rank %d/%d", rank, num_hosts)
        return state["worker_host"]

    # Pre-warm the chip probe so the driver's host_info call answers
    # from cache instead of paying the cold jax import inline.
    warm_task = asyncio.ensure_future(host_info())
    try:
        while True:
            try:
                reader, writer = await asyncio.open_connection(
                    server_ip, port
                )
            except OSError as e:
                logger.info(
                    "server %s:%d unreachable (%s); retry in %.0fs",
                    server_ip,
                    port,
                    e,
                    RETRY_SECONDS,
                )
                await asyncio.sleep(RETRY_SECONDS)
                continue
            transport = StreamRpcTransport(reader, writer)
            peer, readloop = prepare_peer_readloop(transport, "server")
            peer.params["host_info"] = host_info
            peer.params["create_worker"] = create_worker
            peer.params["print"] = print  # driver's remote console
            logger.info("connected to %s:%d; serving", server_ip, port)
            try:
                await readloop()
            except Exception as e:  # noqa: BLE001
                logger.warning("connection lost: %s", e)
            if state["worker_host"] is not None:
                # Fail-fast: this host was part of a live deployment.
                logger.error(
                    "disconnected while deployed — exiting for restart"
                )
                sys.exit(1)
            await asyncio.sleep(RETRY_SECONDS)
    finally:
        warm_task.cancel()
        gc_task.cancel()


def remote_main(server_ip: str, port: int | None = None) -> None:
    """Blocking entry: `vdt remote <server_ip>` (launch.py:668-675)."""
    asyncio.run(agent_async_main(server_ip, port))
