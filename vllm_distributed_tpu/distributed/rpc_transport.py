"""RPC transports + wire format.

Framing matches the reference's (rpc_reader.py:73-82, 117-125, 155-164):
``4-byte big-endian length | 1 type byte | payload`` where type 0 is a
pickled message dict and type 1 is a raw sideband buffer.  Buffers
precede the message that references them and are attached FIFO
(rpc_reader.py's LIFO pop is a known quirk we do not reproduce —
SURVEY.md "known reference quirks").

Transports:
- ``StreamRpcTransport``  — asyncio TCP, cloudpickle payloads: the
  cross-host path (reference RpcPickleStreamTransport,
  rpc_reader.py:146-181).
- ``ConnectionRpcTransport`` — multiprocessing.Pipe with a reader thread:
  the driver↔local-worker path (reference RpcConnectionTransport,
  rpc_reader.py:184-206).

``prepare_peer_readloop`` glues a transport to an RpcPeer with a
mutex-serialized writer (rpc_reader.py:229-239) and returns
(peer, readloop); the read loop ending (EOF/error) kills the peer — that
is the disconnect-detection contract (SURVEY.md §5.3).
"""

from __future__ import annotations

import asyncio
import struct
from typing import Any

import cloudpickle

from vllm_distributed_tpu.distributed.rpc import RpcPeer
from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)

_MSG = 0
_BUF = 1
_HEADER = struct.Struct(">IB")


class RpcTransport:
    async def read(self) -> tuple[int, bytes]:
        raise NotImplementedError

    async def write(self, kind: int, payload: bytes) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class StreamRpcTransport(RpcTransport):
    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
    ) -> None:
        self.reader = reader
        self.writer = writer

    async def read(self) -> tuple[int, bytes]:
        header = await self.reader.readexactly(_HEADER.size)
        length, kind = _HEADER.unpack(header)
        payload = await self.reader.readexactly(length)
        return kind, payload

    async def write(self, kind: int, payload: bytes) -> None:
        self.writer.write(_HEADER.pack(len(payload), kind) + payload)
        await self.writer.drain()

    def close(self) -> None:
        try:
            self.writer.close()
        except Exception:  # noqa: BLE001
            pass


class ConnectionRpcTransport(RpcTransport):
    """multiprocessing.Connection; reads run on the default thread-pool
    executor so the event loop never blocks (reference runs a dedicated
    read thread, rpc_reader.py:209-223)."""

    def __init__(self, connection: Any) -> None:
        self.connection = connection

    async def read(self) -> tuple[int, bytes]:
        loop = asyncio.get_running_loop()
        data = await loop.run_in_executor(None, self.connection.recv_bytes)
        kind = data[0]
        return kind, data[1:]

    async def write(self, kind: int, payload: bytes) -> None:
        self.connection.send_bytes(bytes([kind]) + payload)

    def close(self) -> None:
        try:
            self.connection.close()
        except Exception:  # noqa: BLE001
            pass


def prepare_peer_readloop(
    transport: RpcTransport,
    peer_name: str = "peer",
    pickler: Any = cloudpickle,
):
    """Returns (peer, readloop).  Run ``await readloop()`` until
    disconnect; it kills the peer on exit."""
    write_lock = asyncio.Lock()

    async def send(msg: dict, buffers: list[bytes]) -> None:
        async with write_lock:
            for buf in buffers:
                await transport.write(_BUF, buf)
            await transport.write(_MSG, pickler.dumps(msg))

    peer = RpcPeer(send, peer_name)

    async def readloop() -> None:
        pending_buffers: list[bytes] = []
        try:
            while True:
                kind, payload = await transport.read()
                if kind == _BUF:
                    pending_buffers.append(payload)
                    continue
                msg = pickler.loads(payload)
                buffers, pending_buffers = pending_buffers, []
                await peer.handle_message(msg, buffers)
        finally:
            peer.kill(f"{peer_name}: connection closed")
            transport.close()

    return peer, readloop
