"""RPC transports + wire format + fault injection.

Framing matches the reference's (rpc_reader.py:73-82, 117-125, 155-164):
``4-byte big-endian length | 1 type byte | payload`` where type 0 is a
pickled message dict and type 1 is a raw sideband buffer.  Buffers
precede the message that references them and are attached FIFO
(rpc_reader.py's LIFO pop is a known quirk we do not reproduce —
SURVEY.md "known reference quirks").

Transports:
- ``StreamRpcTransport``  — asyncio TCP, cloudpickle payloads: the
  cross-host path (reference RpcPickleStreamTransport,
  rpc_reader.py:146-181).
- ``ConnectionRpcTransport`` — multiprocessing.Pipe; both directions run
  on the default thread-pool executor so the event loop never blocks
  (reference runs a dedicated read thread, rpc_reader.py:184-206).

``prepare_peer_readloop`` glues a transport to an RpcPeer with a
mutex-serialized writer (rpc_reader.py:229-239) and returns
(peer, readloop); the read loop ending (EOF/error) kills the peer — that
is the disconnect-detection contract (SURVEY.md §5.3).

``FaultInjector`` is the deterministic fault hook the injection test
suite drives: a transport constructed with one consults it on every
outbound frame and can drop, delay, corrupt, or hang writes on demand.
Production transports carry no injector and pay only a None check.
"""

from __future__ import annotations

import asyncio
import struct
import threading
from typing import Any

import cloudpickle

from vllm_distributed_tpu.distributed.rpc import RpcPeer
from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)

_MSG = 0
_BUF = 1
_HEADER = struct.Struct(">IB")


class FaultInjector:
    """Deterministic outbound-frame faults for tests.

    Arm one mode at a time; ``after_writes`` frames pass through first so
    the arming RPC's own reply can escape before the fault engages:

    - ``drop``       — swallow the next *value* frames, then disarm;
    - ``blackhole``  — swallow every subsequent frame (one-way partition:
                       the socket stays open, nothing arrives);
    - ``corrupt``    — flip bytes in the next *value* frames (the reader
                       side fails to unpickle and kills the connection);
    - ``delay``      — sleep *value* seconds before each frame;
    - ``hang``       — block every write forever (wedged sender).

    State is lock-guarded: arming happens on worker threads while writes
    run on the transport's event loop.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._mode: str | None = None
        self._value: float = 0.0
        self._skip = 0
        self.frames_dropped = 0
        self.frames_corrupted = 0

    def arm(
        self, mode: str, value: float = 0.0, after_writes: int = 0
    ) -> None:
        if mode not in ("drop", "blackhole", "corrupt", "delay", "hang"):
            raise ValueError(f"unknown fault mode {mode!r}")
        with self._lock:
            self._mode = mode
            self._value = value
            self._skip = after_writes

    def disarm(self) -> None:
        with self._lock:
            self._mode = None

    async def on_write(
        self, kind: int, payload: bytes
    ) -> tuple[int, bytes] | None:
        """Apply the armed fault to one outbound frame.  Returns the
        (possibly corrupted) frame, or None to drop it; may sleep."""
        with self._lock:
            mode = self._mode
            if mode is None:
                return kind, payload
            if self._skip > 0:
                self._skip -= 1
                return kind, payload
            if mode == "drop":
                self._value -= 1
                if self._value <= 0:
                    self._mode = None
                self.frames_dropped += 1
                return None
            if mode == "blackhole":
                self.frames_dropped += 1
                return None
            if mode == "corrupt":
                self._value -= 1
                if self._value <= 0:
                    self._mode = None
                self.frames_corrupted += 1
                return kind, bytes(b ^ 0xFF for b in payload)
            delay = self._value
        if mode == "delay":
            await asyncio.sleep(delay)
            return kind, payload
        # hang: a wedged sender never completes this write.
        # vdt-lint: disable=unbounded-wait — the unbounded wait IS the
        # fault being injected (tests assert detection stays bounded).
        await asyncio.Event().wait()
        return None  # unreachable


# Process-global injector so the mock-worker layer (which lives behind an
# RPC boundary in the agent process) can arm faults on the agent's own
# transport.  Installed only when VDT_FAULT_INJECTION=1.
_global_injector: FaultInjector | None = None


def set_global_injector(injector: FaultInjector | None) -> None:
    global _global_injector
    _global_injector = injector


def get_global_injector() -> FaultInjector | None:
    return _global_injector


class RpcTransport:
    async def read(self) -> tuple[int, bytes]:
        raise NotImplementedError

    async def write(self, kind: int, payload: bytes) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class StreamRpcTransport(RpcTransport):
    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        injector: FaultInjector | None = None,
    ) -> None:
        self.reader = reader
        self.writer = writer
        self.injector = injector

    async def read(self) -> tuple[int, bytes]:
        # vdt-lint: disable=unbounded-wait — the read side blocks until
        # traffic or EOF by contract (SURVEY.md §5.3: read loop ending =
        # disconnect detection); liveness is owned by the heartbeat
        # loop, which closes this transport to unblock it.
        header = await self.reader.readexactly(_HEADER.size)
        length, kind = _HEADER.unpack(header)
        # vdt-lint: disable=unbounded-wait — same read-side contract.
        payload = await self.reader.readexactly(length)
        return kind, payload

    async def write(self, kind: int, payload: bytes) -> None:
        if self.injector is not None:
            frame = await self.injector.on_write(kind, payload)
            if frame is None:
                return
            kind, payload = frame
        self.writer.write(_HEADER.pack(len(payload), kind) + payload)
        # vdt-lint: disable=unbounded-wait — backpressure wait: deadline
        # ownership is the sender's (deadline-bounded applies time out
        # their own send; heartbeat misses kill a wedged peer, and the
        # kill path closes this writer, failing the drain).
        await self.writer.drain()

    def close(self) -> None:
        try:
            self.writer.close()
        except Exception as e:  # noqa: BLE001 — teardown best-effort
            logger.debug("stream transport close failed: %s", e)


class ConnectionRpcTransport(RpcTransport):
    """multiprocessing.Connection; reads AND writes run on the default
    thread-pool executor so the event loop never blocks (reference runs a
    dedicated read thread, rpc_reader.py:209-223; send_bytes can block on
    a full pipe just like recv_bytes on an empty one)."""

    def __init__(
        self, connection: Any, injector: FaultInjector | None = None
    ) -> None:
        self.connection = connection
        self.injector = injector

    async def read(self) -> tuple[int, bytes]:
        loop = asyncio.get_running_loop()
        data = await loop.run_in_executor(None, self.connection.recv_bytes)
        kind = data[0]
        return kind, data[1:]

    async def write(self, kind: int, payload: bytes) -> None:
        if self.injector is not None:
            frame = await self.injector.on_write(kind, payload)
            if frame is None:
                return
            kind, payload = frame
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(
            None, self.connection.send_bytes, bytes([kind]) + payload
        )

    def close(self) -> None:
        try:
            self.connection.close()
        except Exception as e:  # noqa: BLE001 — teardown best-effort
            logger.debug("pipe transport close failed: %s", e)


def prepare_peer_readloop(
    transport: RpcTransport,
    peer_name: str = "peer",
    pickler: Any = cloudpickle,
):
    """Returns (peer, readloop).  Run ``await readloop()`` until
    disconnect; it kills the peer on exit."""
    write_lock = asyncio.Lock()

    async def send(msg: dict, buffers: list[bytes]) -> None:
        async with write_lock:
            for buf in buffers:
                await transport.write(_BUF, buf)
            await transport.write(_MSG, pickler.dumps(msg))

    peer = RpcPeer(send, peer_name)

    async def readloop() -> None:
        pending_buffers: list[bytes] = []
        try:
            while True:
                # vdt-lint: disable=unbounded-wait — the read loop runs
                # until EOF by contract; heartbeats own liveness and
                # close the transport to end it.
                kind, payload = await transport.read()
                if kind == _BUF:
                    pending_buffers.append(payload)
                    continue
                msg = pickler.loads(payload)
                buffers, pending_buffers = pending_buffers, []
                await peer.handle_message(msg, buffers)
        finally:
            peer.kill(f"{peer_name}: connection closed")
            transport.close()

    return peer, readloop
