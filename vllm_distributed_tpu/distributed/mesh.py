"""Device-mesh construction.

The world is one jax.sharding.Mesh with named axes ("dp", "tp"; the
expert axis folds onto tp when EP is enabled) — parallelism becomes
sharding annotations over these axes instead of the reference's rank
arithmetic (launch.py:211-247; SURVEY.md §2.4, §7 design stance).  ICI
carries same-slice axes; DCN-spanning meshes put the outer (dp) axis
across hosts, which is what device order gives by default.  There is no
"pp" axis on purpose — see ParallelConfig's rejection rationale.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

from vllm_distributed_tpu.config import ParallelConfig


def build_mesh(parallel_config: ParallelConfig, devices=None) -> Mesh:
    tp = parallel_config.tensor_parallel_size
    dp = parallel_config.data_parallel_size
    devices = devices if devices is not None else jax.devices()
    need = tp * dp
    if len(devices) < need:
        raise ValueError(
            f"need {need} devices for dp={dp} tp={tp}, have {len(devices)}"
        )
    devices = np.asarray(devices[:need]).reshape(dp, tp)
    return Mesh(devices, axis_names=("dp", "tp"))
