"""Object-capability RPC peer — the control plane's core.

A clean-room reimplementation of the semantics the reference's vendored
RPC provides (rpc.py:1-619, SURVEY.md §2 C4): symmetric bidirectional
peers, named ``params`` lookup (``get_param``), remote invocation with
futures, transparent proxying of callables/objects (``RpcProxy``),
one-way calls, distributed GC of proxies via ``weakref.finalize`` →
``finalize`` messages, and cross-peer errors carrying remote stack
traces.  Message types mirror the reference's wire model:
``param`` / ``apply`` / ``result`` / ``finalize`` (rpc.py:495-585).

Differences by design (SURVEY.md §7: "implement exactly those" +
known-quirks list): values pass by value whenever the transport pickler
can carry them (SchedulerOutput etc.); only callables and objects marked
``__rpc_proxy__`` are proxied.  The reference's LIFO sideband-buffer bug
and proxy-method caching typo are not reproduced.
"""

from __future__ import annotations

import asyncio
import inspect
import traceback
import weakref
from typing import Any, Callable

from vllm_distributed_tpu import tracing
from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)

_PROXY_KEY = "__vdt_remote_proxy_id__"
_LOCAL_KEY = "__vdt_local_proxy_id__"


class RPCResultError(Exception):
    """An error raised on the remote side, re-raised locally with the
    remote traceback attached (reference: serializeError/deserializeError,
    rpc.py:243-263)."""

    def __init__(self, name: str, message: str, remote_stack: str) -> None:
        super().__init__(f"{name}: {message}\n--- remote stack ---\n{remote_stack}")
        self.name = name
        self.message = message
        self.remote_stack = remote_stack


class RpcProxy:
    """Local handle to a remote object.  Calling it or any attribute of it
    performs a remote apply."""

    def __init__(self, peer: "RpcPeer", proxy_id: str, description: str) -> None:
        object.__setattr__(self, "_peer", peer)
        object.__setattr__(self, "_proxy_id", proxy_id)
        object.__setattr__(self, "_description", description)

    def __call__(self, *args, **kwargs):
        return self._peer._apply(self._proxy_id, None, args, kwargs)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        peer, proxy_id = self._peer, self._proxy_id

        def method(*args, **kwargs):
            return peer._apply(proxy_id, name, args, kwargs)

        method.__name__ = name
        return method

    def __repr__(self) -> str:
        return f"<RpcProxy {self._description} id={self._proxy_id}>"


class RpcPeer:
    """One side of a connection.  ``send`` ships a (message-dict, buffers)
    pair to the other side; incoming traffic is fed to
    ``handle_message``."""

    def __init__(
        self,
        send: Callable[[dict, list[bytes]], Any],
        peer_name: str = "peer",
    ) -> None:
        self.send = send
        self.peer_name = peer_name
        self.params: dict[str, Any] = {}
        self._id_counter = 0
        self._pending: dict[str, asyncio.Future] = {}
        # proxy_id -> local object served to the remote side.
        self._local_proxied: dict[str, Any] = {}
        # id(obj) -> proxy_id, so the same object reuses one id.
        self._local_proxy_ids: dict[int, str] = {}
        # remote proxy_id -> live RpcProxy, so repeated references to one
        # remote object share a single proxy (and a single finalize).
        self._remote_proxies: "weakref.WeakValueDictionary[str, RpcProxy]" = (
            weakref.WeakValueDictionary()
        )
        self._killed: str | None = None
        self.kill_listeners: list[Callable[[str], None]] = []
        # The event loop this peer lives on (set on first use from loop
        # context); finalize callbacks may fire on arbitrary GC threads
        # and must hop onto it via call_soon_threadsafe.
        self._loop: asyncio.AbstractEventLoop | None = None
        try:
            self._loop = asyncio.get_running_loop()
        except RuntimeError:
            pass

    # ---- ids ----
    def _next_id(self) -> str:
        self._id_counter += 1
        return f"{self._id_counter}"

    # ---- serialization of message values ----
    def _should_proxy(self, value: Any) -> bool:
        return callable(value) or getattr(value, "__rpc_proxy__", False)

    def _serialize(self, value: Any) -> Any:
        if isinstance(value, RpcProxy):
            if value._peer is self:
                # Round-trips back to the original local object.
                return {_LOCAL_KEY: value._proxy_id}
            raise ValueError("cannot forward a proxy belonging to another peer")
        if isinstance(value, (list, tuple)):
            return [self._serialize(v) for v in value]
        if isinstance(value, dict):
            return {k: self._serialize(v) for k, v in value.items()}
        if self._should_proxy(value):
            proxy_id = self._local_proxy_ids.get(id(value))
            if proxy_id is None:
                proxy_id = self._next_id()
                self._local_proxy_ids[id(value)] = proxy_id
                self._local_proxied[proxy_id] = value
            return {
                _PROXY_KEY: proxy_id,
                "description": getattr(value, "__name__", type(value).__name__),
            }
        return value

    def _deserialize(self, value: Any) -> Any:
        if isinstance(value, dict):
            if _PROXY_KEY in value:
                pid = value[_PROXY_KEY]
                proxy = self._remote_proxies.get(pid)
                if proxy is None:
                    proxy = RpcProxy(
                        self, pid, value.get("description", "?")
                    )
                    self._remote_proxies[pid] = proxy
                    weakref.finalize(
                        proxy, _send_finalize, weakref.ref(self), pid
                    )
                return proxy
            if _LOCAL_KEY in value:
                return self._local_proxied[value[_LOCAL_KEY]]
            return {k: self._deserialize(v) for k, v in value.items()}
        if isinstance(value, list):
            return [self._deserialize(v) for v in value]
        return value

    # ---- outgoing ----
    async def get_param(self, name: str) -> Any:
        reply_id = self._next_id()
        fut = self._make_pending(reply_id)
        if not fut.done():
            await self._send(
                {"type": "param", "id": reply_id, "param": name}
            )
        # vdt-lint: disable=unbounded-wait — param fetches are boot-time
        # calls; every call site bounds them (wait_for in _handle_agent /
        # _heartbeat_loop, .result(timeout=INIT) around _create_remote_workers).
        return await fut

    # camelCase alias matching the reference surface (launch.py:190).
    getParam = get_param

    def _apply(
        self,
        proxy_id: str,
        method: str | None,
        args: tuple,
        kwargs: dict,
        *,
        oneway: bool = False,
        timeout: float | None = None,
    ):
        msg = {
            "type": "apply",
            "proxyId": proxy_id,
            "method": method,
            "args": self._serialize(list(args)),
            "kwargs": self._serialize(kwargs),
        }
        if oneway:
            # No reply frame, so no trace context either: worker spans
            # could never ship back.
            msg["oneway"] = True
            return self._send(msg)
        # Trace propagation (tracing.py): the caller's active span
        # context rides inside the frame, so the remote side's
        # execute/serialize/reply spans land in the SAME trace with
        # parent/child links across the RPC boundary.  One contextvar
        # read when tracing is off.
        ctx = tracing.current_ctx()
        if ctx is not None and tracing.get_tracer().enabled:
            msg["trace"] = [ctx[0], ctx[1]]
        reply_id = self._next_id()
        msg["id"] = reply_id

        async def send_then_wait():
            fut = self._make_pending(reply_id)
            if fut.done():
                # vdt-lint: disable=unbounded-wait — already resolved
                # (killed-peer short circuit); the await just re-raises.
                return await fut
            if timeout is None:
                await self._send(msg)
                # vdt-lint: disable=unbounded-wait — timeout=None is the
                # caller's explicit contract (device work whose deadline
                # the dispatching executor owns, cf. collective_rpc).
                return await fut

            async def send_and_wait():
                # The deadline covers the SEND too: a peer that stops
                # reading backs up the transport (drain blocks, the
                # writer mutex queues everyone behind it) and must still
                # count as a miss, not wedge the caller.
                await self._send(msg)
                return await fut

            try:
                return await asyncio.wait_for(send_and_wait(), timeout)
            except asyncio.TimeoutError:
                # Reclaim the pending slot: if the reply frame was lost
                # (not merely late), nothing will ever resolve it, and
                # repeated deadline-bounded calls (heartbeats) must not
                # grow the pending map.  A late reply finding no slot is
                # dropped by _handle_result.
                self._pending.pop(reply_id, None)
                raise

        return send_then_wait()

    def _make_pending(self, reply_id: str) -> asyncio.Future:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        if self._killed is not None:
            fut.set_exception(RPCResultError(
                "PeerKilled", "peer is killed", ""
            ))
            return fut
        self._pending[reply_id] = fut
        return fut

    async def _send(self, msg: dict) -> None:
        buffers: list[bytes] = []
        msg = _extract_buffers(msg, buffers)
        result = self.send(msg, buffers)
        if inspect.isawaitable(result):
            # vdt-lint: disable=unbounded-wait — transport backpressure:
            # deadline-bounded applies cover their own send (send_and_wait
            # inside wait_for), and a wedged peer trips the heartbeat
            # watchdog, which kills this peer and fails the wait.
            await result

    # ---- incoming ----
    async def handle_message(
        self, msg: dict, buffers: list[bytes] | None = None
    ) -> None:
        msg = _restore_buffers(msg, buffers or [])
        if self._loop is None:
            self._loop = asyncio.get_running_loop()
        mtype = msg.get("type")
        if mtype == "param":
            await self._handle_param(msg)
        elif mtype == "apply":
            # Run as a task, NOT inline: a handler may itself await an RPC
            # back to the caller (the create_worker callback pattern,
            # launch.py:238), and the read loop must keep draining results
            # while the handler is in flight.  Tasks start in message
            # order, so single-threaded targets still see ordered calls.
            asyncio.ensure_future(self._handle_apply(msg))
        elif mtype == "result":
            self._handle_result(msg)
        elif mtype == "finalize":
            pid = msg.get("proxyId")
            obj = self._local_proxied.pop(pid, None)
            if obj is not None:
                self._local_proxy_ids.pop(id(obj), None)
        else:
            logger.warning("%s: unknown rpc message type %r", self.peer_name, mtype)

    async def _handle_param(self, msg: dict) -> None:
        reply = {"type": "result", "id": msg["id"]}
        try:
            value = self.params[msg["param"]]
            reply["result"] = self._serialize(value)
        except Exception as e:  # noqa: BLE001
            reply.update(_serialize_error(e))
        await self._send(reply)

    async def _handle_apply(self, msg: dict) -> None:
        oneway = msg.get("oneway", False)
        reply = {"type": "result", "id": msg.get("id")}
        # Inbound trace context (see _apply): wrap the local execution
        # in worker-side spans and ship them back inside the reply frame
        # — they are `record=False` so this process accumulates no
        # orphan traces for work it performed on another host's behalf.
        tracer = tracing.get_tracer()
        trace = msg.get("trace") if not oneway else None
        parent = (
            (trace[0], trace[1])
            if trace is not None and tracer.enabled
            else None
        )
        spans = []
        try:
            target = self._local_proxied[msg["proxyId"]]
            method = msg.get("method")
            fn = getattr(target, method) if method else target
            args = self._deserialize(msg.get("args") or [])
            kwargs = self._deserialize(msg.get("kwargs") or {})
            if parent is not None:
                # try/finally so a raising call still ships its (error-
                # annotated) span back to the driver in the error reply.
                sp = None
                try:
                    with tracer.span(
                        "worker.execute",
                        parent=parent,
                        record=False,
                        method=str(method or "__call__"),
                    ) as sp:
                        value = fn(*args, **kwargs)
                        if inspect.isawaitable(value):
                            # vdt-lint: disable=unbounded-wait — executing
                            # the target on the REMOTE caller's behalf; the
                            # calling side owns the deadline (apply_with_
                            # timeout) and long device work is legitimate.
                            value = await value
                finally:
                    if sp is not None:
                        spans.append(sp)
            else:
                value = fn(*args, **kwargs)
                if inspect.isawaitable(value):
                    # vdt-lint: disable=unbounded-wait — same contract as
                    # the traced branch above: the remote caller's
                    # deadline bounds this execution.
                    value = await value
            if oneway:
                return
            if parent is not None:
                sp = None
                try:
                    with tracer.span(
                        "worker.serialize", parent=parent, record=False
                    ) as sp:
                        reply["result"] = self._serialize(value)
                finally:
                    if sp is not None:
                        spans.append(sp)
            else:
                reply["result"] = self._serialize(value)
        except Exception as e:  # noqa: BLE001
            if oneway:
                logger.exception(
                    "%s: error in oneway apply", self.peer_name
                )
                return
            reply.update(_serialize_error(e))
        if parent is not None:
            reply["trace_spans"] = [s.to_wire() for s in spans] + [
                tracer.stamp("worker.reply", parent)
            ]
        await self._send(reply)

    def _handle_result(self, msg: dict) -> None:
        spans = msg.get("trace_spans")
        if spans:
            # Worker-side spans riding the reply frame: merge them into
            # the local trace (clock-offset corrected per host).
            tracing.get_tracer().adopt(spans)
        fut = self._pending.pop(msg.get("id"), None)
        if fut is None or fut.done():
            return
        if "error" in msg:
            e = msg["error"]
            fut.set_exception(
                RPCResultError(
                    e.get("name", "Error"),
                    e.get("message", ""),
                    e.get("stack", ""),
                )
            )
        else:
            fut.set_result(self._deserialize(msg.get("result")))

    # ---- teardown ----
    def kill(self, reason: str = "peer killed") -> None:
        """Fail every pending call and notify listeners.  Disconnect
        detection = transport read loop ending → kill (SURVEY.md §5.3)."""
        if self._killed is not None:
            return
        self._killed = reason
        err = RPCResultError("PeerKilled", reason, "")
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(err)
        self._pending.clear()
        for listener in self.kill_listeners:
            try:
                listener(reason)
            except Exception:  # noqa: BLE001
                logger.exception("kill listener failed")

    @property
    def killed(self) -> bool:
        return self._killed is not None

    @property
    def killed_reason(self) -> str | None:
        return self._killed


def apply_with_timeout(proxy: RpcProxy, timeout: float, *args, **kwargs):
    """Invoke ``proxy(*args, **kwargs)`` with a deadline.  Unlike wrapping
    the call in ``asyncio.wait_for`` from the outside, the peer's pending
    slot is reclaimed on timeout, so lost reply frames cannot leak
    futures (the heartbeat loop calls this every interval forever)."""
    return proxy._peer._apply(
        proxy._proxy_id, None, args, kwargs, timeout=timeout
    )


def apply_oneway(proxy: RpcProxy, method: str | None, *args, **kwargs):
    """Fire-and-forget apply: one frame on the wire, NO reply slot, no
    pending-map entry, nothing to time out.  The step-stream protocol's
    per-step sends (driver→host step frames, host→driver result acks)
    ride this — result delivery and failure detection are owned by the
    stream coordinator, not by per-call futures."""
    return proxy._peer._apply(
        proxy._proxy_id, method, args, kwargs, oneway=True
    )


def _send_finalize(peer_ref, proxy_id: str) -> None:
    """weakref.finalize callback: tell the remote side its object is no
    longer referenced here (distributed GC, reference rpc.py finalize).
    May fire on ANY thread, so it hops onto the peer's loop."""
    peer = peer_ref()
    if peer is None or peer.killed or peer._loop is None:
        return
    msg = {"type": "finalize", "proxyId": proxy_id}
    try:
        peer._loop.call_soon_threadsafe(
            lambda: asyncio.ensure_future(peer._send(msg))
        )
    except RuntimeError:
        pass  # loop closed — process is exiting


def _serialize_error(e: Exception) -> dict:
    return {
        "error": {
            "name": type(e).__name__,
            "message": str(e),
            "stack": traceback.format_exc(),
        }
    }


_BUFFER_KEY = "__vdt_buffer__"


def _extract_buffers(value: Any, buffers: list[bytes]) -> Any:
    """Replace bytes-like leaves with sideband indices; the transport ships
    the raw buffers as separate frames (reference SidebandBufferSerializer,
    rpc_reader.py:26-38 — FIFO here, fixing the upstream LIFO bug)."""
    if isinstance(value, (bytes, bytearray, memoryview)):
        buffers.append(bytes(value))
        return {_BUFFER_KEY: len(buffers) - 1}
    if isinstance(value, dict):
        return {k: _extract_buffers(v, buffers) for k, v in value.items()}
    if isinstance(value, list):
        return [_extract_buffers(v, buffers) for v in value]
    return value


def _restore_buffers(value: Any, buffers: list[bytes]) -> Any:
    if isinstance(value, dict):
        if _BUFFER_KEY in value:
            return buffers[value[_BUFFER_KEY]]
        return {k: _restore_buffers(v, buffers) for k, v in value.items()}
    if isinstance(value, list):
        return [_restore_buffers(v, buffers) for v in value]
    return value
