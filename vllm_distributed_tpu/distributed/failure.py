"""Structured per-host failure attribution for the control plane.

The reference (and vLLM upstream) surface worker loss as an
undifferentiated "engine dead"; a multi-host TPU deployment over DCN has
strictly more ways to partially fail, so every kill path in the control
plane produces a ``HostFailure`` naming WHICH host failed, in WHICH
lifecycle phase, and WHY.  The record travels executor → engine →
AsyncLLM → ``/health`` 503 body / ``vllm:engine_dead_info`` verbatim, so
the operator's first signal already carries the attribution.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

# Lifecycle phases a host can fail in, in boot order.
PHASE_CONNECT = "connect"      # dialing / connection lost
PHASE_INIT = "init"            # remote worker creation / device init
PHASE_EXECUTE = "execute"      # collective_rpc / execute_model
PHASE_HEARTBEAT = "heartbeat"  # liveness probe missed


@dataclass
class HostFailure:
    """One host's failure: who, where in the lifecycle, and the cause
    chain.  ``host_rank == -1`` means no single host is attributable
    (e.g. boot timed out with several agents missing)."""

    host_rank: int
    address: str
    phase: str
    message: str
    cause: str = ""  # flattened exception chain, innermost last
    timestamp: float = field(default_factory=time.time)

    @property
    def recoverable(self) -> bool:
        """Whether an in-process engine rebuild (engine/supervisor.py)
        can plausibly clear this failure.  True for failures pinned on a
        live deployment member — a lost/wedged host comes back when its
        agent redials.  False for attribution-free connect collapses
        (``host_rank == -1``: the deployment never assembled, so a
        rebuild just repeats the same boot timeout)."""
        return self.host_rank >= 0 or self.phase != PHASE_CONNECT

    def describe(self) -> str:
        where = (
            f"host {self.host_rank}" if self.host_rank >= 0 else "deployment"
        )
        if self.address:
            where += f" ({self.address})"
        text = f"[{self.phase}] {where}: {self.message}"
        if self.cause:
            text += f" | cause: {self.cause}"
        return text

    def to_dict(self) -> dict:
        return {
            "host_rank": self.host_rank,
            "address": self.address,
            "phase": self.phase,
            "message": self.message,
            "cause": self.cause,
            "timestamp": self.timestamp,
        }

    @classmethod
    def from_exception(
        cls,
        host_rank: int,
        address: str,
        phase: str,
        message: str,
        exc: BaseException,
    ) -> "HostFailure":
        return cls(
            host_rank=host_rank,
            address=address,
            phase=phase,
            message=message,
            cause=format_cause_chain(exc),
        )


def format_cause_chain(exc: BaseException, limit: int = 5) -> str:
    """Flatten ``raise X from Y`` / implicit-context chains into one
    line: ``TypeError('a') <- OSError('b')``, innermost cause last."""
    parts: list[str] = []
    seen: set[int] = set()
    cur: BaseException | None = exc
    while cur is not None and id(cur) not in seen and len(parts) < limit:
        seen.add(id(cur))
        parts.append(f"{type(cur).__name__}({str(cur)!r})")
        cur = cur.__cause__ or cur.__context__
    return " <- ".join(parts)
