"""Environment-variable registry.

The reference treats vLLM's ``envs.environment_variables`` as the single
registry of recognized env vars and uses it as the replication allowlist
when forwarding driver env vars to remote workers (launch.py:26, 62-72,
198-208).  We keep that design: every env var the framework understands is
declared here, and the control plane replicates everything in the registry
*except* per-host variables to remote hosts.
"""

from __future__ import annotations

import os
from collections.abc import Callable
from typing import Any

# name -> lambda returning the parsed value (lazy so tests can monkeypatch).
environment_variables: dict[str, Callable[[], Any]] = {
    # --- control plane ---
    "VDT_SERVER_PORT": lambda: int(os.environ.get("VDT_SERVER_PORT", "30044")),
    "VDT_HOST_IP": lambda: os.environ.get("VDT_HOST_IP", ""),
    # per-step execute timeout, like VLLM_EXECUTE_MODEL_TIMEOUT_SECONDS
    # (launch.py:334, 343)
    "VDT_EXECUTE_MODEL_TIMEOUT_SECONDS": lambda: int(
        os.environ.get("VDT_EXECUTE_MODEL_TIMEOUT_SECONDS", "300")
    ),
    "VDT_HEALTH_CHECK_TIMEOUT_SECONDS": lambda: int(
        os.environ.get("VDT_HEALTH_CHECK_TIMEOUT_SECONDS", "10")
    ),
    # Heartbeat liveness: the driver pings every remote agent on this
    # interval (seconds, float; 0 disables); VDT_HEARTBEAT_MISS_THRESHOLD
    # consecutive misses mark the host dead without waiting for a request
    # to hit the execute timeout.  Replicated to agents, which run the
    # symmetric watchdog and fail-fast when the server goes silent.
    "VDT_HEARTBEAT_INTERVAL_SECONDS": lambda: float(
        os.environ.get("VDT_HEARTBEAT_INTERVAL_SECONDS", "10")
    ),
    "VDT_HEARTBEAT_MISS_THRESHOLD": lambda: int(
        os.environ.get("VDT_HEARTBEAT_MISS_THRESHOLD", "3")
    ),
    # Boot deadlines: how long the driver waits for all agents to dial in
    # (0 = forever), and for remote worker creation.
    "VDT_CONNECT_TIMEOUT_SECONDS": lambda: float(
        os.environ.get("VDT_CONNECT_TIMEOUT_SECONDS", "600")
    ),
    "VDT_INIT_TIMEOUT_SECONDS": lambda: float(
        os.environ.get("VDT_INIT_TIMEOUT_SECONDS", "120")
    ),
    # Retry-After hint (seconds) on 503s while the engine is dead and the
    # supervisor is reforming the deployment.
    "VDT_RETRY_AFTER_SECONDS": lambda: int(
        os.environ.get("VDT_RETRY_AFTER_SECONDS", "30")
    ),
    # In-process engine recovery (engine/supervisor.py): how many
    # executor rebuilds are attempted within the crash-loop window
    # before a control-plane death becomes terminal.  0 disables
    # recovery entirely (every HostFailure is fatal, the pre-supervisor
    # behavior).
    "VDT_MAX_ENGINE_RESTARTS": lambda: int(
        os.environ.get("VDT_MAX_ENGINE_RESTARTS", "3")
    ),
    # Exponential backoff between rebuild attempts: base * 2^attempt,
    # capped.  The /health Retry-After during RECOVERING derives from
    # the current delay.
    "VDT_ENGINE_RESTART_BACKOFF_SECONDS": lambda: float(
        os.environ.get("VDT_ENGINE_RESTART_BACKOFF_SECONDS", "1")
    ),
    "VDT_ENGINE_RESTART_BACKOFF_CAP_SECONDS": lambda: float(
        os.environ.get("VDT_ENGINE_RESTART_BACKOFF_CAP_SECONDS", "30")
    ),
    # Restarts older than this window are forgotten; more than
    # VDT_MAX_ENGINE_RESTARTS *within* it is a crash loop -> give up.
    "VDT_CRASH_LOOP_WINDOW_SECONDS": lambda: float(
        os.environ.get("VDT_CRASH_LOOP_WINDOW_SECONDS", "300")
    ),
    # Persistent per-host step streams (executor/multihost.py): per-step
    # control messages ride one one-way frame each way through a
    # long-lived run loop instead of dispatch/fetch request-reply pairs.
    # "0" falls back to the legacy two-phase RPC path.
    "VDT_STEP_STREAMS": lambda: os.environ.get(
        "VDT_STEP_STREAMS", "1"
    ).lower() not in ("", "0", "false", "off"),
    # Bound on each host's step-stream inbox (queued-but-undispatched
    # frames); the engine keeps at most max_concurrent_dispatches steps
    # in flight, so this only guards against a runaway driver.
    "VDT_STEP_STREAM_DEPTH": lambda: int(
        os.environ.get("VDT_STEP_STREAM_DEPTH", "8")
    ),
    # --- overload resilience (ISSUE 8) ---
    # Bounded admission: caps on the admission queue (waiting requests
    # not yet scheduled + adds still in the intake).  0 = unbounded —
    # the seed behavior.  Exceeding a cap rejects the request with a
    # typed EngineOverloadedError (HTTP 429 + Retry-After), never an
    # unbounded queue.
    "VDT_MAX_WAITING_REQUESTS": lambda: int(
        os.environ.get("VDT_MAX_WAITING_REQUESTS", "0")
    ),
    # Cap on queued PROMPT tokens awaiting prefill (same scope as
    # above); bounds admission memory independently of request count so
    # a few huge prompts can't evade the depth cap.  0 = unbounded.
    "VDT_MAX_QUEUED_TOKENS": lambda: int(
        os.environ.get("VDT_MAX_QUEUED_TOKENS", "0")
    ),
    # KV backpressure: reject admission when the prompt's estimated
    # page demand (prefix-cache-aware) would leave fewer than this
    # fraction of usable KV pages free.  0 = off.
    "VDT_KV_ADMISSION_WATERMARK": lambda: float(
        os.environ.get("VDT_KV_ADMISSION_WATERMARK", "0")
    ),
    # Server-default per-request deadline (milliseconds) when the
    # client sends none (X-VDT-Deadline-Ms header / deadline_ms body
    # field).  Expired waiting requests are shed before prefill;
    # expired running requests finish with finish_reason="timeout" and
    # partial output.  0 = no default deadline.
    "VDT_DEFAULT_DEADLINE_MS": lambda: int(
        os.environ.get("VDT_DEFAULT_DEADLINE_MS", "0")
    ),
    # Sustained-pressure preempt-to-shed: a request preempted more than
    # this many times while others still wait is finished with
    # finish_reason="overloaded" (HTTP 429 on the non-streaming path)
    # instead of thrashing the allocator with recompute cycles.
    # 0 = off (preempt/resume forever, the seed policy).
    "VDT_PREEMPT_SHED_THRESHOLD": lambda: int(
        os.environ.get("VDT_PREEMPT_SHED_THRESHOLD", "0")
    ),
    # Retry-After hint (seconds) on 429 overload rejections (distinct
    # from VDT_RETRY_AFTER_SECONDS, the dead/recovering 503 hint:
    # overload clears in ITL-scale time, a dead engine in restart-scale
    # time).
    "VDT_OVERLOAD_RETRY_AFTER_SECONDS": lambda: int(
        os.environ.get("VDT_OVERLOAD_RETRY_AFTER_SECONDS", "1")
    ),
    # Graceful drain: how long /drain (and the SIGTERM handler) lets
    # in-flight requests finish before journaling the rest.
    "VDT_DRAIN_TIMEOUT_SECONDS": lambda: float(
        os.environ.get("VDT_DRAIN_TIMEOUT_SECONDS", "30")
    ),
    # Where the drain journal is written (and loaded from at boot).
    # Empty = drain finishes by aborting unfinished requests instead of
    # journaling them.  Per-host: a replica's journal must never be
    # replicated onto its workers.
    "VDT_DRAIN_JOURNAL_PATH": lambda: os.environ.get(
        "VDT_DRAIN_JOURNAL_PATH", ""
    ),
    # --- tiered KV cache (ISSUE 14) ---
    # Host-DRAM spill tier size in KV pages: pages evicted from the HBM
    # pool spill to a bounded host pool (worker-side device_get) and
    # stream back ahead of a prefill resume instead of being
    # recomputed.  0 = off (the default; evictions discard KV exactly
    # like the seed prefix cache).  Only meaningful with
    # --enable-prefix-caching and the radix index.
    "VDT_KV_SPILL_HOST_PAGES": lambda: int(
        os.environ.get("VDT_KV_SPILL_HOST_PAGES", "0")
    ),
    # Restore-vs-recompute crossover: a host-resident run shorter than
    # this many tokens is recomputed instead of restored (below the
    # crossover a DMA round trip costs more than the prefill it saves —
    # bench the sweep with tools/prefix_cache_ablation.py --tiered).
    "VDT_KV_SPILL_RESTORE_MIN_TOKENS": lambda: int(
        os.environ.get("VDT_KV_SPILL_RESTORE_MIN_TOKENS", "32")
    ),
    # --- speculative decoding (ISSUE 11) ---
    # Max tokens the n-gram prompt-lookup proposer drafts per request
    # per step (--speculative-ngram-k); the model runner verifies all
    # drafts in one fused device pass and greedy accept/reject keeps
    # the matching prefix + one bonus token.  0 = off (the default);
    # greedy outputs are bit-identical either way.
    "VDT_SPEC_NGRAM_K": lambda: int(
        os.environ.get("VDT_SPEC_NGRAM_K", "0")
    ),
    # Tail n-gram match lengths the proposer tries, longest first.
    "VDT_SPEC_NGRAM_MAX": lambda: int(
        os.environ.get("VDT_SPEC_NGRAM_MAX", "3")
    ),
    "VDT_SPEC_NGRAM_MIN": lambda: int(
        os.environ.get("VDT_SPEC_NGRAM_MIN", "1")
    ),
    # --- multi-replica routing (ISSUE 10) ---
    # Stable identity of this serving replica, surfaced in /health, the
    # X-VDT-Replica-Id response header, and the vllm:replica_info gauge
    # so router logs/traces/bench can attribute per-replica behavior.
    # Empty = derived from the API server's host:port at boot.
    "VDT_REPLICA_ID": lambda: os.environ.get("VDT_REPLICA_ID", ""),
    # Router backend set: comma-separated replica base URLs
    # (e.g. "http://h1:8000,http://h2:8000").  The `vdt router`
    # --replica flag extends/overrides this.
    "VDT_ROUTER_REPLICAS": lambda: [
        u.strip().rstrip("/")
        for u in os.environ.get("VDT_ROUTER_REPLICAS", "").split(",")
        if u.strip()
    ],
    # Placement policy: "affinity" (prefix-cache affinity, falling back
    # to least-loaded), "least_loaded", or "round_robin" (the A/B
    # baseline bench-serve compares against).
    "VDT_ROUTER_POLICY": lambda: os.environ.get(
        "VDT_ROUTER_POLICY", "affinity"
    ),
    # Replica health-poll interval (seconds); each probe is
    # deadline-bounded by the connect/read timeouts below.
    "VDT_ROUTER_HEALTH_INTERVAL_SECONDS": lambda: float(
        os.environ.get("VDT_ROUTER_HEALTH_INTERVAL_SECONDS", "2")
    ),
    # Affinity index granularity: tokens (or ~4-byte text chunks) per
    # hash-chain block — match the engine page_size so a router block
    # maps onto one cached KV page.
    "VDT_ROUTER_AFFINITY_BLOCK_TOKENS": lambda: int(
        os.environ.get("VDT_ROUTER_AFFINITY_BLOCK_TOKENS", "16")
    ),
    # Per-replica cap on remembered prefix blocks (LRU beyond it).
    "VDT_ROUTER_AFFINITY_CAPACITY": lambda: int(
        os.environ.get("VDT_ROUTER_AFFINITY_CAPACITY", "8192")
    ),
    # Minimum matched tokens before affinity outranks least-loaded
    # placement (below it the signal is noise, not a warm cache).
    "VDT_ROUTER_AFFINITY_MIN_TOKENS": lambda: int(
        os.environ.get("VDT_ROUTER_AFFINITY_MIN_TOKENS", "16")
    ),
    # How many times one request may be live-migrated (journal-replayed
    # onto another replica) before the router gives up on it.
    "VDT_ROUTER_MAX_MIGRATIONS": lambda: int(
        os.environ.get("VDT_ROUTER_MAX_MIGRATIONS", "3")
    ),
    # Upstream deadlines: TCP connect, and the per-read socket timeout
    # on proxied (SSE) responses.
    "VDT_ROUTER_CONNECT_TIMEOUT_SECONDS": lambda: float(
        os.environ.get("VDT_ROUTER_CONNECT_TIMEOUT_SECONDS", "5")
    ),
    "VDT_ROUTER_READ_TIMEOUT_SECONDS": lambda: float(
        os.environ.get("VDT_ROUTER_READ_TIMEOUT_SECONDS", "600")
    ),
    # --- crash-safe router (ISSUE 17) ---
    # Directory for the router's durable control-plane state (a bounded
    # write-ahead log of fleet membership, in-flight request journal
    # checkpoints, and QoS/placement config).  Empty (the default) =
    # no persistence: the router behaves exactly as before.  With a
    # state dir set, a restarted router re-adopts still-running managed
    # replicas instead of leaking or respawning them, and replays
    # journaled in-flight requests when their clients reconnect.
    "VDT_ROUTER_STATE_DIR": lambda: os.environ.get(
        "VDT_ROUTER_STATE_DIR", ""
    ),
    # WAL segment rotation threshold: when the live segment exceeds
    # this many bytes it is compacted (current membership + config +
    # live journals only) into a fresh segment via atomic rename, so
    # the on-disk state stays bounded regardless of uptime.
    "VDT_ROUTER_STATE_SEGMENT_BYTES": lambda: int(
        os.environ.get("VDT_ROUTER_STATE_SEGMENT_BYTES", "4194304")
    ),
    # Bounded fsync cadence: appended records are flushed to the OS on
    # every write but fsync'd at most this often (plus on rotation and
    # close) — a crash can lose at most this window of checkpoints,
    # never the membership records (those fsync immediately).
    "VDT_ROUTER_STATE_FSYNC_INTERVAL_SECONDS": lambda: float(
        os.environ.get("VDT_ROUTER_STATE_FSYNC_INTERVAL_SECONDS", "0.2")
    ),
    # Per-request journal checkpoint cadence: a live stream's cumulative
    # journal (prompt ids + emitted tokens) is re-recorded at most this
    # often — NOT per token, which would make the WAL quadratic in
    # stream length.  Replays after a crash may therefore re-emit up to
    # this window's worth of tokens; the reconnecting client trims them
    # via X-VDT-Resume-Tokens.
    "VDT_ROUTER_STATE_CKPT_INTERVAL_SECONDS": lambda: float(
        os.environ.get("VDT_ROUTER_STATE_CKPT_INTERVAL_SECONDS", "0.25")
    ),
    # Re-adoption grace window: a recovered replica enters the pool in
    # the `verifying` state and transport-level probe failures within
    # this window keep it there (with faster jittered re-probes)
    # instead of declaring it unreachable — a restart storm must not
    # mass-eject a healthy fleet that is briefly slow to answer.
    "VDT_ROUTER_STATE_VERIFY_WINDOW_SECONDS": lambda: float(
        os.environ.get("VDT_ROUTER_STATE_VERIFY_WINDOW_SECONDS", "10")
    ),
    # How long recovered in-flight journals are held for client
    # reconnects after a router restart.  A client that reconnects with
    # X-VDT-Resume-Id inside the window finishes its generation
    # bit-identically; after it, the id gets a clean 503 (retry fresh).
    "VDT_ROUTER_STATE_RECOVERY_TTL_SECONDS": lambda: float(
        os.environ.get("VDT_ROUTER_STATE_RECOVERY_TTL_SECONDS", "120")
    ),
    # --- disaggregated prefill/decode (ISSUE 15) ---
    # Role this serving replica announces in /health ("prefill" |
    # "decode" | "mixed").  The router places long prompts on the
    # prefill pool and hands their KV pages off to a decode-pool
    # replica at first token; "mixed" (the default) serves both phases
    # exactly as before — a fleet with no prefill-role replica never
    # takes the disagg path.
    "VDT_ROUTER_ROLE": lambda: os.environ.get(
        "VDT_ROUTER_ROLE", "mixed"
    ),
    # Prompt-length crossover (router-side): only prompts at/above this
    # many (estimated) tokens are prefilled on the prefill pool and
    # handed off; below it the transfer costs more than the prefill it
    # isolates, so the request is served on the decode/mixed pool like
    # today (tools/disagg_crossover.py benches the sweep).
    "VDT_DISAGG_MIN_PROMPT_TOKENS": lambda: int(
        os.environ.get("VDT_DISAGG_MIN_PROMPT_TOKENS", "512")
    ),
    # KV-page streaming granularity: layers per /internal/kv chunk on
    # the prefill->decode hop (bounds per-frame memory on both sides of
    # the DCN transfer).
    "VDT_DISAGG_CHUNK_LAYERS": lambda: int(
        os.environ.get("VDT_DISAGG_CHUNK_LAYERS", "4")
    ),
    # How long a prefill-only request's KV pages stay held for export
    # after it finishes.  A router that dies mid-hand-off must never
    # leak pool pages forever: expired holds are swept at schedule
    # time and freed like a normal finish.
    "VDT_DISAGG_EXPORT_TTL_SECONDS": lambda: float(
        os.environ.get("VDT_DISAGG_EXPORT_TTL_SECONDS", "30")
    ),
    # Inbound /internal/kv frame-size bound (bytes): chunk frames whose
    # Content-Length exceeds it are rejected with a typed 413 BEFORE
    # buffering, so a misconfigured (or hostile) peer can't balloon the
    # import side's memory.  0 disables the check.
    "VDT_KV_MAX_FRAME_BYTES": lambda: int(
        os.environ.get("VDT_KV_MAX_FRAME_BYTES", "67108864")
    ),
    # --- resilient DCN data plane (ISSUE 19) ---
    # All default-off: with none of these set, every router->replica
    # call keeps its fixed ClientTimeout, retries are unbounded (the
    # pre-existing migration caps still apply), no hedges fire, and the
    # KV transfer protocol is byte-identical to ISSUE 15.
    # Consecutive transport failures/timeouts that trip a replica's
    # circuit breaker open (0 = consecutive-failure trip off).  Open
    # replicas are skipped by placement like unhealthy ones.
    "VDT_ROUTER_BREAKER_FAILURES": lambda: int(
        os.environ.get("VDT_ROUTER_BREAKER_FAILURES", "0")
    ),
    # Open -> half-open after this long; the half-open breaker admits
    # exactly one probe request (success closes, failure re-opens).
    "VDT_ROUTER_BREAKER_COOLDOWN_SECONDS": lambda: float(
        os.environ.get("VDT_ROUTER_BREAKER_COOLDOWN_SECONDS", "5")
    ),
    # Windowed timeout-rate trip: the breaker also opens when at least
    # this fraction of the last window's outcomes were timeouts (needs
    # >= 10 samples in the window; 0 = rate trip off).
    "VDT_ROUTER_BREAKER_TIMEOUT_RATE": lambda: float(
        os.environ.get("VDT_ROUTER_BREAKER_TIMEOUT_RATE", "0")
    ),
    "VDT_ROUTER_BREAKER_WINDOW_SECONDS": lambda: float(
        os.environ.get("VDT_ROUTER_BREAKER_WINDOW_SECONDS", "30")
    ),
    # Retry budget (global + per-replica, monotonic token accounting):
    # a retry or hedge is granted only while granted < min + ratio *
    # attempts, so retries can never amplify outbound load beyond the
    # ratio plus the fixed reserve.  0 = budget off (unbounded retries,
    # exactly as before).  Exhausted budget degrades to the existing
    # 503/migration paths instead of retrying.
    "VDT_ROUTER_RETRY_BUDGET_RATIO": lambda: float(
        os.environ.get("VDT_ROUTER_RETRY_BUDGET_RATIO", "0")
    ),
    "VDT_ROUTER_RETRY_BUDGET_MIN": lambda: float(
        os.environ.get("VDT_ROUTER_RETRY_BUDGET_MIN", "10")
    ),
    # Adaptive deadlines: per-endpoint EWMA latency quantiles replace
    # the fixed unary ClientTimeout totals (clamped to
    # [floor, ceiling]; ceiling 0 = the router read timeout), so a
    # slow-but-alive replica isn't declared dead and a hung one is cut
    # fast.  Streaming reads keep their fixed sock_read deadline.
    "VDT_ROUTER_ADAPTIVE_DEADLINE": lambda: int(
        os.environ.get("VDT_ROUTER_ADAPTIVE_DEADLINE", "0")
    ),
    "VDT_ROUTER_DEADLINE_FLOOR_SECONDS": lambda: float(
        os.environ.get("VDT_ROUTER_DEADLINE_FLOOR_SECONDS", "1")
    ),
    "VDT_ROUTER_DEADLINE_CEILING_SECONDS": lambda: float(
        os.environ.get("VDT_ROUTER_DEADLINE_CEILING_SECONDS", "0")
    ),
    "VDT_ROUTER_DEADLINE_MULTIPLIER": lambda: float(
        os.environ.get("VDT_ROUTER_DEADLINE_MULTIPLIER", "3")
    ),
    # Hedged requests on idempotent read paths (/health, /metrics,
    # /slo scrapes and /internal/kv/export chunk pulls): after a
    # p95-based delay a duplicate request races the first, first winner
    # cancels the loser, hedges draw from the retry budget.
    "VDT_ROUTER_HEDGE": lambda: int(
        os.environ.get("VDT_ROUTER_HEDGE", "0")
    ),
    # Floor under the p95-based hedge delay (a cold or very fast
    # endpoint must not hedge instantly and double its load).
    "VDT_ROUTER_HEDGE_MIN_DELAY_MS": lambda: float(
        os.environ.get("VDT_ROUTER_HEDGE_MIN_DELAY_MS", "50")
    ),
    # Resumable KV transfer: per-chunk retry cap on the prefill->decode
    # page stream.  A dropped connection re-pulls only the missing
    # checksummed chunks (begin carries resume_from) instead of
    # aborting the hand-off to recompute; retries also draw from the
    # retry budget.  0 = single-attempt transfer, exactly as before.
    "VDT_ROUTER_KV_CHUNK_RETRIES": lambda: int(
        os.environ.get("VDT_ROUTER_KV_CHUNK_RETRIES", "0")
    ),
    # --- elastic fleet (ISSUE 13) ---
    # Command template the router's ReplicaManager launches managed
    # replicas with ({port} and {replica_id} placeholders, e.g.
    # "vdt serve MODEL --host 127.0.0.1 --port {port}").  Empty = fleet
    # mode needs --fleet-cmd.
    "VDT_FLEET_CMD": lambda: os.environ.get("VDT_FLEET_CMD", ""),
    # Health-gated warmup: how long a freshly spawned replica may take
    # to answer /health 200 before the spawn counts as a crash.  A
    # replica is never routable before its first healthy answer.
    "VDT_FLEET_WARMUP_TIMEOUT_SECONDS": lambda: float(
        os.environ.get("VDT_FLEET_WARMUP_TIMEOUT_SECONDS", "120")
    ),
    # Scale-down drain bound: how long the manager waits for a
    # replica's /drain (journal-migration of its in-flight streams)
    # before terminating it anyway.  Also bounds the router's SIGTERM
    # drain of the whole managed fleet.
    "VDT_FLEET_DRAIN_TIMEOUT_SECONDS": lambda: float(
        os.environ.get("VDT_FLEET_DRAIN_TIMEOUT_SECONDS", "30")
    ),
    # Reconcile/crash-poll cadence of the fleet supervisor loop.
    "VDT_FLEET_CHECK_INTERVAL_SECONDS": lambda: float(
        os.environ.get("VDT_FLEET_CHECK_INTERVAL_SECONDS", "0.5")
    ),
    # Crash-loop policy, mirroring the PR 3 engine supervisor: at most
    # this many restarts within the window (0 = never restart a crashed
    # replica), with exponential backoff between attempts.
    "VDT_FLEET_MAX_RESTARTS": lambda: int(
        os.environ.get("VDT_FLEET_MAX_RESTARTS", "3")
    ),
    "VDT_FLEET_RESTART_WINDOW_SECONDS": lambda: float(
        os.environ.get("VDT_FLEET_RESTART_WINDOW_SECONDS", "300")
    ),
    "VDT_FLEET_RESTART_BACKOFF_SECONDS": lambda: float(
        os.environ.get("VDT_FLEET_RESTART_BACKOFF_SECONDS", "1")
    ),
    "VDT_FLEET_RESTART_BACKOFF_CAP_SECONDS": lambda: float(
        os.environ.get("VDT_FLEET_RESTART_BACKOFF_CAP_SECONDS", "30")
    ),
    # --- autoscaler (ISSUE 13; --autoscale arms the loop) ---
    # Control-loop tick interval and replica-count bounds.
    "VDT_AUTOSCALE_INTERVAL_SECONDS": lambda: float(
        os.environ.get("VDT_AUTOSCALE_INTERVAL_SECONDS", "5")
    ),
    "VDT_AUTOSCALE_MIN_REPLICAS": lambda: int(
        os.environ.get("VDT_AUTOSCALE_MIN_REPLICAS", "1")
    ),
    "VDT_AUTOSCALE_MAX_REPLICAS": lambda: int(
        os.environ.get("VDT_AUTOSCALE_MAX_REPLICAS", "4")
    ),
    # Hysteresis watermarks on the primary signal (mean waiting-queue
    # depth per routable replica, the PR 7 admission gauge the pool
    # already scrapes): scale up above the high mark, down below the
    # low mark, hold in between.
    "VDT_AUTOSCALE_UP_WAITING": lambda: float(
        os.environ.get("VDT_AUTOSCALE_UP_WAITING", "4")
    ),
    "VDT_AUTOSCALE_DOWN_WAITING": lambda: float(
        os.environ.get("VDT_AUTOSCALE_DOWN_WAITING", "1")
    ),
    # Per-direction cooldowns: no two scale-ups (downs) closer than
    # this, so one burst can't slam the fleet to max and back.
    "VDT_AUTOSCALE_UP_COOLDOWN_SECONDS": lambda: float(
        os.environ.get("VDT_AUTOSCALE_UP_COOLDOWN_SECONDS", "15")
    ),
    "VDT_AUTOSCALE_DOWN_COOLDOWN_SECONDS": lambda: float(
        os.environ.get("VDT_AUTOSCALE_DOWN_COOLDOWN_SECONDS", "60")
    ),
    # Secondary scale-up triggers (0 = off): fleet 429 rate (rejections
    # per second over the tick window) and fleet ITL p99 (ms, from the
    # ISSUE 12 /router/slo merge) above which the fleet grows even if
    # queues look shallow.
    "VDT_AUTOSCALE_MAX_REJECT_RATE": lambda: float(
        os.environ.get("VDT_AUTOSCALE_MAX_REJECT_RATE", "0")
    ),
    "VDT_AUTOSCALE_ITL_P99_MS": lambda: float(
        os.environ.get("VDT_AUTOSCALE_ITL_P99_MS", "0")
    ),
    # --- QoS control plane (ISSUE 16) ---
    # SLO class registry: one entry per class,
    # "name:priority[:share[:weight]]", comma-separated (e.g.
    # "interactive:10:0.5,default:0:0.3,batch:-10:0:2.0").  Priority
    # orders admission and preemption (higher admits first, preempts
    # last); share is the class's guaranteed-minimum fraction of the
    # bounded-admission caps (work-conserving: spare capacity is
    # borrowable by any class); weight scales the preempt-to-shed
    # budget.  Empty (the default) disables the QoS control plane
    # entirely — seed scheduling is bit-identical.
    "VDT_QOS_CLASSES": lambda: os.environ.get("VDT_QOS_CLASSES", ""),
    # Chunked-prefill fairness budget: while any decode-bound request
    # of higher-or-equal class is running, prefill chunks may take at
    # most this fraction of the per-step token budget, bounding decode
    # ITL under a long concurrent prefill.  Work-conserving: with no
    # qualifying decode running, prefill uses the full budget.
    # 0 = off (the seed policy: prefill fills whatever budget is left).
    "VDT_QOS_PREFILL_SHARE": lambda: float(
        os.environ.get("VDT_QOS_PREFILL_SHARE", "0")
    ),
    # Router per-class placement: "shared" (seed behaviour — every
    # class places on every replica), "segregate" (disjoint replica
    # partition per class, proportional to admission shares), or
    # "reserve" (co-locate, but lower classes avoid the top class's
    # headroom replicas while alternatives exist).
    "VDT_QOS_PLACEMENT": lambda: os.environ.get(
        "VDT_QOS_PLACEMENT", "shared"
    ),
    # Per-class SLO-aware scale-up: grow the fleet when any class's
    # windowed goodput ratio (from the /router/slo merge) sags below
    # this floor (0 = trigger off), ignoring windows with fewer than
    # VDT_AUTOSCALE_GOODPUT_MIN_REQUESTS finished requests.
    "VDT_AUTOSCALE_GOODPUT_FLOOR": lambda: float(
        os.environ.get("VDT_AUTOSCALE_GOODPUT_FLOOR", "0")
    ),
    "VDT_AUTOSCALE_GOODPUT_MIN_REQUESTS": lambda: int(
        os.environ.get("VDT_AUTOSCALE_GOODPUT_MIN_REQUESTS", "20")
    ),
    # Per-role autoscaling of the disagg prefill pool (ISSUE 15): the
    # prefill-pool target tracks an EWMA of the long-prompt arrival
    # rate (prompts at/above VDT_DISAGG_MIN_PROMPT_TOKENS) divided by
    # the per-replica absorbable rate benched at the crossover.
    # 0 = off (the pool stays at --fleet-prefill).
    "VDT_AUTOSCALE_PREFILL_RPS": lambda: float(
        os.environ.get("VDT_AUTOSCALE_PREFILL_RPS", "0")
    ),
    "VDT_AUTOSCALE_PREFILL_EWMA_SECONDS": lambda: float(
        os.environ.get("VDT_AUTOSCALE_PREFILL_EWMA_SECONDS", "30")
    ),
    "VDT_AUTOSCALE_PREFILL_MIN": lambda: int(
        os.environ.get("VDT_AUTOSCALE_PREFILL_MIN", "0")
    ),
    "VDT_AUTOSCALE_PREFILL_MAX": lambda: int(
        os.environ.get("VDT_AUTOSCALE_PREFILL_MAX", "4")
    ),
    # --- observability ---
    # SLO targets for goodput accounting (engine/slo.py, ISSUE 12), in
    # milliseconds.  A bare number sets the "default" class; per-class:
    # "default:500,interactive:200,batch:5000".  Empty = no targets
    # (every class attains trivially; goodput == completed requests).
    "VDT_SLO_TTFT_MS": lambda: os.environ.get("VDT_SLO_TTFT_MS", ""),
    "VDT_SLO_ITL_MS": lambda: os.environ.get("VDT_SLO_ITL_MS", ""),
    # Flight recorder (engine/flight_recorder.py): per-step records
    # kept in the always-on ring (0 disables), and where the JSON
    # artifacts land on HostFailure/recovery/drain (per-host; empty =
    # <tmpdir>/vdt-flightrecorder).
    "VDT_FLIGHT_RECORDER_SIZE": lambda: int(
        os.environ.get("VDT_FLIGHT_RECORDER_SIZE", "512")
    ),
    "VDT_FLIGHT_RECORDER_DIR": lambda: os.environ.get(
        "VDT_FLIGHT_RECORDER_DIR", ""
    ),
    # Server-side jax.profiler captures (POST /debug/profile): artifact
    # directory; empty disables the endpoint (404).  --profile-dir
    # wins.  Per-host: a profile is local state like a drain journal.
    "VDT_PROFILE_DIR": lambda: os.environ.get("VDT_PROFILE_DIR", ""),
    # Per-request tracing (tracing.py): default off; the engine step
    # loop runs the no-op tracer path and /debug/traces answers 404.
    # Replicated to agents so worker-side RPC spans land in the same
    # trace as the driver's.
    "VDT_TRACING": lambda: os.environ.get("VDT_TRACING", "0").lower()
    not in ("", "0", "false", "off"),
    # Completed traces kept in memory (bounded ring; oldest evicted).
    "VDT_TRACE_RING_SIZE": lambda: int(
        os.environ.get("VDT_TRACE_RING_SIZE", "256")
    ),
    # OTLP export of completed traces (tracing.py): on by default when
    # the opentelemetry SDK is importable; "0"/"false" disables even
    # with the SDK present.
    "VDT_TRACE_OTLP": lambda: os.environ.get("VDT_TRACE_OTLP", "1")
    not in ("0", "false"),
    # --- fleet sentinel (ISSUE 20) ---
    # Unified event timeline (engine/sentinel.py): events kept per
    # component log (engine ring served at /debug/events, router ring
    # merged into /router/timeline).  0 disables event collection.
    "VDT_SENTINEL_EVENTS_SIZE": lambda: int(
        os.environ.get("VDT_SENTINEL_EVENTS_SIZE", "512")
    ),
    # SLO objective for burn-rate math: the target attainment ratio.
    # burn = error_rate / (1 - objective); at 0.99 a burn of 1.0 means
    # exactly 1% of requests are missing their targets.
    "VDT_SLO_OBJECTIVE": lambda: float(
        os.environ.get("VDT_SLO_OBJECTIVE", "0.99")
    ),
    # Multi-window burn-rate alert threshold: an alert fires when the
    # burn exceeds this on EVERY window (5m and 1h) simultaneously.
    "VDT_SENTINEL_BURN_THRESHOLD": lambda: float(
        os.environ.get("VDT_SENTINEL_BURN_THRESHOLD", "10")
    ),
    # Robust-z (median/MAD, sigma units) past which a replica's signal
    # marks it degraded (router/sentinel.py anomaly scoring).
    "VDT_SENTINEL_ANOMALY_THRESHOLD": lambda: float(
        os.environ.get("VDT_SENTINEL_ANOMALY_THRESHOLD", "4")
    ),
    # Let anomaly scores influence placement: outlier replicas are
    # DEPRIORITIZED (chosen only when no in-band replica can take the
    # request), never ejected.  Default off: scoring is observe-only.
    "VDT_SENTINEL_PLACEMENT": lambda: os.environ.get(
        "VDT_SENTINEL_PLACEMENT", "0"
    ).lower()
    not in ("", "0", "false", "off"),
    # --- per-host test/operator hooks (never replicated) ---
    # Install the deterministic FaultInjector on this process's RPC
    # transports (tests/test_fault_injection.py arms it over RPC).
    "VDT_FAULT_INJECTION": lambda: os.environ.get(
        "VDT_FAULT_INJECTION", ""
    )
    == "1",
    # Deterministic pre-dial delay in the agent (fault harness only).
    "VDT_FAULT_CONNECT_DELAY_SECONDS": lambda: float(
        os.environ.get("VDT_FAULT_CONNECT_DELAY_SECONDS", "0")
    ),
    # Pin this host's chip advertisement instead of probing jax in a
    # subprocess (operators/tests; both must be set to take effect).
    "VDT_ADVERTISE_NUM_CHIPS": lambda: os.environ.get(
        "VDT_ADVERTISE_NUM_CHIPS"
    ),
    "VDT_ADVERTISE_PLATFORM": lambda: os.environ.get(
        "VDT_ADVERTISE_PLATFORM"
    ),
    # --- engine ---
    "VDT_LOG_LEVEL": lambda: os.environ.get("VDT_LOG_LEVEL", "INFO"),
    "VDT_COMPILE_CACHE_DIR": lambda: os.environ.get(
        "VDT_COMPILE_CACHE_DIR", os.path.expanduser("~/.cache/vdt/jax_cache")
    ),
    # Persistent jax.export artifact cache for warm restarts (skips
    # trace+lower, not just XLA compile): "auto" = on for TPU,
    # "1" = always, "0" = off.  Artifacts live under
    # $VDT_COMPILE_CACHE_DIR/aot.
    "VDT_AOT_CACHE": lambda: os.environ.get("VDT_AOT_CACHE", "auto"),
    "VDT_HBM_UTILIZATION": lambda: float(
        os.environ.get("VDT_HBM_UTILIZATION", "0.9")
    ),
    "VDT_HTTP_TIMEOUT_KEEP_ALIVE": lambda: int(
        os.environ.get("VDT_HTTP_TIMEOUT_KEEP_ALIVE", "5")
    ),
    # force the jax platform (cpu for tests, tpu in prod)
    "VDT_PLATFORM": lambda: os.environ.get("VDT_PLATFORM", ""),
    "VDT_USE_PALLAS": lambda: os.environ.get("VDT_USE_PALLAS", "auto"),
    # MoE expert dispatch: "auto" picks per call site — dense-fused for
    # bandwidth-bound shapes (decode, or quantized experts whose
    # dequant fuses into the dense dot but not into ragged_dot),
    # ragged (sorted jax.lax.ragged_dot, ~k/E of the dense FLOPs) for
    # compute-bound prefill rows.  "ragged"/"dense" force one path.
    "VDT_MOE_IMPL": lambda: os.environ.get("VDT_MOE_IMPL", "auto"),
    # --- external, replicated for weight download ---
    "HF_TOKEN": lambda: os.environ.get("HF_TOKEN", ""),
    "HUGGING_FACE_HUB_TOKEN": lambda: os.environ.get("HUGGING_FACE_HUB_TOKEN", ""),
    "HF_HOME": lambda: os.environ.get("HF_HOME", ""),
}

# Per-host variables that must NOT be replicated to remote workers, the
# analog of the exclusion set at launch.py:62-69 ({VLLM_HOST_IP,
# VLLM_HOST_PORT, LOCAL_RANK, CUDA_VISIBLE_DEVICES}).
NON_REPLICATED_ENV_VARS = {
    "VDT_HOST_IP",
    "VDT_SERVER_PORT",
    "TPU_VISIBLE_DEVICES",
    "JAX_PLATFORMS",
    "LOCAL_RANK",
    "RANK",
    # Per-host test/operator hooks: the driver's values must never leak
    # onto remote hosts (arming faults fleet-wide, or pinning every
    # host's chip advertisement to the driver's, would be wrong).
    "VDT_FAULT_INJECTION",
    "VDT_FAULT_CONNECT_DELAY_SECONDS",
    "VDT_ADVERTISE_NUM_CHIPS",
    "VDT_ADVERTISE_PLATFORM",
    # A replica's drain journal is local state: replicating the path
    # onto remote workers would have every host writing (and on boot,
    # consuming) the same file.
    "VDT_DRAIN_JOURNAL_PATH",
    # Flight-recorder artifacts and profiler captures are local state
    # for the same reason (and workers run no engine loop to record).
    "VDT_FLIGHT_RECORDER_DIR",
    "VDT_PROFILE_DIR",
    # Replica identity and router knobs are per-process: replicating a
    # replica's id onto its workers (or a router's backend set onto
    # anything) would be meaningless at best and confusing in logs.
    "VDT_REPLICA_ID",
    "VDT_ROUTER_REPLICAS",
    "VDT_ROUTER_POLICY",
    "VDT_ROUTER_HEALTH_INTERVAL_SECONDS",
    "VDT_ROUTER_AFFINITY_BLOCK_TOKENS",
    "VDT_ROUTER_AFFINITY_CAPACITY",
    "VDT_ROUTER_AFFINITY_MIN_TOKENS",
    "VDT_ROUTER_MAX_MIGRATIONS",
    "VDT_ROUTER_CONNECT_TIMEOUT_SECONDS",
    "VDT_ROUTER_READ_TIMEOUT_SECONDS",
    # Crash-safe router state (ISSUE 17): the WAL is the ROUTER
    # process's local durable state — replicating the dir onto workers
    # or replicas would have every process writing (and on boot,
    # recovering) the same fleet.
    "VDT_ROUTER_STATE_DIR",
    "VDT_ROUTER_STATE_SEGMENT_BYTES",
    "VDT_ROUTER_STATE_FSYNC_INTERVAL_SECONDS",
    "VDT_ROUTER_STATE_CKPT_INTERVAL_SECONDS",
    "VDT_ROUTER_STATE_VERIFY_WINDOW_SECONDS",
    "VDT_ROUTER_STATE_RECOVERY_TTL_SECONDS",
    # Sentinel placement (ISSUE 20) is a router-process decision: the
    # anomaly scores live in the router; replicas have no pool to
    # deprioritize against.  (The other sentinel knobs DO replicate —
    # objective/threshold/log size are fleet-wide policy.)
    "VDT_SENTINEL_PLACEMENT",
    # Disaggregation (ISSUE 15): the role is per-replica identity like
    # VDT_REPLICA_ID; the crossover/chunking knobs configure the ROUTER
    # process's hand-off orchestration; export holds are driver-engine
    # state (workers hold no pages of their own to expire).
    "VDT_ROUTER_ROLE",
    "VDT_DISAGG_MIN_PROMPT_TOKENS",
    "VDT_DISAGG_CHUNK_LAYERS",
    "VDT_DISAGG_EXPORT_TTL_SECONDS",
    # Resilient data plane (ISSUE 19): breakers, retry budgets,
    # adaptive deadlines, hedging, and chunk-resume all configure the
    # ROUTER process's outbound HTTP behavior — replicating them onto
    # engine workers would be meaningless.  (VDT_KV_MAX_FRAME_BYTES is
    # replica-side server config and DOES replicate.)
    "VDT_ROUTER_BREAKER_FAILURES",
    "VDT_ROUTER_BREAKER_COOLDOWN_SECONDS",
    "VDT_ROUTER_BREAKER_TIMEOUT_RATE",
    "VDT_ROUTER_BREAKER_WINDOW_SECONDS",
    "VDT_ROUTER_RETRY_BUDGET_RATIO",
    "VDT_ROUTER_RETRY_BUDGET_MIN",
    "VDT_ROUTER_ADAPTIVE_DEADLINE",
    "VDT_ROUTER_DEADLINE_FLOOR_SECONDS",
    "VDT_ROUTER_DEADLINE_CEILING_SECONDS",
    "VDT_ROUTER_DEADLINE_MULTIPLIER",
    "VDT_ROUTER_HEDGE",
    "VDT_ROUTER_HEDGE_MIN_DELAY_MS",
    "VDT_ROUTER_KV_CHUNK_RETRIES",
    # Fleet lifecycle + autoscaler knobs configure the ROUTER process's
    # control loops; replicating them to engine workers (or to the
    # managed replicas themselves) would be meaningless.
    "VDT_FLEET_CMD",
    "VDT_FLEET_WARMUP_TIMEOUT_SECONDS",
    "VDT_FLEET_DRAIN_TIMEOUT_SECONDS",
    "VDT_FLEET_CHECK_INTERVAL_SECONDS",
    "VDT_FLEET_MAX_RESTARTS",
    "VDT_FLEET_RESTART_WINDOW_SECONDS",
    "VDT_FLEET_RESTART_BACKOFF_SECONDS",
    "VDT_FLEET_RESTART_BACKOFF_CAP_SECONDS",
    "VDT_AUTOSCALE_INTERVAL_SECONDS",
    "VDT_AUTOSCALE_MIN_REPLICAS",
    "VDT_AUTOSCALE_MAX_REPLICAS",
    "VDT_AUTOSCALE_UP_WAITING",
    "VDT_AUTOSCALE_DOWN_WAITING",
    "VDT_AUTOSCALE_UP_COOLDOWN_SECONDS",
    "VDT_AUTOSCALE_DOWN_COOLDOWN_SECONDS",
    "VDT_AUTOSCALE_MAX_REJECT_RATE",
    "VDT_AUTOSCALE_ITL_P99_MS",
    # QoS (ISSUE 16): placement and the goodput/per-role autoscale
    # knobs configure the ROUTER's control loops (the class registry
    # itself, VDT_QOS_CLASSES, and the engine-side fairness budget DO
    # replicate — every replica must agree on the class table).
    "VDT_QOS_PLACEMENT",
    "VDT_AUTOSCALE_GOODPUT_FLOOR",
    "VDT_AUTOSCALE_GOODPUT_MIN_REQUESTS",
    "VDT_AUTOSCALE_PREFILL_RPS",
    "VDT_AUTOSCALE_PREFILL_EWMA_SECONDS",
    "VDT_AUTOSCALE_PREFILL_MIN",
    "VDT_AUTOSCALE_PREFILL_MAX",
}

# Extra vars replicated even though they are not VDT_* (launch.py:70-72).
ADDITIONAL_REPLICATED_ENV_VARS = {
    "HF_TOKEN",
    "HUGGING_FACE_HUB_TOKEN",
    "HF_HOME",
}


def replication_env(environ: dict[str, str] | None = None) -> dict[str, str]:
    """Env vars to copy from the driver to a remote worker.

    Mirrors launch.py:198-208: everything in the registry that is actually
    set in the driver's environment, minus per-host vars, plus the HF vars.
    """
    environ = os.environ if environ is None else environ
    out: dict[str, str] = {}
    for name in environment_variables:
        if name in NON_REPLICATED_ENV_VARS:
            continue
        if name in environ:
            out[name] = environ[name]
    for name in ADDITIONAL_REPLICATED_ENV_VARS:
        if name in environ:
            out[name] = environ[name]
    return out


def __getattr__(name: str) -> Any:
    if name in environment_variables:
        return environment_variables[name]()
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
