"""Mixtral / Qwen3-MoE sparse-MoE decoder (milestone config 5; the
reference's flagship deployment is a Qwen3-Coder MoE,
/root/reference/.env.server:11).

The attention block, forward loop, and weight plumbing are inherited from
the Llama decoder (models/llama.py) — only the MLP is swapped for a
top-k routed mixture of experts:

    router: logits = x @ Wg            [T, E]
    probs  = softmax(logits)           (float32, HF semantics)
    topw, topi = top_k(probs, k)       renormalized when norm_topk_prob

The default expert computation is a *sorted ragged dispatch*
(VDT_MOE_IMPL=ragged): the T×k token→expert assignments are flattened,
sorted by expert, and each projection is ONE grouped matmul
(jax.lax.ragged_dot) over the sorted rows — ~k/E of the dense FLOPs,
which is what makes a 160-expert/8-active flagship
(Qwen3-Coder-480B-A35B, the reference's deployment) servable.  The TPU
lowering of ragged_dot is verified truly grouped (cost-analysis flops ==
2·M·H·I, checked on-chip by bench._check_kernels); token drop/capacity
factors are never used — inference must match the reference exactly.

VDT_MOE_IMPL=dense keeps the GShard-style dense dispatch (every expert
on every token, a [T, E] combine matrix) as the correctness oracle:

    h1 = einsum('th,ehi->tei', x, W1); h3 = likewise W3
    y  = einsum('tei,eih,te->th', silu(h1)*h3, W2, combine)

Sharding: under EP the expert axis E is sharded over "tp" — each device
holds E/tp whole experts and, since activations are replicated over the
tp group, runs the grouped matmul over the full sorted row range with
rows outside its experts' contiguous slice folded into the edge groups
(keeping row offsets aligned with no weight copies) and masked from the
psum combine — an all-to-all-free EP layout.  Without EP each expert
splits over its intermediate dim exactly like the dense MLP.

Sliding-window attention (some Mixtral checkpoints set sliding_window) is
not applied; contexts are served full via the paged KV cache, matching
vLLM's default for Mixtral-8x7B (config ships null).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from vllm_distributed_tpu.models.llama import LlamaForCausalLM


class MixtralForCausalLM(LlamaForCausalLM):
    architectures = (
        "MixtralForCausalLM",
        "Qwen3MoeForCausalLM",
    )
    # Router stays unquantized (tiny and routing-decision-sensitive).
    QUANT_PARAMS = (
        LlamaForCausalLM.QUANT_PARAMS - {"gate", "up", "down"}
    ) | {"w1", "w2", "w3"}

    def __init__(self, model_config: Any) -> None:
        super().__init__(model_config)
        hf = model_config.hf_config
        self.qk_norm = self.model_type == "qwen3_moe"
        self.attn_bias = False
        # MoE shape: Mixtral uses num_local_experts/intermediate_size,
        # Qwen3-MoE num_experts/moe_intermediate_size.
        self.num_experts = int(
            getattr(hf, "num_local_experts", 0)
            or getattr(hf, "num_experts", 0)
        )
        if self.num_experts <= 0:
            raise ValueError(
                f"{self.architectures[0]} requires an expert count "
                "(num_local_experts/num_experts) in the HF config"
            )
        self.top_k = int(getattr(hf, "num_experts_per_tok", 2))
        self.moe_intermediate = int(
            getattr(hf, "moe_intermediate_size", 0)
            or getattr(hf, "intermediate_size", 0)
        )
        # Mixtral always renormalizes the top-k probs; Qwen3-MoE gates it.
        self.norm_topk = bool(getattr(hf, "norm_topk_prob", True))
        self.expert_parallel = bool(
            getattr(model_config, "enable_expert_parallel", False)
        )
        if getattr(hf, "mlp_only_layers", None):
            raise NotImplementedError(
                "mlp_only_layers (dense layers mixed into an MoE stack) "
                "is not supported yet"
            )
        if int(getattr(hf, "decoder_sparse_step", 1) or 1) != 1:
            raise NotImplementedError("decoder_sparse_step > 1 not supported")

    def validate_mesh(self, mesh) -> None:
        """Pre-placement check (called by the loader before any
        device_put): EP shards whole experts over the tp axis."""
        self._mesh = mesh  # the ragged dispatch shard_maps over it
        tp = mesh.shape.get("tp", 1)
        if self.expert_parallel and self.num_experts % tp:
            raise ValueError(
                f"expert parallelism needs num_experts "
                f"({self.num_experts}) divisible by tp ({tp})"
            )

    # ---- params ----
    def init_params(self, rng: jax.Array) -> dict:
        """Random init: Llama tree with the dense MLP swapped for
        router + stacked expert weights."""
        params = super().init_params(rng)
        e, h, im = self.num_experts, self.hidden_size, self.moe_intermediate

        def nrm(key, shape):
            return (jax.random.normal(key, shape, jnp.float32) * 0.02).astype(
                self.dtype
            )

        keys = iter(
            jax.random.split(jax.random.fold_in(rng, 1), 4 * self.num_layers)
        )
        for layer in params["layers"]:
            for dense in ("gate", "up", "down"):
                del layer[dense]
            layer["router"] = nrm(next(keys), (h, e))
            layer["w1"] = nrm(next(keys), (e, h, im))
            layer["w3"] = nrm(next(keys), (e, h, im))
            layer["w2"] = nrm(next(keys), (e, im, h))
        return params

    def map_hf_name(self, name: str):
        """MoE names (router + per-expert tensors) resolved here; the
        attention/embedding names fall through to the Llama table.

        Mixtral: model.layers.{i}.block_sparse_moe.gate.weight and
        .experts.{e}.w{1,2,3}.weight; Qwen3-MoE: mlp.gate.weight and
        mlp.experts.{e}.{gate,down,up}_proj.weight.  Per-expert tensors
        land at ("layers", i, wN, e) and are stacked to [E, ...] by
        finalize_params.
        """
        if name.startswith("model.layers."):
            parts = name.split(".")
            i = int(parts[2])
            rest = ".".join(parts[3:])
            if rest in ("block_sparse_moe.gate.weight", "mlp.gate.weight"):
                return ("layers", i, "router"), "T"
            for prefix in ("block_sparse_moe.experts.", "mlp.experts."):
                if rest.startswith(prefix):
                    eparts = rest[len(prefix) :].split(".")
                    e = int(eparts[0])
                    which = {
                        "w1.weight": "w1",
                        "w2.weight": "w2",
                        "w3.weight": "w3",
                        "gate_proj.weight": "w1",
                        "down_proj.weight": "w2",
                        "up_proj.weight": "w3",
                    }.get(".".join(eparts[1:]))
                    if which is None:
                        return None
                    return ("layers", i, which, e), "T"
        return super().map_hf_name(name)

    def _expert_specs(self) -> dict:
        """Final (stacked [E, ...]) specs for the expert tensors."""
        if self.expert_parallel:
            # Whole experts sharded over the tp axis: E % tp must hold.
            return {
                "w1": P("tp", None, None),
                "w3": P("tp", None, None),
                "w2": P("tp", None, None),
            }
        # Dense-MLP-style: split every expert over its intermediate dim.
        return {
            "w1": P(None, None, "tp"),
            "w3": P(None, None, "tp"),
            "w2": P(None, "tp", None),
        }

    def partition_specs(self) -> dict:
        specs = super().partition_specs()
        expert = self._expert_specs()
        for layer in specs["layers"]:
            for dense in ("gate", "up", "down"):
                del layer[dense]
            layer["router"] = P()
            layer.update(expert)
        return specs

    def load_specs(self) -> dict:
        """Per-tensor specs used DURING HF load, where expert tensors are
        still unstacked ({e: [h, im]} dicts).  Under EP an unstacked
        expert's final home is one device group, which NamedSharding
        cannot express for a single tensor — so in-flight experts shard
        over their input dim (bounded memory: tensor/tp per device) and
        finalize_params reshards the per-layer stack to the expert
        layout."""
        specs = self.partition_specs()
        if self.expert_parallel:
            per_expert = {
                "w1": P("tp", None),
                "w3": P("tp", None),
                "w2": P("tp", None),
            }
        else:
            per_expert = {
                "w1": P(None, "tp"),
                "w3": P(None, "tp"),
                "w2": P("tp", None),
            }
        for layer in specs["layers"]:
            for name, spec in per_expert.items():
                layer[name] = {e: spec for e in range(self.num_experts)}
        return specs

    def finalize_params(self, params: dict, mesh) -> dict:
        """Stack per-expert weight dicts into [E, ...] arrays with the
        final sharding (called by the loader after all tensors land).
        Quantized experts stack their q/scale parts.  Stacking runs
        under jit with explicit out_shardings so XLA reshards in-flight
        (input-dim shards -> expert shards) without a replicated
        transient — the per-layer peak stays O(layer/tp) per device."""
        from jax.sharding import NamedSharding

        from vllm_distributed_tpu.ops.quant import (
            QuantizedTensor,
            aligned_spec,
            quant_spec,
        )

        def stack_to(parts, spec):
            if mesh is None:
                return jnp.stack(parts)
            out = NamedSharding(
                mesh,
                aligned_spec(
                    spec, (len(parts), *parts[0].shape), mesh
                ),
            )
            return jax.jit(
                lambda *xs: jnp.stack(xs), out_shardings=out
            )(*parts)

        final = self._expert_specs()
        for layer in params["layers"]:
            for name in ("w1", "w2", "w3"):
                entry = layer.get(name)
                if not isinstance(entry, dict):
                    continue
                if sorted(entry) != list(range(self.num_experts)):
                    raise ValueError(
                        f"checkpoint is missing experts for {name}: "
                        f"have {sorted(entry)}, want 0..{self.num_experts - 1}"
                    )
                parts = [entry[e] for e in range(self.num_experts)]
                if isinstance(parts[0], QuantizedTensor):
                    qs = quant_spec(final[name], parts[0].bits)
                    layer[name] = QuantizedTensor(
                        q=stack_to([p.q for p in parts], qs.q),
                        scale=stack_to([p.scale for p in parts], qs.scale),
                        bits=parts[0].bits,
                        group=parts[0].group,
                        shape=(self.num_experts, *parts[0].shape),
                        dtype=parts[0].dtype,
                        matmul=parts[0].matmul,
                    )
                    continue
                layer[name] = stack_to(parts, final[name])
        return params

    # ---- forward (attention loop inherited; MLP is the routed MoE) ----
    def _route(self, h: jax.Array, layer: dict):
        """Router: top-k expert ids + (renormalized) weights per token."""
        logits = h @ layer["router"].astype(h.dtype)  # [T, E]
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        topw, topi = jax.lax.top_k(probs, self.top_k)  # [T, k]
        if self.norm_topk:
            topw = topw / topw.sum(axis=-1, keepdims=True)
        return topw, topi

    def _moe_impl(self) -> str:
        from vllm_distributed_tpu import envs

        return envs.VDT_MOE_IMPL

    def _mlp(self, h: jax.Array, layer: dict) -> jax.Array:
        impl = self._moe_impl()
        if impl == "auto":
            # Measured on v5e (BENCH_r05 moe config + PERF.md): decode
            # is weight-BANDWIDTH-bound, where the dense einsum wins —
            # XLA fuses the int8 dequant into the dot so the compressed
            # bytes stream once, while ragged_dot cannot fuse a
            # producer and materializes the bf16 expert stack per call
            # (4.4x slower end-to-end at batch 32).  The ragged path's
            # k/E FLOP saving only pays on big COMPUTE-bound row
            # counts with unquantized experts.
            from vllm_distributed_tpu.ops.quant import QuantizedTensor

            quantized = isinstance(layer["w1"], QuantizedTensor)
            rows = h.shape[0] * self.top_k
            impl = "dense" if (quantized or rows <= 256) else "ragged"
        if impl == "dense":
            return self._mlp_dense(h, layer)
        return self._mlp_ragged(h, layer)

    def _mlp_dense(self, h: jax.Array, layer: dict) -> jax.Array:
        """GShard-style dense dispatch — every expert runs on every
        token, a [T, E] combine matrix (zeros outside the top-k) weights
        the results.  Exact and GSPMD-friendly; the correctness oracle
        for the ragged path and the fallback for shapes it rejects."""
        from vllm_distributed_tpu.ops.quant import maybe_dequantize

        t = h.shape[0]
        topw, topi = self._route(h, layer)
        combine = (
            jnp.zeros((t, self.num_experts), jnp.float32)
            .at[jnp.arange(t)[:, None], topi]
            .set(topw)
            .astype(h.dtype)
        )
        w1 = maybe_dequantize(layer["w1"], h.dtype)
        w3 = maybe_dequantize(layer["w3"], h.dtype)
        w2 = maybe_dequantize(layer["w2"], h.dtype)
        h1 = jnp.einsum("th,ehi->tei", h, w1)
        h3 = jnp.einsum("th,ehi->tei", h, w3)
        inner = jax.nn.silu(h1) * h3
        return jnp.einsum("tei,eih,te->th", inner, w2, combine)

    def _mlp_ragged(self, h: jax.Array, layer: dict) -> jax.Array:
        """Sorted ragged dispatch (SURVEY §2.5's TPU plan; VERDICT r3
        #4): flatten the T×k assignments, sort rows by expert, run ONE
        grouped matmul per projection (jax.lax.ragged_dot), and
        scatter-add the weighted results back — ~k/E of the dense
        path's expert FLOPs, which is what makes a 160-expert/8-active
        flagship servable.

        Sharding: under EP each device holds E/tp whole experts; the
        activations are replicated over "tp", so instead of an
        all-to-all each shard runs the grouped matmul over the FULL
        sorted row range with its local expert stack — rows outside its
        experts' range fold into the edge groups (so group offsets stay
        aligned without padding/copying weights) and are masked from
        the psum-combined output.  Without EP, experts split over their
        intermediate dim like the dense MLP (partial products psum)."""
        from vllm_distributed_tpu.ops.quant import maybe_dequantize

        t = h.shape[0]
        e, k = self.num_experts, self.top_k
        topw, topi = self._route(h, layer)
        flat_e = topi.reshape(-1).astype(jnp.int32)  # [T*k]
        order = jnp.argsort(flat_e)
        tok = order // k
        xs = h[tok]  # [T*k, H] sorted by expert
        gs = jnp.bincount(flat_e, length=e).astype(jnp.int32)
        row_w = topw.reshape(-1)[order].astype(h.dtype)  # [T*k]

        w1 = maybe_dequantize(layer["w1"], h.dtype)
        w3 = maybe_dequantize(layer["w3"], h.dtype)
        w2 = maybe_dequantize(layer["w2"], h.dtype)

        mesh = getattr(self, "_mesh", None)
        tp = mesh.shape.get("tp", 1) if mesh is not None else 1
        if tp > 1 and self.expert_parallel:
            orows = self._ragged_ep(xs, gs, w1, w3, w2, mesh, tp)
        elif tp > 1:
            orows = self._ragged_tp(xs, gs, w1, w3, w2, mesh)
        else:
            h1 = jax.lax.ragged_dot(xs, w1, gs)
            h3 = jax.lax.ragged_dot(xs, w3, gs)
            inner = jax.nn.silu(h1) * h3
            orows = jax.lax.ragged_dot(inner, w2, gs)

        # f32 accumulation for the k-way combine: the dense oracle's
        # einsum promotes the combine matrix to f32, so a bf16
        # scatter-add here would drift from it (ADVICE r4 #1).
        y = jnp.zeros((t, h.shape[1]), jnp.float32)
        contrib = orows.astype(jnp.float32) * row_w.astype(jnp.float32)[
            :, None
        ]
        return y.at[tok].add(contrib).astype(h.dtype)

    def _ragged_ep(self, xs, gs, w1, w3, w2, mesh, tp):
        """EP shard_map: each device's local experts own a contiguous
        range of the sorted rows; out-of-range rows are folded into the
        first/last local group (keeping offsets aligned without weight
        copies), computed as garbage, masked, and psum-combined."""
        from jax.sharding import PartitionSpec as P

        e = self.num_experts
        e_local = e // tp
        m = xs.shape[0]

        def body(xs_, gs_, w1_, w3_, w2_):
            idx = jax.lax.axis_index("tp")
            cum = jnp.cumsum(gs_)
            lo_e = idx * e_local
            start = jnp.where(lo_e > 0, cum[jnp.maximum(lo_e - 1, 0)], 0)
            end = cum[lo_e + e_local - 1]
            gs_local = jax.lax.dynamic_slice(gs_, (lo_e,), (e_local,))
            # Fold the out-of-range rows into the edge groups.
            gs_fold = gs_local.at[0].add(start)
            gs_fold = gs_fold.at[e_local - 1].add(m - end)
            h1 = jax.lax.ragged_dot(xs_, w1_, gs_fold)
            h3 = jax.lax.ragged_dot(xs_, w3_, gs_fold)
            inner = jax.nn.silu(h1) * h3
            orows = jax.lax.ragged_dot(inner, w2_, gs_fold)
            rows = jnp.arange(m, dtype=jnp.int32)
            in_range = (rows >= start) & (rows < end)
            orows = jnp.where(in_range[:, None], orows, 0)
            return jax.lax.psum(orows, "tp")

        f = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(
                P(), P(),
                P("tp", None, None), P("tp", None, None),
                P("tp", None, None),
            ),
            out_specs=P(),
            check_vma=False,
        )
        return f(xs, gs, w1, w3, w2)

    def _ragged_tp(self, xs, gs, w1, w3, w2, mesh):
        """Non-EP tp: experts split over the intermediate dim (like the
        dense MLP); w2's partial products psum inside the region."""
        from jax.sharding import PartitionSpec as P

        def body(xs_, gs_, w1_, w3_, w2_):
            h1 = jax.lax.ragged_dot(xs_, w1_, gs_)
            h3 = jax.lax.ragged_dot(xs_, w3_, gs_)
            inner = jax.nn.silu(h1) * h3
            part = jax.lax.ragged_dot(inner, w2_, gs_)
            return jax.lax.psum(part, "tp")

        f = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(
                P(), P(),
                P(None, None, "tp"), P(None, None, "tp"),
                P(None, "tp", None),
            ),
            out_specs=P(),
            check_vma=False,
        )
        return f(xs, gs, w1, w3, w2)
