"""Architecture-name → model-class registry (the model-zoo dispatch the
reference delegates to the vllm package's registry, SURVEY.md §2.3)."""

from __future__ import annotations

from typing import Any

_REGISTRY: dict[str, Any] = {}


def register_model(cls) -> Any:
    for arch in cls.architectures:
        _REGISTRY[arch] = cls
    return cls


def _populate() -> None:
    if _REGISTRY:
        return
    from vllm_distributed_tpu.models.llama import LlamaForCausalLM
    from vllm_distributed_tpu.models.opt import OPTForCausalLM

    register_model(LlamaForCausalLM)
    register_model(OPTForCausalLM)
    try:
        from vllm_distributed_tpu.models.mixtral import MixtralForCausalLM

        register_model(MixtralForCausalLM)
    except ImportError:
        pass


def get_model_class(architecture: str):
    _populate()
    try:
        return _REGISTRY[architecture]
    except KeyError:
        raise ValueError(
            f"unsupported architecture {architecture!r}; known: "
            f"{sorted(_REGISTRY)}"
        ) from None


def list_architectures() -> list[str]:
    _populate()
    return sorted(_REGISTRY)
