"""Llama-family decoder (Llama 1/2/3, Mistral, Qwen2, Qwen3-dense).

The flagship model family of the parity configs (BASELINE.md: Llama-2-7B /
13B / 70B).  One implementation covers the variants via config switches:
GQA (num_key_value_heads), attention biases (Qwen2), per-head QK RMS-norm
(Qwen3), rope scaling (Llama-3), tied embeddings.

Functional style: ``init_params`` builds the pytree, ``forward`` is pure
and jit-safe.  Tensor parallelism is expressed purely as NamedSharding
partition specs over the mesh's "tp" axis (``partition_specs``); XLA/GSPMD
inserts the all-reduces after the row-parallel projections — the
TPU-native replacement for the reference's NCCL all-reduce inside vLLM
workers (SURVEY.md §2.2, §2.4).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from vllm_distributed_tpu.models.common import (
    SupportsQuantization,
    apply_rope,
    linear,
    rms_norm,
    rope_frequencies,
)
from vllm_distributed_tpu.ops.attention import (
    AttentionMetadata,
    paged_attention_reference,
    write_kv_pages,
)


class LlamaForCausalLM(SupportsQuantization):
    architectures = (
        "LlamaForCausalLM",
        "MistralForCausalLM",
        "Qwen2ForCausalLM",
        "Qwen3ForCausalLM",
    )
    # Weight-only quantization targets: the large matmuls.  Embeddings
    # (gathered, and possibly tied to lm_head), norms, and biases stay in
    # the model dtype.
    QUANT_PARAMS = frozenset(
        {"wq", "wk", "wv", "wo", "gate", "up", "down", "lm_head"}
    )

    def __init__(self, model_config: Any) -> None:
        hf = model_config.hf_config
        self.model_type = hf.model_type
        self.num_layers = model_config.get_num_layers()
        self.hidden_size = model_config.get_hidden_size()
        self.num_heads = model_config.get_num_attention_heads()
        self.num_kv_heads = model_config.get_num_kv_heads()
        self.head_dim = model_config.get_head_dim()
        self.intermediate_size = hf.intermediate_size
        self.vocab_size = hf.vocab_size
        self.rope_theta = float(getattr(hf, "rope_theta", 10000.0))
        self.rope_scaling = getattr(hf, "rope_scaling", None)
        self.rms_eps = float(getattr(hf, "rms_norm_eps", 1e-6))
        # Qwen2 carries q/k/v biases; Llama/Mistral/Qwen3 do not.
        self.attn_bias = bool(
            getattr(hf, "attention_bias", self.model_type == "qwen2")
        )
        self.qk_norm = self.model_type == "qwen3"
        self.tie_embeddings = bool(getattr(hf, "tie_word_embeddings", False))
        self.dtype = jnp.dtype(model_config.dtype)
        self.scale = self.head_dim**-0.5
        self._init_quant(model_config)

    # ---- params ----
    def init_params(self, rng: jax.Array) -> dict:
        """Random init (tests / --load-format dummy)."""
        h, nh, nkv, d, im, v = (
            self.hidden_size,
            self.num_heads,
            self.num_kv_heads,
            self.head_dim,
            self.intermediate_size,
            self.vocab_size,
        )

        def nrm(key, shape):
            return (jax.random.normal(key, shape, jnp.float32) * 0.02).astype(
                self.dtype
            )

        keys = iter(jax.random.split(rng, 7 * self.num_layers + 3))
        layers = []
        for _ in range(self.num_layers):
            layer = {
                "input_ln": jnp.ones((h,), self.dtype),
                "post_attn_ln": jnp.ones((h,), self.dtype),
                "wq": nrm(next(keys), (h, nh * d)),
                "wk": nrm(next(keys), (h, nkv * d)),
                "wv": nrm(next(keys), (h, nkv * d)),
                "wo": nrm(next(keys), (nh * d, h)),
                "gate": nrm(next(keys), (h, im)),
                "up": nrm(next(keys), (h, im)),
                "down": nrm(next(keys), (im, h)),
            }
            if self.attn_bias:
                layer["bq"] = jnp.zeros((nh * d,), self.dtype)
                layer["bk"] = jnp.zeros((nkv * d,), self.dtype)
                layer["bv"] = jnp.zeros((nkv * d,), self.dtype)
            if self.qk_norm:
                layer["q_norm"] = jnp.ones((d,), self.dtype)
                layer["k_norm"] = jnp.ones((d,), self.dtype)
            layers.append(layer)
        params = {
            "embed": nrm(next(keys), (v, h)),
            "layers": layers,
            "norm": jnp.ones((h,), self.dtype),
        }
        if not self.tie_embeddings:
            params["lm_head"] = nrm(next(keys), (h, v))
        return params

    def map_hf_name(self, name: str):
        """HF safetensors name -> (param path, 'T' to transpose) or None.

        HF reference layout: model.layers.{i}.self_attn.{q,k,v,o}_proj etc.
        """
        if name == "model.embed_tokens.weight":
            return ("embed",), None
        if name == "model.norm.weight":
            return ("norm",), None
        if name == "lm_head.weight":
            if self.tie_embeddings:
                return None
            return ("lm_head",), "T"
        if not name.startswith("model.layers."):
            return None
        parts = name.split(".")
        i = int(parts[2])
        rest = ".".join(parts[3:])
        table = {
            "self_attn.q_proj.weight": ("wq", "T"),
            "self_attn.k_proj.weight": ("wk", "T"),
            "self_attn.v_proj.weight": ("wv", "T"),
            "self_attn.o_proj.weight": ("wo", "T"),
            "self_attn.q_proj.bias": ("bq", None),
            "self_attn.k_proj.bias": ("bk", None),
            "self_attn.v_proj.bias": ("bv", None),
            "self_attn.q_norm.weight": ("q_norm", None),
            "self_attn.k_norm.weight": ("k_norm", None),
            "mlp.gate_proj.weight": ("gate", "T"),
            "mlp.up_proj.weight": ("up", "T"),
            "mlp.down_proj.weight": ("down", "T"),
            "input_layernorm.weight": ("input_ln", None),
            "post_attention_layernorm.weight": ("post_attn_ln", None),
        }
        hit = table.get(rest)
        if hit is None:
            return None
        return ("layers", i, hit[0]), hit[1]

    def partition_specs(self) -> dict:
        """PartitionSpecs mirroring the param tree, for the mesh "tp" axis.

        Column-parallel (out-dim sharded): wq/wk/wv/gate/up + lm_head;
        row-parallel (in-dim sharded): wo/down — GSPMD inserts the psum.
        """
        layer = {
            "input_ln": P(),
            "post_attn_ln": P(),
            "wq": P(None, "tp"),
            "wk": P(None, "tp"),
            "wv": P(None, "tp"),
            "wo": P("tp", None),
            "gate": P(None, "tp"),
            "up": P(None, "tp"),
            "down": P("tp", None),
        }
        if self.attn_bias:
            layer.update({"bq": P("tp"), "bk": P("tp"), "bv": P("tp")})
        if self.qk_norm:
            layer.update({"q_norm": P(), "k_norm": P()})
        specs = {
            "embed": P(None, "tp"),
            "layers": [dict(layer) for _ in range(self.num_layers)],
            "norm": P(),
        }
        if not self.tie_embeddings:
            specs["lm_head"] = P(None, "tp")
        return specs

    def kv_cache_spec(self) -> P:
        """Combined KV pool [2, P, page, HD]: shard the flat head×dim
        lanes over tp (heads are contiguous in HD, so this is per-kv-head
        sharding)."""
        return P(None, None, None, "tp")

    # ---- quantized projection fusion (single-chip fast path) ----
    _QKV_FUSE = ("wq", "wk", "wv")
    _GU_FUSE = ("gate", "up")

    def fuse_quantized_projections(self, params: dict) -> dict:
        """Concatenate the quantized Q|K|V and gate|up weights
        out-dim-wise so each layer issues one Pallas weight-streaming
        call instead of three/two (per-out-block computation is
        independent, so results are bit-identical to the unfused
        calls).  int8 concatenates q [in, out] and per-channel scales;
        int4 concatenates the packed q [in/2, out] and group scales
        [in/group, out] — both along the out dim, which preserves the
        packing and group layout exactly.  Only applies where every
        member is an eligible kernel-mode tensor of the same
        bits/group; called by the runner on the single-chip path after
        load."""
        from vllm_distributed_tpu.ops.quant import QuantizedTensor

        def fusable(layer, names):
            ws = [layer.get(n) for n in names]
            if not all(
                isinstance(w, QuantizedTensor)
                and w.bits in (8, 4)
                and w.q.ndim == 2
                and w.matmul in ("pallas", "pallas_interpret")
                # A tp-sharded concat along the out dim would interleave
                # shards of q|k|v instead of sharding the fused tensor:
                # fusion is a single-chip optimization only.
                and w.mesh is None
                for w in ws
            ):
                return None
            if len({(w.bits, w.group) for w in ws}) != 1:
                return None  # mixed schemes stay unfused
            if any(layer.get(f"b{n[-1]}") is not None for n in names
                   if n.startswith("w")):
                return None  # biased projections (qwen2) stay unfused
            return ws

        for layer in params.get("layers", []):
            for names, fused_name in (
                (self._QKV_FUSE, "wqkv"),
                (self._GU_FUSE, "wgu"),
            ):
                ws = fusable(layer, names)
                if ws is None:
                    continue
                layer[fused_name] = QuantizedTensor(
                    q=jnp.concatenate([w.q for w in ws], axis=-1),
                    scale=jnp.concatenate([w.scale for w in ws], axis=-1),
                    bits=ws[0].bits,
                    group=ws[0].group,
                    shape=(ws[0].shape[0], sum(w.shape[1] for w in ws)),
                    dtype=ws[0].dtype,
                    matmul=ws[0].matmul,
                )
                for n in names:
                    del layer[n]
        return params

    # ---- forward ----
    def _qkv(self, h: jax.Array, layer: dict, t: int):
        nh, nkv, d = self.num_heads, self.num_kv_heads, self.head_dim
        wqkv = layer.get("wqkv")
        if wqkv is not None:
            qkv = linear(h, wqkv)
            q = qkv[:, : nh * d]
            k = qkv[:, nh * d : (nh + nkv) * d]
            v = qkv[:, (nh + nkv) * d :]
        else:
            q = linear(h, layer["wq"], layer.get("bq"))
            k = linear(h, layer["wk"], layer.get("bk"))
            v = linear(h, layer["wv"], layer.get("bv"))
        return (
            q.reshape(t, nh, d),
            k.reshape(t, nkv, d),
            v.reshape(t, nkv, d),
        )

    def _mlp(self, h: jax.Array, layer: dict) -> jax.Array:
        """Post-attention MLP for one layer (overridden by MoE models)."""
        wgu = layer.get("wgu")
        if wgu is not None:
            gu = linear(h, wgu)
            gate = gu[:, : self.intermediate_size]
            up = gu[:, self.intermediate_size :]
        else:
            gate, up = linear(h, layer["gate"]), linear(h, layer["up"])
        return linear(jax.nn.silu(gate) * up, layer["down"])

    def forward(
        self,
        params: dict,
        token_ids: jax.Array,  # [T]
        kv_caches: list,  # per layer combined kv_pages [2, P, page, HD]
        meta: AttentionMetadata,
        attn_fn: Callable = paged_attention_reference,
        kv_write_fn: Callable = write_kv_pages,
        return_hidden: bool = False,
    ) -> tuple:
        """Returns (logits [S, V] at meta.logits_indices, updated kv);
        with return_hidden also the final-norm hidden states [S, H]
        (embeddings / scoring, /v1/embeddings parity)."""
        x = params["embed"][token_ids].astype(self.dtype)
        inv_freq = rope_frequencies(
            self.head_dim, self.rope_theta, rope_scaling=self.rope_scaling
        )
        new_kv = []
        t = token_ids.shape[0]
        for layer, kv_pages in zip(params["layers"], kv_caches):
            h = rms_norm(x, layer["input_ln"], self.rms_eps)
            q, k, v = self._qkv(h, layer, t)
            if self.qk_norm:
                q = rms_norm(q, layer["q_norm"], self.rms_eps)
                k = rms_norm(k, layer["k_norm"], self.rms_eps)
            q = apply_rope(q, meta.q_positions, inv_freq)
            k = apply_rope(k, meta.q_positions, inv_freq)
            kv_pages = kv_write_fn(kv_pages, k, v, meta.slot_mapping)
            new_kv.append(kv_pages)
            attn = attn_fn(
                q, kv_pages, meta,
                scale=self.scale, num_kv_heads=self.num_kv_heads,
            )
            x = x + linear(attn.reshape(t, -1), layer["wo"])

            h = rms_norm(x, layer["post_attn_ln"], self.rms_eps)
            x = x + self._mlp(h, layer)

        x = rms_norm(x, params["norm"], self.rms_eps)
        sel = x[meta.logits_indices]  # [S, H]
        lm_head = params.get("lm_head")
        if lm_head is None:
            logits = sel @ params["embed"].T.astype(sel.dtype)
        else:
            from vllm_distributed_tpu.ops.quant import quant_matmul

            logits = quant_matmul(sel, lm_head)
        logits = logits.astype(jnp.float32)
        if return_hidden:
            return logits, new_kv, sel.astype(jnp.float32)
        return logits, new_kv
