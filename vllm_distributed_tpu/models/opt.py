"""OPT decoder — parity config 1 (BASELINE.md: facebook/opt-125m, tp=1).

Learned positional embeddings (offset +2, the HF OPT quirk), pre-LN
blocks, ReLU MLP, biased projections, tied lm_head.  Supports
word_embed_proj_dim != hidden_size (opt-350m's project_in/out).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from vllm_distributed_tpu.models.common import (
    SupportsQuantization,
    layer_norm,
    linear,
)
from vllm_distributed_tpu.ops.attention import (
    AttentionMetadata,
    paged_attention_reference,
    write_kv_pages,
)

_POS_OFFSET = 2  # HF OPT reserves the first two position rows.


class OPTForCausalLM(SupportsQuantization):
    architectures = ("OPTForCausalLM",)
    QUANT_PARAMS = frozenset({"wq", "wk", "wv", "wo", "fc1", "fc2"})

    def __init__(self, model_config: Any) -> None:
        hf = model_config.hf_config
        self._init_quant(model_config)
        self.num_layers = hf.num_hidden_layers
        self.hidden_size = hf.hidden_size
        self.num_heads = hf.num_attention_heads
        self.num_kv_heads = hf.num_attention_heads
        self.head_dim = self.hidden_size // self.num_heads
        self.ffn_dim = hf.ffn_dim
        self.vocab_size = hf.vocab_size
        self.max_positions = hf.max_position_embeddings
        self.word_embed_dim = getattr(
            hf, "word_embed_proj_dim", self.hidden_size
        )
        self.do_layer_norm_before = bool(
            getattr(hf, "do_layer_norm_before", True)
        )
        self.dtype = jnp.dtype(model_config.dtype)
        self.scale = self.head_dim**-0.5
        self.eps = 1e-5

    def init_params(self, rng: jax.Array) -> dict:
        h, d, f, v = self.hidden_size, self.head_dim, self.ffn_dim, self.vocab_size

        def nrm(key, shape):
            return (jax.random.normal(key, shape, jnp.float32) * 0.02).astype(
                self.dtype
            )

        keys = iter(jax.random.split(rng, 7 * self.num_layers + 4))
        layers = []
        for _ in range(self.num_layers):
            layers.append(
                {
                    "attn_ln_w": jnp.ones((h,), self.dtype),
                    "attn_ln_b": jnp.zeros((h,), self.dtype),
                    "wq": nrm(next(keys), (h, h)),
                    "bq": jnp.zeros((h,), self.dtype),
                    "wk": nrm(next(keys), (h, h)),
                    "bk": jnp.zeros((h,), self.dtype),
                    "wv": nrm(next(keys), (h, h)),
                    "bv": jnp.zeros((h,), self.dtype),
                    "wo": nrm(next(keys), (h, h)),
                    "bo": jnp.zeros((h,), self.dtype),
                    "final_ln_w": jnp.ones((h,), self.dtype),
                    "final_ln_b": jnp.zeros((h,), self.dtype),
                    "fc1": nrm(next(keys), (h, f)),
                    "fc1_b": jnp.zeros((f,), self.dtype),
                    "fc2": nrm(next(keys), (f, h)),
                    "fc2_b": jnp.zeros((h,), self.dtype),
                }
            )
        params = {
            "embed": nrm(next(keys), (v, self.word_embed_dim)),
            "embed_pos": nrm(
                next(keys), (self.max_positions + _POS_OFFSET, h)
            ),
            "layers": layers,
        }
        # HF OPT only has a decoder-level final LN when pre-LN (opt-350m
        # ships none and applies none).
        if self.do_layer_norm_before:
            params["final_ln_w"] = jnp.ones((h,), self.dtype)
            params["final_ln_b"] = jnp.zeros((h,), self.dtype)
        if self.word_embed_dim != h:
            params["project_in"] = nrm(next(keys), (self.word_embed_dim, h))
            params["project_out"] = nrm(next(keys), (h, self.word_embed_dim))
        return params

    def map_hf_name(self, name: str):
        # Some checkpoints use "model.decoder.", others "decoder.".
        if name.startswith("model."):
            name = name[len("model.") :]
        if name == "lm_head.weight":
            return None  # tied
        if not name.startswith("decoder."):
            return None
        name = name[len("decoder.") :]
        top = {
            "embed_tokens.weight": (("embed",), None),
            "embed_positions.weight": (("embed_pos",), None),
            "final_layer_norm.weight": (("final_ln_w",), None),
            "final_layer_norm.bias": (("final_ln_b",), None),
            "project_in.weight": (("project_in",), "T"),
            "project_out.weight": (("project_out",), "T"),
        }
        if name in top:
            return top[name]
        if not name.startswith("layers."):
            return None
        parts = name.split(".")
        i = int(parts[1])
        rest = ".".join(parts[2:])
        table = {
            "self_attn.q_proj.weight": ("wq", "T"),
            "self_attn.q_proj.bias": ("bq", None),
            "self_attn.k_proj.weight": ("wk", "T"),
            "self_attn.k_proj.bias": ("bk", None),
            "self_attn.v_proj.weight": ("wv", "T"),
            "self_attn.v_proj.bias": ("bv", None),
            "self_attn.out_proj.weight": ("wo", "T"),
            "self_attn.out_proj.bias": ("bo", None),
            "self_attn_layer_norm.weight": ("attn_ln_w", None),
            "self_attn_layer_norm.bias": ("attn_ln_b", None),
            "final_layer_norm.weight": ("final_ln_w", None),
            "final_layer_norm.bias": ("final_ln_b", None),
            "fc1.weight": ("fc1", "T"),
            "fc1.bias": ("fc1_b", None),
            "fc2.weight": ("fc2", "T"),
            "fc2.bias": ("fc2_b", None),
        }
        hit = table.get(rest)
        if hit is None:
            return None
        return ("layers", i, hit[0]), hit[1]

    def partition_specs(self) -> dict:
        layer = {
            "attn_ln_w": P(), "attn_ln_b": P(),
            "wq": P(None, "tp"), "bq": P("tp"),
            "wk": P(None, "tp"), "bk": P("tp"),
            "wv": P(None, "tp"), "bv": P("tp"),
            "wo": P("tp", None), "bo": P(),
            "final_ln_w": P(), "final_ln_b": P(),
            "fc1": P(None, "tp"), "fc1_b": P("tp"),
            "fc2": P("tp", None), "fc2_b": P(),
        }
        specs = {
            "embed": P(None, None),
            "embed_pos": P(),
            "layers": [dict(layer) for _ in range(self.num_layers)],
        }
        if self.do_layer_norm_before:
            specs["final_ln_w"] = P()
            specs["final_ln_b"] = P()
        if self.word_embed_dim != self.hidden_size:
            specs["project_in"] = P()
            specs["project_out"] = P()
        return specs

    def kv_cache_spec(self) -> P:
        """Combined KV pool [2, P, page, HD]: shard flat head lanes."""
        return P(None, None, None, "tp")

    def forward(
        self,
        params: dict,
        token_ids: jax.Array,
        kv_caches: list,
        meta: AttentionMetadata,
        attn_fn: Callable = paged_attention_reference,
        kv_write_fn: Callable = write_kv_pages,
        return_hidden: bool = False,
    ) -> tuple:
        t = token_ids.shape[0]
        x = params["embed"][token_ids].astype(self.dtype)
        if "project_in" in params:
            x = linear(x, params["project_in"])
        pos = params["embed_pos"][meta.q_positions + _POS_OFFSET].astype(
            self.dtype
        )
        x = x + pos
        new_kv = []
        for layer, kv_pages in zip(params["layers"], kv_caches):
            h = (
                layer_norm(x, layer["attn_ln_w"], layer["attn_ln_b"], self.eps)
                if self.do_layer_norm_before
                else x
            )
            q = linear(h, layer["wq"], layer["bq"]).reshape(
                t, self.num_heads, self.head_dim
            )
            k = linear(h, layer["wk"], layer["bk"]).reshape(
                t, self.num_kv_heads, self.head_dim
            )
            v = linear(h, layer["wv"], layer["bv"]).reshape(
                t, self.num_kv_heads, self.head_dim
            )
            kv_pages = kv_write_fn(kv_pages, k, v, meta.slot_mapping)
            new_kv.append(kv_pages)
            attn = attn_fn(
                q, kv_pages, meta,
                scale=self.scale, num_kv_heads=self.num_kv_heads,
            )
            x = x + linear(attn.reshape(t, -1), layer["wo"], layer["bo"])
            if not self.do_layer_norm_before:
                x = layer_norm(
                    x, layer["attn_ln_w"], layer["attn_ln_b"], self.eps
                )

            h = (
                layer_norm(
                    x, layer["final_ln_w"], layer["final_ln_b"], self.eps
                )
                if self.do_layer_norm_before
                else x
            )
            h = jax.nn.relu(linear(h, layer["fc1"], layer["fc1_b"]))
            h = linear(h, layer["fc2"], layer["fc2_b"])
            x = x + h
            if not self.do_layer_norm_before:
                x = layer_norm(
                    x, layer["final_ln_w"], layer["final_ln_b"], self.eps
                )

        if "final_ln_w" in params:
            x = layer_norm(
                x, params["final_ln_w"], params["final_ln_b"], self.eps
            )
        if "project_out" in params:
            x = linear(x, params["project_out"])
        sel = x[meta.logits_indices]
        logits = (sel @ params["embed"].T.astype(sel.dtype)).astype(
            jnp.float32
        )
        if return_hidden:
            return logits, new_kv, sel.astype(jnp.float32)
        return logits, new_kv
