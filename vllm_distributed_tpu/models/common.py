"""Shared functional building blocks for the model zoo.

Models here are *functions over pytrees*, not stateful modules: params are
nested dicts of jax arrays, the forward pass is pure, and everything
composes with jit/pjit/NamedSharding (SURVEY.md §7 design stance).  Linear
weights are stored input-major (``[in, out]``) so application is a plain
``x @ w`` that XLA tiles onto the MXU without transposes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class SupportsQuantization:
    """Weight-only quantization hooks shared by the model zoo.

    Subclasses set ``QUANT_PARAMS`` (leaf names of the big matmuls —
    embeddings/norms/biases/routers stay in the model dtype) and call
    ``_init_quant(model_config)`` from ``__init__``.  A model that skips
    both simply never quantizes (the loader checks ``quant_method``)."""

    QUANT_PARAMS: frozenset = frozenset()

    def _init_quant(self, model_config) -> None:
        self.quant_method = model_config.quantization

    def should_quantize(self, path: tuple) -> bool:
        """Whether the param at `path` gets weight-only quantization
        (per-expert paths end in an int index; the name precedes it)."""
        names = [k for k in path if isinstance(k, str)]
        return bool(names) and names[-1] in self.QUANT_PARAMS


def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    x = x * jax.lax.rsqrt(var + eps)
    return (x * weight.astype(jnp.float32)).astype(dtype)


def layer_norm(
    x: jax.Array, weight: jax.Array, bias: jax.Array, eps: float
) -> jax.Array:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    x = (x - mean) * jax.lax.rsqrt(var + eps)
    return (x * weight.astype(jnp.float32) + bias.astype(jnp.float32)).astype(
        dtype
    )


def linear(x: jax.Array, w, b: jax.Array | None = None) -> jax.Array:
    from vllm_distributed_tpu.ops.quant import quant_matmul

    return quant_matmul(x, w, b)


def rope_frequencies(
    head_dim: int,
    theta: float,
    *,
    rope_scaling: dict | None = None,
) -> jax.Array:
    """Inverse frequencies [head_dim // 2], with llama3/linear scaling."""
    inv_freq = 1.0 / (
        theta
        ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    if not rope_scaling:
        return inv_freq
    rtype = rope_scaling.get("rope_type", rope_scaling.get("type", ""))
    if rtype == "linear":
        return inv_freq / float(rope_scaling["factor"])
    if rtype == "llama3":
        factor = float(rope_scaling["factor"])
        lo = float(rope_scaling.get("low_freq_factor", 1.0))
        hi = float(rope_scaling.get("high_freq_factor", 4.0))
        orig = float(rope_scaling.get("original_max_position_embeddings", 8192))
        wavelen = 2.0 * jnp.pi / inv_freq
        ratio = orig / wavelen
        smooth = jnp.clip((ratio - lo) / (hi - lo), 0.0, 1.0)
        scaled = jnp.where(
            wavelen > orig / lo,  # low-frequency band: full scaling
            inv_freq / factor,
            jnp.where(
                wavelen < orig / hi,  # high-frequency band: no scaling
                inv_freq,
                (1.0 - smooth) * inv_freq / factor + smooth * inv_freq,
            ),
        )
        return scaled
    # Unknown scaling types fall back to unscaled (logged by the loader).
    return inv_freq


def apply_rope(
    x: jax.Array,  # [T, H, D]
    positions: jax.Array,  # [T]
    inv_freq: jax.Array,  # [D // 2]
) -> jax.Array:
    """HF-llama convention: rotate_half over the (front, back) halves."""
    angles = positions[:, None].astype(jnp.float32) * inv_freq[None, :]
    cos = jnp.cos(angles)[:, None, :]  # [T, 1, D/2]
    sin = jnp.sin(angles)[:, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(x.dtype)
