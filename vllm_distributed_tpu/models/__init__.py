from vllm_distributed_tpu.models.registry import get_model_class

__all__ = ["get_model_class"]
