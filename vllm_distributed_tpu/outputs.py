"""Request/step output types.

``ModelRunnerOutput`` is the per-step contract returned from workers to the
executor (the analog of vLLM's ModelRunnerOutput consumed at launch.py:46,
326).  ``RequestOutput``/``CompletionOutput`` are the user-facing results
streamed by the engine.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class ModelRunnerOutput:
    """What a worker returns from one execute_model step.

    Only the designated reply rank returns a populated instance; all other
    ranks return None (reference: launch.py:536-538).
    """

    # req_id -> newly sampled token ids this step (usually length 1).
    sampled_token_ids: dict[str, list[int]] = field(default_factory=dict)
    # req_id -> list of (token_id -> logprob) dicts, parallel to sampled ids.
    logprobs: dict[str, list[dict[int, float]]] = field(default_factory=dict)
    # req_id -> number of prompt tokens processed this step (chunked prefill).
    num_prompt_tokens_processed: dict[str, int] = field(default_factory=dict)
    # KV-connector progress (disaggregated prefill, SURVEY.md §3.4):
    # requests whose KV finished moving on THIS worker this step; the
    # executor-side KVOutputAggregator intersects across the world.
    kv_finished_sending: set[str] = field(default_factory=set)
    kv_finished_recving: set[str] = field(default_factory=set)
    # Tiered KV cache (ISSUE 14): wall seconds this worker spent
    # applying the step's spill/restore spans (device_get/device_put
    # batches) before executing — feeds vllm:kv_restore_seconds and the
    # engine.kv_restore trace span on restore-bearing steps.
    kv_tier_seconds: float = 0.0


@dataclass
class CompletionOutput:
    index: int
    text: str
    token_ids: list[int]
    cumulative_logprob: float | None = None
    logprobs: list[dict[int, float]] | None = None
    finish_reason: str | None = None  # "stop" | "length" | "abort"
    stop_reason: int | str | None = None

    @property
    def finished(self) -> bool:
        return self.finish_reason is not None


@dataclass
class RequestOutput:
    request_id: str
    prompt: str | None
    prompt_token_ids: list[int]
    outputs: list[CompletionOutput]
    finished: bool
    metrics: "RequestMetrics | None" = None


@dataclass
class RequestMetrics:
    """Per-request timing.  Wall-clock stamps (`*_time`) exist for span
    start timestamps and human display; every INTERVAL (TTFT, ITL, e2e,
    stage durations) is computed from the `*_time_mono` monotonic
    counterparts so an NTP step can never produce negative or garbage
    latency observations."""

    arrival_time: float = 0.0
    first_scheduled_time: float | None = None
    first_token_time: float | None = None
    finished_time: float | None = None
    # Monotonic counterparts, used for all interval math.
    arrival_time_mono: float = 0.0
    first_scheduled_time_mono: float | None = None
    first_token_time_mono: float | None = None
    finished_time_mono: float | None = None
    last_token_time_mono: float | None = None
    # Prompt tokens already reported to vllm:prompt_tokens (prefill
    # progress is counted per processed step, remainder at first token).
    prompt_tokens_counted: int = 0
    # Tokens served from the prefix cache at the last admission
    # (0 unless --enable-prefix-caching hit; RequestOutput-visible).
    cached_tokens: int = 0
    # ---- SLO accounting (ISSUE 12, engine/slo.py) ----
    # Raw client-supplied class (slo_class sampling param / header) and
    # its sanitized, cardinality-bounded form (cached by EngineMetrics).
    slo_class: str = "default"
    slo_class_resolved: str | None = None
    # Worst observed inter-token interval (monotonic), and the
    # request's own log-bucket ITL tally — the per-request timeline the
    # fleet histogram merge is bit-recomputable from.
    slo_itl_max_s: float | None = None
    slo_itl_buckets: dict[int, int] | None = None

    @property
    def ttft(self) -> float | None:
        if self.first_token_time_mono is not None and self.arrival_time_mono:
            return self.first_token_time_mono - self.arrival_time_mono
        if self.first_token_time is None:
            return None
        return self.first_token_time - self.arrival_time
