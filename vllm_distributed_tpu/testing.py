"""Synthetic model snapshots for tests, dryruns, and benches.

Zero-egress environments can't download checkpoints, so anything that
needs a model builds one: a config.json on disk (weights come from
--load-format dummy) shaped like the real family member it stands in for.
"""

from __future__ import annotations

import json
import os
import tempfile


def write_llama_config(
    dirname: str | None = None,
    *,
    vocab_size: int = 128,
    hidden: int = 64,
    intermediate: int = 128,
    layers: int = 2,
    heads: int = 8,
    kv_heads: int = 4,
    max_pos: int = 2048,
    dtype: str = "float32",
    tie_embeddings: bool = False,
) -> str:
    """Write a Llama-architecture config.json; returns the directory."""
    if dirname is None:
        dirname = tempfile.mkdtemp(prefix="vdt_tiny_llama_")
    cfg = {
        "architectures": ["LlamaForCausalLM"],
        "model_type": "llama",
        "hidden_size": hidden,
        "intermediate_size": intermediate,
        "num_hidden_layers": layers,
        "num_attention_heads": heads,
        "num_key_value_heads": kv_heads,
        "head_dim": hidden // heads,
        "vocab_size": vocab_size,
        "max_position_embeddings": max_pos,
        "rms_norm_eps": 1e-6,
        "rope_theta": 10000.0,
        "torch_dtype": dtype,
        "tie_word_embeddings": tie_embeddings,
        "hidden_act": "silu",
        "bos_token_id": 1,
        "eos_token_id": 2,
    }
    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, "config.json"), "w") as f:
        json.dump(cfg, f)
    return dirname


def write_mixtral_config(
    dirname: str | None = None,
    *,
    vocab_size: int = 128,
    hidden: int = 64,
    intermediate: int = 128,
    layers: int = 2,
    heads: int = 8,
    kv_heads: int = 4,
    num_experts: int = 4,
    top_k: int = 2,
    max_pos: int = 2048,
    dtype: str = "float32",
) -> str:
    """Write a Mixtral-architecture config.json; returns the directory."""
    if dirname is None:
        dirname = tempfile.mkdtemp(prefix="vdt_tiny_mixtral_")
    cfg = {
        "architectures": ["MixtralForCausalLM"],
        "model_type": "mixtral",
        "hidden_size": hidden,
        "intermediate_size": intermediate,
        "num_hidden_layers": layers,
        "num_attention_heads": heads,
        "num_key_value_heads": kv_heads,
        "head_dim": hidden // heads,
        "num_local_experts": num_experts,
        "num_experts_per_tok": top_k,
        "vocab_size": vocab_size,
        "max_position_embeddings": max_pos,
        "rms_norm_eps": 1e-6,
        "rope_theta": 10000.0,
        "torch_dtype": dtype,
        "tie_word_embeddings": False,
        "hidden_act": "silu",
        "sliding_window": None,
        "bos_token_id": 1,
        "eos_token_id": 2,
    }
    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, "config.json"), "w") as f:
        json.dump(cfg, f)
    return dirname


def full_attention_reference(q, k, v, scale, causal=True):
    """Dense (non-paged) attention oracle [T, H, D] with GQA repeat —
    the reference for ring attention (tests + multichip dryrun)."""
    import jax
    import jax.numpy as jnp

    t, hq, _ = q.shape
    hkv = k.shape[1]
    if hkv != hq:
        k = jnp.repeat(k, hq // hkv, axis=1)
        v = jnp.repeat(v, hq // hkv, axis=1)
    logits = jnp.einsum("qhd,khd->hqk", q, k) * scale
    if causal:
        mask = jnp.arange(t)[:, None] >= jnp.arange(t)[None, :]
        logits = jnp.where(mask[None], logits, -jnp.inf)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("hqk,khd->qhd", p, v)


# Shapes of real family members, for dummy-weight perf runs.
LLAMA_1B = dict(
    vocab_size=32000, hidden=2048, intermediate=8192, layers=16,
    heads=32, kv_heads=8, max_pos=4096, dtype="bfloat16",
)
LLAMA_7B = dict(
    vocab_size=32000, hidden=4096, intermediate=11008, layers=32,
    heads=32, kv_heads=32, max_pos=4096, dtype="bfloat16",
)
# Mixtral-8x7B (milestone config 5), for dummy-weight EP perf runs.
MIXTRAL_8X7B = dict(
    vocab_size=32000, hidden=4096, intermediate=14336, layers=32,
    heads=32, kv_heads=8, num_experts=8, top_k=2, max_pos=4096,
    dtype="bfloat16",
)
# Mixtral-style MoE scaled to fit ONE v5e chip with int8 weights
# (~4.8B params): same 8-expert/top-2 routing shape as the flagship
# family, 1B-class dims — the single-chip MoE bench config.
MIXTRAL_8X1B = dict(
    vocab_size=32000, hidden=2048, intermediate=5632, layers=16,
    heads=32, kv_heads=8, num_experts=8, top_k=2, max_pos=4096,
    dtype="bfloat16",
)
