"""Prometheus instrumentation for the engine loop (SURVEY.md §5.5).

The reference serves vLLM's Prometheus metrics through `build_app`
(/root/reference/src/launch.py:429-432); this is the TPU-native engine's
equivalent, using vLLM's metric names (prefix ``vllm:``) so existing
dashboards/alerts keep working after a backend swap.

One ``EngineMetrics`` per engine with its own CollectorRegistry (no
global-registry collisions across engines/tests).  Disabled via
``--disable-log-stats`` (ObservabilityConfig.collect_metrics=False), in
which case every record call is a no-op and /metrics reports only
process defaults.
"""

from __future__ import annotations

import time

_TTFT_BUCKETS = (
    0.001, 0.005, 0.01, 0.02, 0.04, 0.06, 0.08, 0.1, 0.25, 0.5,
    0.75, 1.0, 2.5, 5.0, 7.5, 10.0, 20.0, 40.0, 80.0,
)
_ITL_BUCKETS = (
    0.0005, 0.001, 0.005, 0.01, 0.02, 0.04, 0.06, 0.08, 0.1, 0.25,
    0.5, 0.75, 1.0, 2.5,
)
_E2E_BUCKETS = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 20.0, 40.0, 80.0, 160.0,
)
# Heartbeat RTTs live on the control plane (DCN), not the data plane:
# sub-millisecond on loopback, a few ms cross-host, anything near the
# ping interval is a miss in the making.
_HEARTBEAT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0,
)
# One recovery = teardown + backoff + agent redial + executor rebuild +
# replay; sub-second with warm AOT caches on mocks, tens of seconds on a
# real pod slice (device init + weight load dominate).
_RECOVERY_BUCKETS = (
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 20.0, 40.0, 80.0, 160.0, 320.0,
)
# Request stages (queue wait, prefill, decode) span the TTFT..e2e range.
_STAGE_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    20.0, 40.0, 80.0, 160.0,
)
# Emitted tokens per spec-decode verify window: 1 (full reject) up to
# K+1 (full accept); integer buckets up to the largest sane K.
_SPEC_ACCEPT_BUCKETS = (1, 2, 3, 4, 6, 8, 12, 16, 24, 33)
# Step phases (schedule on host CPU, dispatch fan-out, gather wait):
# schedule/dispatch are sub-millisecond, gather bounds device time.
_STEP_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
)
# Per-SLO-class latency histograms (ISSUE 12), in MILLISECONDS to match
# the VDT_SLO_*_MS target units.  Coarser than the engine's log-bucket
# histograms (engine/slo.py) — the fleet-exact merge runs over those;
# these exist so ordinary Prometheus dashboards get per-class curves.
_SLO_TTFT_MS_BUCKETS = (
    1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0,
    2500.0, 5000.0, 10000.0, 30000.0, 60000.0, 120000.0,
)
_SLO_ITL_MS_BUCKETS = (
    0.5, 1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0,
)
# XLA compile wall time (trace+lower+compile+first run): sub-second on
# warm AOT/disk caches, tens of seconds cold on a pod slice.
_COMPILE_BUCKETS = (
    0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 20.0, 40.0,
    80.0, 160.0, 320.0,
)
# Host-tier restore batches (ISSUE 14): a few pages over PCIe/DMA —
# sub-millisecond on loopback mocks, milliseconds for real page spans;
# anything approaching prefill time means the crossover is set wrong.
_KV_RESTORE_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
)

# Span name (tracing.py) -> per-stage histogram attribute.  The tracer's
# metrics sink feeds these, so the Prometheus histograms and the traces
# derive from the SAME measurements and can never disagree.
SPAN_METRIC_MAP = {
    "engine.queue": "queue_time",
    "engine.prefill": "prefill_time",
    "engine.decode": "decode_time",
    "scheduler.schedule": "step_schedule_time",
    "executor.dispatch": "step_dispatch_time",
    "executor.gather": "step_gather_time",
}

# Every vllm:* metric family this engine documents (README
# "Observability"), as rendered in `# TYPE` lines (counters carry the
# `_total` suffix there).  tests/test_metrics.py asserts render()
# exposes exactly this set — additions/removals must update both.
DOCUMENTED_METRICS = (
    "vllm:num_requests_running",
    "vllm:num_requests_waiting",
    "vllm:prompt_tokens_total",
    "vllm:generation_tokens_total",
    "vllm:num_preemptions_total",
    "vllm:prefix_cache_queries_total",
    "vllm:prefix_cache_hits_total",
    # ---- tiered KV cache (ISSUE 14) ----
    "vllm:kv_spill_pages_total",
    "vllm:kv_restore_pages_total",
    "vllm:kv_restore_seconds",
    "vllm:host_kv_bytes",
    # ---- disaggregated prefill/decode hand-off (ISSUE 15) ----
    "vllm:kv_transfer_pages_total",
    "vllm:kv_transfer_bytes_total",
    "vllm:kv_transfer_seconds",
    "vllm:spec_decode_draft_tokens_total",
    "vllm:spec_decode_accepted_tokens_total",
    "vllm:spec_decode_acceptance_length",
    "vllm:gpu_cache_usage_perc",
    "vllm:time_to_first_token_seconds",
    "vllm:time_per_output_token_seconds",
    "vllm:e2e_request_latency_seconds",
    "vllm:request_queue_time_seconds",
    "vllm:request_prefill_time_seconds",
    "vllm:request_decode_time_seconds",
    "vllm:step_schedule_time_seconds",
    "vllm:step_dispatch_time_seconds",
    "vllm:step_gather_time_seconds",
    "vllm:request_success_total",
    "vllm:pipeline_breaks_total",
    "vllm:requests_rejected_total",
    "vllm:engine_drain_state",
    "vllm:admission_queued_tokens",
    "vllm:replica_info",
    "vllm:host_up",
    "vllm:heartbeat_latency_seconds",
    "vllm:engine_dead_info",
    "vllm:engine_restarts_total",
    "vllm:requests_replayed_total",
    "vllm:engine_recovery_seconds",
    # ---- SLO/goodput accounting (ISSUE 12) ----
    "vllm:slo_requests_total",
    "vllm:slo_ttft_attained_total",
    "vllm:slo_itl_attained_total",
    "vllm:goodput_requests_total",
    "vllm:slo_ttft_ms",
    "vllm:slo_itl_ms",
    # ---- XLA/device telemetry (ISSUE 12) ----
    "vllm:xla_compiles_total",
    "vllm:xla_compile_seconds",
    "vllm:hbm_live_bytes",
    "vllm:step_roofline_frac",
    # ---- fleet sentinel (ISSUE 20) ----
    "vllm:slo_burn_rate",
    "vllm:itl_p99_ms",
)


class EngineMetrics:
    """Engine-loop instruments; every method is a no-op when disabled."""

    def __init__(self, model_name: str, enabled: bool = True) -> None:
        from vllm_distributed_tpu.engine.sentinel import (
            BurnRateTracker,
            SentinelLog,
        )

        self.enabled = enabled
        self.registry = None
        # Fleet sentinel (ISSUE 20): the engine's slice of the unified
        # event timeline (served at /debug/events) and its own
        # multi-window SLO burn tracker.  Both live even when the
        # prometheus exposition is disabled — events are not metrics.
        self.events = SentinelLog("engine")
        self.burn = BurnRateTracker()
        if not enabled:
            return
        try:
            from prometheus_client import (
                CollectorRegistry,
                Counter,
                Gauge,
                Histogram,
            )
        except ImportError:
            # Degrade to disabled rather than failing engine startup on
            # an install without the (optional) prometheus_client.
            import logging

            logging.getLogger(__name__).warning(
                "prometheus_client not installed; metrics disabled"
            )
            self.enabled = False
            return

        self.registry = CollectorRegistry()
        label = {"model_name": model_name}

        def counter(name, doc):
            return Counter(
                name, doc, ["model_name"], registry=self.registry
            ).labels(**label)

        def gauge(name, doc):
            return Gauge(
                name, doc, ["model_name"], registry=self.registry
            ).labels(**label)

        def histogram(name, doc, buckets):
            return Histogram(
                name,
                doc,
                ["model_name"],
                buckets=buckets,
                registry=self.registry,
            ).labels(**label)

        self.num_running = gauge(
            "vllm:num_requests_running",
            "Requests currently executing on the device",
        )
        self.num_waiting = gauge(
            "vllm:num_requests_waiting", "Requests queued for admission"
        )
        self.prompt_tokens = counter(
            "vllm:prompt_tokens", "Prefill tokens processed"
        )
        self.generation_tokens = counter(
            "vllm:generation_tokens", "Tokens generated"
        )
        self.preemptions = counter(
            "vllm:num_preemptions", "Requests preempted by the scheduler"
        )
        self.prefix_cache_queries = counter(
            "vllm:prefix_cache_queries",
            "Tokens looked up in the prefix cache at (re-)admission "
            "(includes preemption-resume lookups)",
        )
        # Split per tier (ISSUE 14): tier="hbm" counts resident hits,
        # tier="host" tokens restored from the host-DRAM spill tier.
        # Sum across tiers for the pre-tiering total.
        self._prefix_cache_hits = Counter(
            "vllm:prefix_cache_hits",
            "Tokens served from cached KV pages instead of prefill "
            "(cross-request prefix reuse and preemption-resume "
            'recovery), per cache tier: "hbm" resident pages, "host" '
            "pages restored from the host-DRAM spill tier",
            ["model_name", "tier"],
            registry=self.registry,
        )
        self.prefix_cache_hits_hbm = self._prefix_cache_hits.labels(
            model_name=model_name, tier="hbm"
        )
        self.prefix_cache_hits_host = self._prefix_cache_hits.labels(
            model_name=model_name, tier="host"
        )
        # ---- tiered KV cache (ISSUE 14) ----
        self.kv_spill_pages = counter(
            "vllm:kv_spill_pages",
            "KV pages spilled from the HBM pool to the host-DRAM tier "
            "on eviction (worker-side device_get batches)",
        )
        self.kv_restore_pages = counter(
            "vllm:kv_restore_pages",
            "KV pages streamed back from the host-DRAM tier into "
            "freshly allocated HBM pages ahead of a prefill resume",
        )
        self.kv_restore_seconds = histogram(
            "vllm:kv_restore_seconds",
            "Worker wall time applying a restore-bearing step's KV-tier "
            "spans (the restore stall the engine.kv_restore span traces)",
            _KV_RESTORE_BUCKETS,
        )
        self.host_kv_bytes = gauge(
            "vllm:host_kv_bytes",
            "Bytes of KV held in the host-DRAM spill tier "
            "(slots in use x per-page pool bytes)",
        )
        # ---- disaggregated prefill/decode hand-off (ISSUE 15) ----
        # direction="out": page-layer chunks exported to another
        # replica; direction="in": chunks imported and committed here.
        self._kv_transfer_pages = Counter(
            "vllm:kv_transfer_pages",
            "KV page-layer chunks moved over DCN for prefill/decode "
            'hand-offs (pages x layers), by direction: "out" exported '
            'from this replica\'s held prefills, "in" imported and '
            "committed into the local prefix index",
            ["model_name", "direction"],
            registry=self.registry,
        )
        self._kv_transfer_bytes = Counter(
            "vllm:kv_transfer_bytes",
            "KV bytes moved over DCN for prefill/decode hand-offs, by "
            "direction (pre-base64 wire payload)",
            ["model_name", "direction"],
            registry=self.registry,
        )
        self.kv_transfer_pages_out = self._kv_transfer_pages.labels(
            model_name=model_name, direction="out"
        )
        self.kv_transfer_pages_in = self._kv_transfer_pages.labels(
            model_name=model_name, direction="in"
        )
        self.kv_transfer_bytes_out = self._kv_transfer_bytes.labels(
            model_name=model_name, direction="out"
        )
        self.kv_transfer_bytes_in = self._kv_transfer_bytes.labels(
            model_name=model_name, direction="in"
        )
        self.kv_transfer_seconds = histogram(
            "vllm:kv_transfer_seconds",
            "Wall seconds per KV hand-off transfer on this replica "
            "(export: hold creation to release; import: begin to "
            "commit)",
            _KV_RESTORE_BUCKETS,
        )
        # ---- speculative decoding (ISSUE 11) ----
        self.spec_draft_tokens = counter(
            "vllm:spec_decode_draft_tokens",
            "Tokens drafted by the n-gram prompt-lookup proposer into "
            "fused verify passes",
        )
        self.spec_accepted_tokens = counter(
            "vllm:spec_decode_accepted_tokens",
            "Drafted tokens accepted by greedy verification (bonus "
            "tokens not counted; acceptance rate = accepted / draft)",
        )
        self.spec_acceptance_length = histogram(
            "vllm:spec_decode_acceptance_length",
            "Tokens emitted per verified request window (1 + accepted "
            "drafts; 1 = full reject, K+1 = full accept)",
            _SPEC_ACCEPT_BUCKETS,
        )
        self.kv_cache_usage = gauge(
            "vllm:gpu_cache_usage_perc",  # vLLM's name, kept for dashboards
            "Fraction of usable KV pages held by live requests "
            "(evictable cached pages count as free)",
        )
        self.ttft = histogram(
            "vllm:time_to_first_token_seconds",
            "Time from request arrival to first generated token",
            _TTFT_BUCKETS,
        )
        self.itl = histogram(
            "vllm:time_per_output_token_seconds",
            "Inter-token latency (per generated token after the first)",
            _ITL_BUCKETS,
        )
        self.e2e_latency = histogram(
            "vllm:e2e_request_latency_seconds",
            "Request end-to-end latency",
            _E2E_BUCKETS,
        )
        # ---- per-stage latencies, fed from span data (tracing.py) via
        # observe_span so dashboards and traces can never disagree.
        # Populated only while tracing is enabled.
        self.queue_time = histogram(
            "vllm:request_queue_time_seconds",
            "Arrival to first schedule (admission queue wait)",
            _STAGE_BUCKETS,
        )
        self.prefill_time = histogram(
            "vllm:request_prefill_time_seconds",
            "First schedule to first token ((chunked) prefill)",
            _STAGE_BUCKETS,
        )
        self.decode_time = histogram(
            "vllm:request_decode_time_seconds",
            "First token to finish (decode)",
            _STAGE_BUCKETS,
        )
        self.step_schedule_time = histogram(
            "vllm:step_schedule_time_seconds",
            "Scheduler time per engine step",
            _STEP_BUCKETS,
        )
        self.step_dispatch_time = histogram(
            "vllm:step_dispatch_time_seconds",
            "Per-host RPC dispatch fan-out time per step",
            _STEP_BUCKETS,
        )
        self.step_gather_time = histogram(
            "vllm:step_gather_time_seconds",
            "Per-host reply wait per step (bounds device time + DCN)",
            _STEP_BUCKETS,
        )
        self.pipeline_breaks = counter(
            "vllm:pipeline_breaks",
            "Async-scheduling reconciliation drains: the predicted "
            "post-step state was invalidated (stop/EOS/budget "
            "mid-window, admission, preemption risk) and the dispatch "
            "pipeline flushed before rescheduling",
        )
        self._success = Counter(
            "vllm:request_success",
            "Finished requests by finish reason",
            ["model_name", "finished_reason"],
            registry=self.registry,
        )
        # ---- overload resilience (ISSUE 8) ----
        self._rejected = Counter(
            "vllm:requests_rejected",
            "Admission rejections (HTTP 429) by reason: queue_full | "
            "queued_tokens | kv_pressure | draining",
            ["model_name", "reason"],
            registry=self.registry,
        )
        self.drain_state = gauge(
            "vllm:engine_drain_state",
            "0 serving, 1 draining (admission stopped, in-flight work "
            "finishing), 2 drained (unfinished work journaled/aborted)",
        )
        self.admission_queued_tokens = gauge(
            "vllm:admission_queued_tokens",
            "Prompt tokens queued for admission (waiting requests "
            "awaiting (re-)prefill)",
        )
        # ---- multi-replica identity (ISSUE 10 satellite) ----
        self._replica_info = Gauge(
            "vllm:replica_info",
            "Constant 1; the replica_id label is this serving "
            "replica's stable identity (VDT_REPLICA_ID, default "
            "host:port) so multi-replica dashboards can attribute "
            "series per replica",
            ["model_name", "replica_id"],
            registry=self.registry,
        )
        # ---- control-plane liveness ----
        self._host_up = Gauge(
            "vllm:host_up",
            "1 while the host answers heartbeats, 0 once marked dead",
            ["model_name", "host_rank"],
            registry=self.registry,
        )
        self.heartbeat_latency = histogram(
            "vllm:heartbeat_latency_seconds",
            "Control-plane heartbeat round-trip time per remote host",
            _HEARTBEAT_BUCKETS,
        )
        self._engine_dead = Gauge(
            "vllm:engine_dead_info",
            "1 when the engine is dead; labels carry the failure "
            "attribution (lifecycle phase + offending host)",
            ["model_name", "phase", "host_rank"],
            registry=self.registry,
        )
        # ---- supervised recovery (engine/supervisor.py).  These live in
        # the same EngineMetrics instance, which is carried ACROSS engine
        # rebuilds — counters must not reset when the engine recovers.
        self.engine_restarts = counter(
            "vllm:engine_restarts_total",
            "In-process engine recovery attempts started by the "
            "supervisor (teardown + executor rebuild)",
        )
        self.requests_replayed = counter(
            "vllm:requests_replayed_total",
            "Interrupted requests re-admitted from the request journal "
            "after an engine recovery",
        )
        self.recovery_seconds = histogram(
            "vllm:engine_recovery_seconds",
            "Engine death to recovered-and-replayed, per successful "
            "recovery cycle",
            _RECOVERY_BUCKETS,
        )
        # ---- SLO/goodput accounting (ISSUE 12).  slo_class is a
        # BOUNDED label: SloAccounting sanitizes and caps the class set
        # (overflow folds into "other"), so client-controlled names can
        # never explode series cardinality (vdt-lint VDT009).
        self._slo_requests = Counter(
            "vllm:slo_requests",
            "Finished requests per SLO class (attainment denominator)",
            ["model_name", "slo_class"],
            registry=self.registry,
        )
        self._slo_ttft_attained = Counter(
            "vllm:slo_ttft_attained",
            "Finished requests whose TTFT met the class target "
            "(VDT_SLO_TTFT_MS; no target = trivially attained)",
            ["model_name", "slo_class"],
            registry=self.registry,
        )
        self._slo_itl_attained = Counter(
            "vllm:slo_itl_attained",
            "Finished requests whose WORST inter-token latency met the "
            "class target (VDT_SLO_ITL_MS; no target or single-token "
            "output = trivially attained)",
            ["model_name", "slo_class"],
            registry=self.registry,
        )
        self._goodput_requests = Counter(
            "vllm:goodput_requests",
            "DistServe goodput: requests that completed (stop/length) "
            "within BOTH their TTFT and ITL SLO targets",
            ["model_name", "slo_class"],
            registry=self.registry,
        )
        self._slo_ttft_ms = Histogram(
            "vllm:slo_ttft_ms",
            "TTFT per SLO class, milliseconds (per-class dashboard "
            "view; the fleet-exact merge runs over the /slo log-bucket "
            "histograms)",
            ["model_name", "slo_class"],
            buckets=_SLO_TTFT_MS_BUCKETS,
            registry=self.registry,
        )
        self._slo_itl_ms = Histogram(
            "vllm:slo_itl_ms",
            "Inter-token latency per SLO class, milliseconds",
            ["model_name", "slo_class"],
            buckets=_SLO_ITL_MS_BUCKETS,
            registry=self.registry,
        )
        # ---- XLA/device telemetry (ISSUE 12), fed by the driver's
        # pull of worker DeviceTelemetry snapshots (one representative
        # host: the executor's reply rank).
        self._xla_compiles = Counter(
            "vllm:xla_compiles",
            "jit compiles observed on the reply-rank worker, by the "
            "triggering bucket-shape kind (prefill | decode | spec); a "
            "climbing counter in steady state is a recompile storm",
            ["model_name", "kind"],
            registry=self.registry,
        )
        self.xla_compile_seconds = histogram(
            "vllm:xla_compile_seconds",
            "Wall time of each observed jit compile "
            "(trace+lower+compile+first run)",
            _COMPILE_BUCKETS,
        )
        self.hbm_live_bytes = gauge(
            "vllm:hbm_live_bytes",
            "Live HBM bytes on the reply-rank worker's first device "
            "(memory creep is a gauge, not an OOM post-mortem)",
        )
        self.step_roofline_frac = gauge(
            "vllm:step_roofline_frac",
            "Last step's estimated bytes-touched/second over the "
            "device's peak HBM bandwidth (0 when unknown)",
        )
        # ---- fleet sentinel (ISSUE 20) ----
        self._slo_burn = Gauge(
            "vllm:slo_burn_rate",
            "SLO error-budget burn rate per class and window "
            "(error_rate / (1 - VDT_SLO_OBJECTIVE)); refreshed on "
            "every /metrics render",
            ["model_name", "slo_class", "window"],
            registry=self.registry,
        )
        self.itl_p99_ms = gauge(
            "vllm:itl_p99_ms",
            "p99 inter-token latency across all SLO classes (merged "
            "log-bucket histograms, bucket-representative ms) — the "
            "router's sentinel scrapes this as a per-replica condition "
            "signal",
        )
        from vllm_distributed_tpu.engine.slo import SloAccounting

        self.slo = SloAccounting()
        self._dead_labels: tuple[str, str] | None = None
        self._model_name = model_name

    # ---- engine-loop hooks ----
    def record_queues(
        self, running: int, waiting: int, waiting_tokens: int | None = None
    ) -> None:
        if not self.enabled:
            return
        self.num_running.set(running)
        self.num_waiting.set(waiting)
        if waiting_tokens is not None:
            self.admission_queued_tokens.set(waiting_tokens)

    def record_rejected(self, reason: str) -> None:
        """One admission rejection (typed EngineOverloadedError -> 429)."""
        if self.enabled:
            self._rejected.labels(
                model_name=self._model_name, reason=reason
            ).inc()

    def record_drain_state(self, state: int) -> None:
        if self.enabled:
            self.drain_state.set(state)

    def record_preemptions(self, n: int) -> None:
        if self.enabled and n:
            self.preemptions.inc(n)

    def record_pipeline_break(self) -> None:
        if self.enabled:
            self.pipeline_breaks.inc()

    def record_prompt_tokens(self, n: int) -> None:
        if self.enabled and n:
            self.prompt_tokens.inc(n)

    def record_prefix_cache(
        self, queries: int, hits: int, host_hits: int = 0
    ) -> None:
        """``hits`` is the TOTAL across tiers; ``host_hits`` the
        host-restored share of it (tier="hbm" gets the remainder)."""
        if not self.enabled:
            return
        if queries:
            self.prefix_cache_queries.inc(queries)
        if hits - host_hits > 0:
            self.prefix_cache_hits_hbm.inc(hits - host_hits)
        if host_hits:
            self.prefix_cache_hits_host.inc(host_hits)

    def record_kv_tier(
        self, spilled: int, restored: int, host_bytes: int | None = None
    ) -> None:
        """Page deltas from one step's tier spans + the current host
        occupancy (None leaves the gauge untouched)."""
        if not self.enabled:
            return
        if spilled:
            self.kv_spill_pages.inc(spilled)
        if restored:
            self.kv_restore_pages.inc(restored)
        if host_bytes is not None:
            self.host_kv_bytes.set(host_bytes)

    def record_kv_transfer(
        self, direction: str, pages: int, nbytes: int
    ) -> None:
        """One hand-off chunk batch (ISSUE 15): page-layer count and
        wire bytes, by direction ("out" export / "in" import)."""
        if not self.enabled:
            return
        if direction == "out":
            self.kv_transfer_pages_out.inc(pages)
            self.kv_transfer_bytes_out.inc(nbytes)
        else:
            self.kv_transfer_pages_in.inc(pages)
            self.kv_transfer_bytes_in.inc(nbytes)

    def record_kv_transfer_seconds(self, seconds: float) -> None:
        if self.enabled:
            self.kv_transfer_seconds.observe(max(seconds, 0.0))

    def record_kv_restore_seconds(self, seconds: float) -> None:
        if self.enabled:
            self.kv_restore_seconds.observe(max(seconds, 0.0))

    def record_kv_cache_usage(self, frac: float) -> None:
        if self.enabled:
            self.kv_cache_usage.set(frac)

    def record_spec_decode(self, drafted: int, accepted: int) -> None:
        """Token deltas from one speculative verify step."""
        if not self.enabled:
            return
        if drafted:
            self.spec_draft_tokens.inc(drafted)
        if accepted:
            self.spec_accepted_tokens.inc(accepted)

    def record_spec_acceptance_length(self, num_emitted: int) -> None:
        """Tokens emitted by one request's verify window (1 + accepted)."""
        if self.enabled:
            self.spec_acceptance_length.observe(num_emitted)

    def _slo_class(self, req_metrics) -> str:
        """Resolve (and cache) the request's bounded SLO-class label."""
        cls = req_metrics.slo_class_resolved
        if cls is None:
            cls = self.slo.resolve(req_metrics.slo_class)
            req_metrics.slo_class_resolved = cls
        return cls

    def record_new_tokens(self, req_metrics, n: int, now: float | None = None) -> None:
        """n new tokens for one request: TTFT on the first, ITL after.
        ``now`` and every interval endpoint are MONOTONIC clock reads
        (the *_mono RequestMetrics fields) — an NTP step must never
        produce a negative/garbage TTFT, ITL, or e2e observation."""
        if not self.enabled or n <= 0:
            return
        now = now if now is not None else time.monotonic()
        self.generation_tokens.inc(n)
        cls = self._slo_class(req_metrics)
        last = req_metrics.last_token_time_mono
        if req_metrics.first_token_time_mono is not None and last is None:
            # first batch of tokens for this request
            ttft = (
                req_metrics.first_token_time_mono
                - req_metrics.arrival_time_mono
            )
            self.ttft.observe(ttft)
            self.slo.record_ttft(cls, ttft)
            self._slo_ttft_ms.labels(
                model_name=self._model_name, slo_class=cls
            ).observe(max(ttft, 0.0) * 1000.0)
            n_after_first = n - 1
            # A fused dispatch can deliver the first token WITH its
            # successors: their intervals start at the first token.
            last = req_metrics.first_token_time_mono
        else:
            n_after_first = n
        if last is not None and n_after_first > 0:
            per_tok = max(now - last, 0.0) / n_after_first
            for _ in range(n_after_first):
                self.itl.observe(per_tok)
            # SLO accounting (ISSUE 12): class histogram + the request's
            # own per-bucket tally (what the fleet merge is recomputable
            # from) + worst-interval tracking for the ITL attainment.
            idx = self.slo.record_itl(cls, per_tok, n_after_first)
            buckets = req_metrics.slo_itl_buckets
            if buckets is None:
                buckets = req_metrics.slo_itl_buckets = {}
            buckets[idx] = buckets.get(idx, 0) + n_after_first
            if (
                req_metrics.slo_itl_max_s is None
                or per_tok > req_metrics.slo_itl_max_s
            ):
                req_metrics.slo_itl_max_s = per_tok
            itl_ms = self._slo_itl_ms.labels(
                model_name=self._model_name, slo_class=cls
            )
            for _ in range(n_after_first):
                itl_ms.observe(per_tok * 1000.0)
        req_metrics.last_token_time_mono = now

    def record_replica_info(self, replica_id: str) -> None:
        """Publish this replica's stable identity (API-server boot)."""
        if self.enabled and replica_id:
            self._replica_info.labels(
                model_name=self._model_name, replica_id=replica_id
            ).set(1)

    # ---- control-plane liveness hooks (called from the executor's
    # heartbeat loop and the engine failure callback; every caller
    # tolerates a disabled/None metrics object) ----
    def record_heartbeat(self, host_rank: int, latency: float) -> None:
        if not self.enabled:
            return
        self._host_up.labels(
            model_name=self._model_name, host_rank=str(host_rank)
        ).set(1)
        self.heartbeat_latency.observe(latency)

    def record_host_down(self, host_rank: int) -> None:
        if not self.enabled:
            return
        self._host_up.labels(
            model_name=self._model_name, host_rank=str(host_rank)
        ).set(0)

    def record_engine_dead(self, failure) -> None:
        """`failure` is a HostFailure or None (non-control-plane death)."""
        if not self.enabled:
            return
        phase = failure.phase if failure is not None else "unknown"
        host = str(failure.host_rank) if failure is not None else ""
        self._dead_labels = (phase, host)
        self._engine_dead.labels(
            model_name=self._model_name, phase=phase, host_rank=host
        ).set(1)

    # ---- supervised recovery hooks ----
    def record_restart(self) -> None:
        if self.enabled:
            self.engine_restarts.inc()

    def record_replayed(self, n: int) -> None:
        if self.enabled and n:
            self.requests_replayed.inc(n)

    def record_recovery_seconds(self, seconds: float) -> None:
        if self.enabled:
            self.recovery_seconds.observe(seconds)

    def record_engine_recovered(self) -> None:
        """Clear the dead gauge set by record_engine_dead (same label
        set, so dashboards see the incident close, not a new series)."""
        if not self.enabled or self._dead_labels is None:
            return
        phase, host = self._dead_labels
        self._engine_dead.labels(
            model_name=self._model_name, phase=phase, host_rank=host
        ).set(0)

    def record_finished(self, req_metrics, reason: str | None) -> None:
        if not self.enabled:
            return
        if req_metrics.finished_time_mono is not None:
            # Monotonic interval: immune to wall-clock (NTP) steps.
            self.e2e_latency.observe(
                req_metrics.finished_time_mono
                - req_metrics.arrival_time_mono
            )
        elif req_metrics.finished_time is not None:
            self.e2e_latency.observe(
                max(req_metrics.finished_time - req_metrics.arrival_time, 0.0)
            )
        self._success.labels(
            model_name=self._model_name, finished_reason=reason or "unknown"
        ).inc()
        # SLO/goodput accounting (ISSUE 12): attainment of this
        # request's class targets, from the same monotonic stamps.
        cls = self._slo_class(req_metrics)
        ttft_s = req_metrics.ttft
        ttft_ok, itl_ok, good = self.slo.record_finished(
            cls,
            ttft_s,
            req_metrics.slo_itl_max_s,
            req_metrics.slo_itl_buckets,
            reason,
        )
        labels = dict(model_name=self._model_name, slo_class=cls)
        self._slo_requests.labels(**labels).inc()
        if ttft_ok:
            self._slo_ttft_attained.labels(**labels).inc()
        if itl_ok:
            self._slo_itl_attained.labels(**labels).inc()
        if good:
            self._goodput_requests.labels(**labels).inc()
        # Fleet sentinel (ISSUE 20): cumulative (requests, goodput)
        # feeds the multi-window burn tracker; a paired-window breach
        # enters the timeline.
        requests, goodput = self.slo.class_counts(cls)
        for fired in self.burn.observe(cls, requests, goodput):
            self.events.emit("alert_slo_burn", **fired)

    # ---- XLA/device telemetry hooks (ISSUE 12), fed by
    # LLMEngine.refresh_device_telemetry from worker snapshots ----
    def record_xla_compiles(self, kind: str, n: int) -> None:
        """Counter fed from CUMULATIVE per-kind worker totals (delta
        computed by the engine), so a recompile storm that overflows
        the bounded event ring between scrapes still counts exactly."""
        if self.enabled and n > 0:
            self._xla_compiles.labels(
                model_name=self._model_name, kind=kind
            ).inc(n)

    def record_xla_compile_seconds(self, seconds: float) -> None:
        """Histogram fed from individual timed events (best-effort: the
        event ring is bounded, the counter above is the exact tally)."""
        if self.enabled:
            self.xla_compile_seconds.observe(max(seconds, 0.0))

    def record_device_snapshot(self, snap: dict) -> None:
        """Gauges from one worker DeviceTelemetry snapshot (compile
        events are folded separately so each is counted exactly once)."""
        if not self.enabled:
            return
        self.hbm_live_bytes.set(snap.get("hbm_live_bytes", 0) or 0)
        self.step_roofline_frac.set(snap.get("roofline_frac", 0.0) or 0.0)

    def slo_snapshot(self, include_timelines: bool = True) -> dict | None:
        """Replica /slo view (None while metrics are disabled)."""
        if not self.enabled:
            return None
        return self.slo.snapshot(include_timelines=include_timelines)

    def observe_span(self, name: str, duration: float) -> None:
        """Tracer metrics sink (tracing.Tracer.set_metrics_sink): every
        completed local span whose name maps to a per-stage histogram
        feeds it, so /metrics and /debug/traces share one measurement."""
        if not self.enabled:
            return
        attr = SPAN_METRIC_MAP.get(name)
        if attr is not None:
            getattr(self, attr).observe(max(duration, 0.0))

    def render(self) -> bytes:
        """Prometheus text exposition of this engine's registry."""
        if self.registry is None:
            return b"# metrics disabled (--disable-log-stats)\n"
        from prometheus_client import generate_latest

        # Sentinel gauges (ISSUE 20) are scrape-time views of the SLO
        # accounting, not event-driven — refresh them per render so the
        # burn decays as the windows slide even with no new requests.
        p99 = self.slo.itl_p99_ms()
        if p99 is not None:
            self.itl_p99_ms.set(p99)
        for cls, rates in self.burn.snapshot().items():
            for window, value in rates.items():
                self._slo_burn.labels(
                    model_name=self._model_name,
                    slo_class=cls,
                    window=window,
                ).set(value)
        return generate_latest(self.registry)
