"""End-to-end request tracing (ISSUE 5 tentpole).

A zero-hard-dependency tracer answering "where did this request's
800 ms go?": W3C-style 128-bit trace ids, parent/child spans, and a
bounded ring buffer of completed traces, threaded through every layer a
request crosses (API server → engine → scheduler → executor → RPC →
worker).  Upstream vLLM ships per-request OpenTelemetry traces next to
its Prometheus metrics for the same reason (Kwon et al. 2023); Llumnix
(Sun et al. 2024) shows per-request latency telemetry is the raw input
any scheduling/migration layer needs.

Design rules:

- **No-op fast path.**  With tracing disabled (the default), ``span()``
  returns a module-level singleton and ``record_span``/``event`` return
  immediately — the hot loop allocates nothing.
- **No hard deps.**  Pure stdlib.  OTLP export engages only when the
  ``opentelemetry-sdk`` package is installed (degrading silently, like
  ``prometheus_client`` in metrics.py).
- **Spans cross the RPC boundary.**  ``distributed/rpc.py`` embeds the
  current trace context in apply frames and ships the worker-side spans
  back inside the reply frame; ``adopt()`` merges them into the local
  trace, shifting timestamps by the per-host clock offset the executor
  estimates from heartbeat RTTs.
- **Wall clock only for span starts.**  Durations come from
  ``time.monotonic()`` deltas, so an NTP step can skew where a trace
  sits on the absolute timeline but never the shape of the spans.
- **Metrics feed from span data.**  A single sink (EngineMetrics) sees
  every completed local span, so the per-stage Prometheus histograms
  and the traces can never disagree.

Config: ``ObservabilityConfig.enable_tracing`` (CLI ``--enable-tracing``)
or ``VDT_TRACING=1``; ring size via ``VDT_TRACE_RING_SIZE``.
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable

from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)

# (trace_id, span_id) — the wire-format trace context.  Plain tuples so
# they pickle into RPC frames and dataclasses without ceremony.
TraceContext = tuple  # tuple[str, str]

# Active span context of the current thread/task; read by the RPC layer
# when building apply frames, set by Span.__enter__.
_current: contextvars.ContextVar[TraceContext | None] = contextvars.ContextVar(
    "vdt_trace_ctx", default=None
)


def current_ctx() -> TraceContext | None:
    return _current.get()


def new_trace_id() -> str:
    return os.urandom(16).hex()  # 128-bit, W3C trace-id sized


def new_span_id() -> str:
    return os.urandom(8).hex()


class _NoopSpan:
    """Shared do-nothing span: the disabled-tracing fast path returns
    this singleton, so opening a span allocates nothing."""

    __slots__ = ()
    ctx = None

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set_attribute(self, key: str, value: Any) -> None:
        pass

    def end(self) -> None:
        pass

    def to_wire(self) -> dict:
        return {}


NOOP_SPAN = _NoopSpan()


class Span:
    """One timed operation.  Use as a context manager (``with
    tracer.span(...)``); the code-hygiene suite bans orphanable manual
    ``start_span`` calls outside a ``with``."""

    __slots__ = (
        "name",
        "trace_id",
        "span_id",
        "parent_id",
        "start",
        "duration",
        "host",
        "attributes",
        "_tracer",
        "_t0",
        "_token",
        "_record",
    )

    def __init__(
        self,
        tracer: "Tracer",
        name: str,
        trace_id: str,
        parent_id: str | None,
        host: str,
        attributes: dict,
        record: bool,
    ) -> None:
        self.name = name
        self.trace_id = trace_id
        self.span_id = new_span_id()
        self.parent_id = parent_id
        self.host = host
        self.attributes = attributes
        self.start = time.time()
        self.duration: float | None = None
        self._tracer = tracer
        self._t0 = time.monotonic()
        self._token: contextvars.Token | None = None
        self._record = record

    @property
    def ctx(self) -> TraceContext:
        return (self.trace_id, self.span_id)

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def __enter__(self) -> "Span":
        self._token = _current.set(self.ctx)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            self.attributes["error"] = exc_type.__name__
        self.end()
        return False

    def end(self) -> None:
        if self.duration is not None:
            return  # already ended
        self.duration = time.monotonic() - self._t0
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        self._tracer._close(self)

    def to_wire(self) -> dict:
        """Serializable form, shipped inside RPC reply frames and served
        from /debug/traces."""
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "host": self.host,
            "start": self.start,
            "duration": self.duration,
            "attributes": self.attributes,
        }


class _Trace:
    """All spans of one trace, accumulated until the root span closes."""

    __slots__ = ("trace_id", "spans", "root_span_id", "done")

    def __init__(self, trace_id: str) -> None:
        self.trace_id = trace_id
        self.spans: list[dict] = []
        self.root_span_id: str | None = None
        self.done = False

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "root_span_id": self.root_span_id,
            "complete": self.done,
            "spans": list(self.spans),
        }


class Tracer:
    """Process-global tracer.  Thread-safe: spans are opened/closed on
    the event loop, the engine thread, executor loop and gather pool."""

    def __init__(self) -> None:
        self.enabled = False
        self.host = "driver"
        self._lock = threading.Lock()
        self._active: dict[str, _Trace] = {}
        self._ring: deque[_Trace] = deque(maxlen=256)
        self._finished: dict[str, _Trace] = {}
        self._open_spans = 0
        # host -> (wall-clock offset vs this process, rtt of the sample)
        self._clock_offsets: dict[str, tuple[float, float]] = {}
        self._metrics_sink: Callable[[str, float], None] | None = None
        self._otlp = None  # lazily resolved exporter, or False

    # ---- configuration ----
    def configure(
        self,
        enabled: bool,
        ring_size: int | None = None,
        host: str | None = None,
    ) -> "Tracer":
        self.enabled = enabled
        if host is not None:
            self.host = host
        if ring_size is not None and ring_size != self._ring.maxlen:
            with self._lock:
                self._ring = deque(self._ring, maxlen=max(1, ring_size))
                # Shrinking evicts the oldest traces from the deque; the
                # id index must follow or get_trace() keeps resurrecting
                # (and retaining) traces snapshot() no longer lists.
                self._finished = {t.trace_id: t for t in self._ring}
        return self

    def set_metrics_sink(
        self, sink: Callable[[str, float], None] | None
    ) -> None:
        """Single slot (not a list): the engine re-registers the same
        EngineMetrics across supervisor rebuilds without stacking."""
        self._metrics_sink = sink

    def clear_metrics_sink(self, sink: Callable[[str, float], None]) -> None:
        """Detach ``sink`` if it is the installed one (engine shutdown
        must not keep its EngineMetrics alive through the global tracer);
        a newer engine's sink is left in place."""
        if self._metrics_sink == sink:
            self._metrics_sink = None

    def reset(self) -> None:
        with self._lock:
            self._active.clear()
            self._finished.clear()
            self._ring.clear()
            self._open_spans = 0
            self._clock_offsets.clear()

    # ---- span creation ----
    def span(
        self,
        name: str,
        parent: TraceContext | None = None,
        trace_root: bool = False,
        record: bool = True,
        **attributes: Any,
    ):
        """Open a span as a context manager.  ``parent`` is an explicit
        (trace_id, span_id); None inherits the calling context.  A span
        with neither a parent nor ``trace_root`` is dropped (no-op) —
        untraced work stays untraced.  ``record=False`` spans are not
        stored locally (the worker side ships them back to the driver
        instead of accumulating orphan traces)."""
        if not self.enabled:
            return NOOP_SPAN
        if parent is None and not trace_root:
            parent = _current.get()
            if parent is None:
                return NOOP_SPAN
        trace_id = new_trace_id() if parent is None else parent[0]
        parent_id = None if parent is None else parent[1]
        span = Span(
            self, name, trace_id, parent_id, self.host, attributes, record
        )
        with self._lock:
            self._open_spans += 1
        return span

    # Manual open; must be paired with .end() under try/finally.  The
    # code-hygiene AST check bans calls outside a `with` so spans cannot
    # leak open — prefer span().
    start_span = span

    def record_span(
        self,
        name: str,
        start: float,
        duration: float,
        parent: TraceContext | None = None,
        **attributes: Any,
    ) -> None:
        """Record an already-measured interval (start: wall clock,
        duration: monotonic delta).  Never 'open', so it cannot leak.
        Feeds the metrics sink even without a trace context, so stage
        histograms populate for untraced engine-level callers too."""
        if not self.enabled:
            return
        self._sink(name, duration)
        if parent is None:
            return
        self._store(
            {
                "name": name,
                "trace_id": parent[0],
                "span_id": new_span_id(),
                "parent_id": parent[1],
                "host": self.host,
                "start": start,
                "duration": duration,
                "attributes": attributes,
            }
        )

    def event(
        self, ctx: TraceContext | None, name: str, **attributes: Any
    ) -> None:
        """Instant event (zero-duration span) on an existing trace."""
        if not self.enabled or ctx is None:
            return
        self._store(self.stamp(name, ctx, **attributes))

    def stamp(
        self, name: str, parent: TraceContext, **attributes: Any
    ) -> dict:
        """Build (without storing) an instant-span dict — used for the
        worker-side reply marker shipped inside the RPC result frame."""
        return {
            "name": name,
            "trace_id": parent[0],
            "span_id": new_span_id(),
            "parent_id": parent[1],
            "host": self.host,
            "start": time.time(),
            "duration": None,
            "attributes": attributes,
        }

    # ---- cross-host ----
    def set_clock_offset(self, host: str, offset: float, rtt: float) -> None:
        """Record one (remote wall − local wall) sample.  Low-RTT samples
        are the trustworthy ones; a stored sample slowly decays so a
        fresh estimate eventually wins even if its RTT is worse."""
        with self._lock:
            cur = self._clock_offsets.get(host)
            if cur is None or rtt <= cur[1] * 1.25:
                self._clock_offsets[host] = (offset, rtt)
            else:
                self._clock_offsets[host] = (cur[0], cur[1] * 1.05)

    def clock_offset(self, host: str) -> float:
        with self._lock:
            cur = self._clock_offsets.get(host)
        return 0.0 if cur is None else cur[0]

    def adopt(self, spans: list[dict]) -> None:
        """Merge spans recorded on another host (shipped back inside an
        RPC reply) into their trace, mapping remote wall clocks onto the
        local timeline via the estimated per-host offset."""
        if not self.enabled:
            return
        for span in spans:
            if not isinstance(span, dict) or "trace_id" not in span:
                continue
            offset = self.clock_offset(span.get("host", ""))
            if offset:
                span = dict(span)
                span["start"] = span["start"] - offset
            self._store(span)

    # ---- storage ----
    def _close(self, span: Span) -> None:
        with self._lock:
            self._open_spans = max(self._open_spans - 1, 0)
        self._sink(span.name, span.duration or 0.0)
        if not span._record:
            return
        wire = span.to_wire()
        is_root = span.parent_id is None
        with self._lock:
            trace = self._trace_for(span.trace_id)
            trace.spans.append(wire)
            if is_root:
                trace.root_span_id = span.span_id
                self._finalize(trace)
        if is_root:
            self._export_otlp(trace)

    def _store(self, wire: dict) -> None:
        with self._lock:
            self._trace_for(wire["trace_id"]).spans.append(wire)

    def _trace_for(self, trace_id: str) -> _Trace:
        """Lock held.  Finished traces still accept late spans (a
        pipelined gather can outlive the request's root span)."""
        trace = self._finished.get(trace_id)
        if trace is not None:
            return trace
        trace = self._active.get(trace_id)
        if trace is None:
            trace = _Trace(trace_id)
            self._active[trace_id] = trace
            # Bound the active set: a trace whose root never closes
            # (engine-level caller, crashed request) must not leak.
            while len(self._active) > max(self._ring.maxlen or 1, 64):
                _, oldest = next(iter(self._active.items()))
                del self._active[oldest.trace_id]
                self._finalize(oldest)
        return trace

    def _finalize(self, trace: _Trace) -> None:
        """Lock held: move a trace to the completed ring.  Idempotent:
        a trace force-evicted from the active set (overflow) whose root
        span closes later must not enter the ring twice."""
        if self._finished.get(trace.trace_id) is trace:
            trace.done = trace.done or trace.root_span_id is not None
            return
        trace.done = trace.root_span_id is not None
        self._active.pop(trace.trace_id, None)
        if len(self._ring) == self._ring.maxlen:
            evicted = self._ring[0]
            self._finished.pop(evicted.trace_id, None)
        self._ring.append(trace)
        self._finished[trace.trace_id] = trace

    def _sink(self, name: str, duration: float) -> None:
        sink = self._metrics_sink
        if sink is not None:
            try:
                sink(name, duration)
            except Exception as e:  # noqa: BLE001 — telemetry only
                logger.debug("metrics sink failed for %s: %s", name, e)

    # ---- introspection ----
    @property
    def num_open_spans(self) -> int:
        return self._open_spans

    def snapshot(self, limit: int | None = None) -> list[dict]:
        """Recent completed traces, oldest first."""
        with self._lock:
            traces = list(self._ring)
        if limit is not None:
            traces = traces[-limit:]
        return [t.to_dict() for t in traces]

    def get_trace(self, trace_id: str) -> dict | None:
        with self._lock:
            trace = self._finished.get(trace_id) or self._active.get(
                trace_id
            )
            return None if trace is None else trace.to_dict()

    def to_chrome(self, limit: int | None = None) -> dict:
        """Chrome trace-event format (loads directly in Perfetto /
        chrome://tracing): complete events ('X') for spans, instant
        events ('i') for zero-duration markers, with process-name
        metadata mapping pids to hosts."""
        events: list[dict] = []
        hosts: dict[str, int] = {}
        for trace in self.snapshot(limit):
            tid = int(trace["trace_id"][:8], 16) & 0x7FFFFFFF
            for span in trace["spans"]:
                pid = hosts.setdefault(span["host"], len(hosts) + 1)
                args = dict(span["attributes"])
                args.update(
                    trace_id=span["trace_id"],
                    span_id=span["span_id"],
                    parent_id=span["parent_id"],
                )
                event = {
                    "name": span["name"],
                    "cat": "vdt",
                    "pid": pid,
                    "tid": tid,
                    "ts": span["start"] * 1e6,
                    "args": args,
                }
                if span["duration"] is None:
                    event.update(ph="i", s="t")
                else:
                    event.update(ph="X", dur=span["duration"] * 1e6)
                events.append(event)
        for host, pid in hosts.items():
            events.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": 0,
                    "args": {"name": host},
                }
            )
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def to_chrome_json(self, limit: int | None = None) -> str:
        return json.dumps(self.to_chrome(limit))

    # ---- optional OTLP export ----
    def _export_otlp(self, trace: _Trace) -> None:
        """Best-effort OTLP export of a completed trace.  Engages only
        when the opentelemetry *SDK* is installed (the bare -api package
        is not enough); otherwise degrades silently, exactly like
        metrics.py does without prometheus_client.  VDT_TRACE_OTLP=0
        disables even with the SDK present."""
        if self._otlp is None:
            self._otlp = self._init_otlp()
        if not self._otlp:
            return
        try:
            self._otlp(trace)
        except Exception as e:  # noqa: BLE001 — telemetry only
            logger.debug("OTLP export failed: %s", e)

    def _init_otlp(self):
        from vllm_distributed_tpu import envs

        if not envs.VDT_TRACE_OTLP:
            return False
        try:
            from opentelemetry.sdk.resources import Resource
            from opentelemetry.sdk.trace import TracerProvider
            from opentelemetry.sdk.trace.export import (
                BatchSpanProcessor,
            )
            from opentelemetry.exporter.otlp.proto.http.trace_exporter import (
                OTLPSpanExporter,
            )
        except ImportError:
            return False
        provider = TracerProvider(
            resource=Resource.create(
                {"service.name": "vllm-distributed-tpu"}
            )
        )
        provider.add_span_processor(BatchSpanProcessor(OTLPSpanExporter()))
        otel_tracer = provider.get_tracer("vdt")

        def export(trace: _Trace) -> None:
            # Re-play the finished spans through the SDK; otel assigns
            # its own ids, so the original ids ride along as attributes.
            for span in trace.spans:
                start_ns = int(span["start"] * 1e9)
                end_ns = start_ns + int((span["duration"] or 0.0) * 1e9)
                otel_span = otel_tracer.start_span(
                    span["name"], start_time=start_ns
                )
                try:
                    for k, v in span["attributes"].items():
                        otel_span.set_attribute(str(k), str(v))
                    otel_span.set_attribute("vdt.trace_id", span["trace_id"])
                    otel_span.set_attribute("vdt.host", span["host"])
                finally:
                    otel_span.end(end_time=end_ns)

        return export


_tracer = Tracer()


def get_tracer() -> Tracer:
    return _tracer


def configure_from_env(host: str | None = None) -> Tracer:
    """Configure the global tracer from VDT_TRACING/VDT_TRACE_RING_SIZE
    (worker agents call this after the driver replicates its env)."""
    from vllm_distributed_tpu import envs

    return _tracer.configure(
        enabled=envs.VDT_TRACING,
        ring_size=envs.VDT_TRACE_RING_SIZE,
        host=host,
    )
