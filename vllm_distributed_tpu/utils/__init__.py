"""Small shared utilities (network, math, id counters)."""

from __future__ import annotations

import asyncio
import contextlib
import inspect
import itertools
import socket
from typing import Any


def cdiv(a: int, b: int) -> int:
    return -(-a // b)


def round_up(x: int, multiple: int) -> int:
    return cdiv(x, multiple) * multiple


def next_power_of_2(x: int) -> int:
    if x <= 1:
        return 1
    return 1 << (x - 1).bit_length()


def get_ip() -> str:
    """Best-effort primary IP of this host (reference: launch.py:94 uses
    vllm's get_ip for the collective rendezvous address)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        # Does not actually send packets; picks the interface that would
        # route to a public address.
        s.connect(("8.8.8.8", 80))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def get_open_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def get_distributed_init_method(ip: str, port: int) -> str:
    """Coordinator address for jax.distributed.initialize (the analog of the
    torch rendezvous minted at launch.py:94)."""
    return f"{ip}:{port}"


class Counter:
    """Monotonic id generator."""

    def __init__(self, start: int = 0) -> None:
        self._start = start
        self._it = itertools.count(start)

    def __next__(self) -> int:
        return next(self._it)

    def reset(self) -> None:
        self._it = itertools.count(self._start)


async def maybe_await(value: Any) -> Any:
    """Await if awaitable, else pass through (reference rpc.py maybe_await)."""
    if inspect.isawaitable(value):
        return await value
    return value


def run_method(obj: Any, method: str | Any, args: tuple, kwargs: dict) -> Any:
    """Dispatch a method on obj by string name or callable (the contract of
    vLLM's run_method used at launch.py:529)."""
    if isinstance(method, str):
        func = getattr(obj, method)
    else:
        func = method.__get__(obj, obj.__class__)
    return func(*args, **kwargs)


@contextlib.contextmanager
def cancel_task_on_exit(task: asyncio.Task):
    try:
        yield task
    finally:
        task.cancel()
