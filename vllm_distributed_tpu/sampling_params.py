"""Per-request sampling parameters.

Capability parity with the sampling surface the reference exposes through
the OpenAI API it serves (SURVEY.md §2.3: EngineClient.generate).  Kept
deliberately small and TPU-friendly: every knob here lowers to a vectorized
operation inside the jitted sampling program (ops/sampling.py) — no
per-request Python in the hot loop.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class SamplingParams:
    n: int = 1
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = -1  # -1 = disabled
    min_p: float = 0.0
    max_tokens: int | None = 16
    min_tokens: int = 0
    stop: list[str] = field(default_factory=list)
    stop_token_ids: list[int] = field(default_factory=list)
    ignore_eos: bool = False
    repetition_penalty: float = 1.0
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    logprobs: int | None = None
    seed: int | None = None
    # Greedy iff temperature == 0.
    detokenize: bool = True
    include_stop_str_in_output: bool = False
    # Per-request deadline in milliseconds from arrival (client-supplied
    # via the deadline_ms body field or X-VDT-Deadline-Ms header); None
    # falls back to the server default (SchedulerConfig
    # default_deadline_ms, 0 = no deadline).  An expired waiting request
    # is shed before prefill; an expired running request finishes with
    # finish_reason="timeout" and partial output.
    deadline_ms: int | None = None
    # SLO class for goodput accounting (ISSUE 12; slo_class body field /
    # X-VDT-SLO-Class header).  Keys the per-class attainment counters
    # and log-bucket histograms against the VDT_SLO_TTFT_MS /
    # VDT_SLO_ITL_MS targets; sanitized and cardinality-bounded by
    # engine/slo.py before it becomes a metric label.
    slo_class: str = "default"
    # Disaggregated prefill (ISSUE 15, internal — set by the replica's
    # API layer on the router's X-VDT-Disagg hop, never by clients):
    # the request runs prefill plus its first sampled token, then
    # finishes with its KV pages HELD for export (engine/kv_transfer.py)
    # instead of freed, so the router can stream them to a decode-pool
    # replica and resume there.
    prefill_only: bool = False

    def __post_init__(self) -> None:
        if self.temperature < 0.0:
            raise ValueError(f"temperature must be >= 0, got {self.temperature}")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if self.top_k < -1 or self.top_k == 0:
            raise ValueError(f"top_k must be -1 or positive, got {self.top_k}")
        if self.max_tokens is not None and self.max_tokens < 1:
            raise ValueError(f"max_tokens must be >= 1, got {self.max_tokens}")
        if self.n < 1:
            raise ValueError(f"n must be >= 1, got {self.n}")
        if not 0.0 <= self.min_p <= 1.0:
            raise ValueError(f"min_p must be in [0, 1], got {self.min_p}")
        if self.deadline_ms is not None and self.deadline_ms < 1:
            raise ValueError(
                f"deadline_ms must be >= 1, got {self.deadline_ms}"
            )

    @property
    def is_greedy(self) -> bool:
        return self.temperature == 0.0

    def clone(self) -> "SamplingParams":
        return SamplingParams(
            n=self.n,
            temperature=self.temperature,
            top_p=self.top_p,
            top_k=self.top_k,
            min_p=self.min_p,
            max_tokens=self.max_tokens,
            min_tokens=self.min_tokens,
            stop=list(self.stop),
            stop_token_ids=list(self.stop_token_ids),
            ignore_eos=self.ignore_eos,
            repetition_penalty=self.repetition_penalty,
            presence_penalty=self.presence_penalty,
            frequency_penalty=self.frequency_penalty,
            logprobs=self.logprobs,
            seed=self.seed,
            detokenize=self.detokenize,
            include_stop_str_in_output=self.include_stop_str_in_output,
            deadline_ms=self.deadline_ms,
            slo_class=self.slo_class,
            prefill_only=self.prefill_only,
        )
