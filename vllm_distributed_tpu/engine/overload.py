"""Overload resilience: bounded admission + KV backpressure + drain
state (ISSUE 8 tentpole).

The engine before this module accepted unbounded work: the scheduler's
waiting deque and the AsyncLLM intake grew without limit, and nothing
shed load before HBM pages or the API process fell over.  vLLM (Kwon et
al. 2023) treats KV watermarks and preempt-to-recompute as first-class
admission signals; Llumnix (Sun et al. 2024) shows that a *drain*
primitive — stop admitting, finish or hand off in-flight work — is the
building block for multi-replica routing and live migration.  This
module is the admission side of both.

``AdmissionController`` runs on the event loop (called from
``AsyncLLM.generate`` before anything is enqueued) and answers one
question cheaply: *may this request enter the building?*  It consults

- its own pending counters (adds accepted but not yet consumed by the
  engine thread's intake drain),
- the scheduler's waiting-queue snapshot (`len()` and an integer token
  counter — both single reads, safe under the GIL against the engine
  thread's mutations),
- the allocator's free-page count against a configurable watermark,
  with a prefix-cache-aware estimate of the prompt's page demand, and
- the drain state.

Every reject raises a typed ``EngineOverloadedError`` carrying the
machine-readable reason — the HTTP layer maps it to 429 + Retry-After,
*distinct* from the PR 2/3 ``EngineDeadError``/``EngineRecoveringError``
503 states: overload clears in inter-token time, a dead engine in
restart time, and load balancers must tell them apart.

All checks are **default-off**: with every cap at 0 the controller's
fast path is a single drain-flag read and the seed behavior is
byte-for-byte unchanged.
"""

from __future__ import annotations

import threading

from vllm_distributed_tpu.engine.qos import QosRegistry
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.utils import cdiv

logger = init_logger(__name__)

# Drain states surfaced by /health and the vllm:engine_drain_state
# gauge.
DRAIN_SERVING = 0
DRAIN_DRAINING = 1
DRAIN_DRAINED = 2

DRAIN_STATE_NAMES = {
    DRAIN_SERVING: "serving",
    DRAIN_DRAINING: "draining",
    DRAIN_DRAINED: "drained",
}


class EngineOverloadedError(RuntimeError):
    """The engine is shedding load: admission was rejected (or an
    admitted request was shed) because a configured cap, watermark, or
    the drain state says accepting it would make things worse.  Maps to
    HTTP 429 + Retry-After.  ``reason`` is machine-readable:
    queue_full | queued_tokens | kv_pressure | draining | overloaded.
    """

    def __init__(
        self, message: str, reason: str = "overloaded", retry_after: int = 1
    ) -> None:
        super().__init__(message)
        self.reason = reason
        self.retry_after = retry_after


class AdmissionController:
    """Bounded admission for one AsyncLLM.  Event-loop side owns
    reserve/release; the engine thread calls ``consumed`` when an add
    leaves the intake.  Counters use a lock (cheap: admission is
    per-request, not per-token) so reserve/consumed interleavings can't
    lose a decrement."""

    def __init__(self, scheduler_config, retry_after: int = 1) -> None:
        self.config = scheduler_config
        self.retry_after = retry_after
        self._lock = threading.Lock()
        # Adds accepted by reserve() but not yet consumed by the engine
        # thread's intake drain (the scheduler can't see them yet).
        self._pending_requests = 0
        self._pending_tokens = 0
        # Per-class mirrors of the pending counters, maintained only
        # when the QoS registry is enabled (ISSUE 16).  Keys are
        # registry-resolved class names, so the dicts are bounded by
        # MAX_CLASSES no matter what strings requests carry.
        self.qos = QosRegistry.parse(
            getattr(scheduler_config, "qos_classes", "")
        )
        self._pending_by_class: dict[str, int] = {}
        self._pending_tokens_by_class: dict[str, int] = {}
        self._drain_state = DRAIN_SERVING
        # Bound by the engine thread after boot; None while unwired
        # (checks degrade to caps-only, no scheduler snapshot).
        self._scheduler = None

    # ---- wiring ----
    def attach_scheduler(self, scheduler) -> None:
        """Point the controller at the (possibly rebuilt) scheduler.
        Reads of its waiting len / token counter / allocator free count
        are single attribute+int reads, GIL-atomic against the engine
        thread."""
        self._scheduler = scheduler

    # ---- drain state ----
    @property
    def drain_state(self) -> int:
        return self._drain_state

    @property
    def drain_state_name(self) -> str:
        return DRAIN_STATE_NAMES[self._drain_state]

    @property
    def draining(self) -> bool:
        return self._drain_state != DRAIN_SERVING

    def begin_drain(self) -> None:
        self._drain_state = DRAIN_DRAINING

    def finish_drain(self) -> None:
        self._drain_state = DRAIN_DRAINED

    # ---- admission ----
    def _overloaded(self, reason: str, detail: str) -> EngineOverloadedError:
        return EngineOverloadedError(
            f"engine overloaded ({reason}): {detail}",
            reason=reason,
            retry_after=self.retry_after,
        )

    def pending(self) -> tuple[int, int]:
        with self._lock:
            return self._pending_requests, self._pending_tokens

    def queue_depth(self) -> int:
        """Admission-queue depth: scheduler waiting + intake pending."""
        sched = self._scheduler
        waiting = len(sched.waiting) if sched is not None else 0
        return waiting + self._pending_requests

    def queued_tokens(self) -> int:
        sched = self._scheduler
        base = sched.num_waiting_tokens if sched is not None else 0
        return base + self._pending_tokens

    def class_queue_depth(self, name: str) -> int:
        """One class's admission-queue depth (scheduler + pending)."""
        sched = self._scheduler
        waiting = 0
        if sched is not None:
            waiting = getattr(sched, "waiting_by_class", {}).get(name, 0)
        return waiting + self._pending_by_class.get(name, 0)

    def class_queued_tokens(self, name: str) -> int:
        sched = self._scheduler
        base = 0
        if sched is not None:
            base = getattr(sched, "waiting_tokens_by_class", {}).get(
                name, 0
            )
        return base + self._pending_tokens_by_class.get(name, 0)

    def _admit_shared(
        self,
        cap: int,
        total: int,
        new: int,
        slo_class: str | None,
        class_usage,
    ) -> bool:
        """Guaranteed-minimum share admission (QoS enabled only).

        A class admits if it fits inside its own guaranteed slice of
        the cap (``share * cap``) OR the whole queue still has spare
        capacity to borrow (work-conserving: guarantees never idle the
        cap when one class is the only traffic).  Under sustained
        overload the borrow clause fails for everyone and only classes
        inside their guarantee keep admitting — so the 429s land on
        the over-share / zero-share (low-priority) classes first.  The
        guarantee clause can overshoot the cap, but by at most
        ``sum(shares) * cap`` (shares sum <= 1 by construction), so
        the queue stays bounded at 2x the configured cap worst-case.
        """
        if total + new <= cap:
            return True  # spare capacity: borrow, no questions asked
        qc = self.qos.resolve(slo_class)
        if qc.admission_share <= 0.0:
            return False
        guaranteed = int(qc.admission_share * cap)
        return class_usage(qc.name) + new <= guaranteed

    def _check(
        self,
        num_requests: int,
        est_tokens: int,
        prompt_token_ids: list[int] | None = None,
        slo_class: str | None = None,
    ) -> EngineOverloadedError | None:
        """The decision, caps-first (cheapest signals first).  Returns
        the reject to raise, or None to admit."""
        if self.draining:
            return self._overloaded(
                "draining",
                "engine is draining; not admitting new requests",
            )
        cfg = self.config
        qos_on = self.qos.enabled
        if cfg.max_waiting_requests > 0:
            depth = self.queue_depth()
            admit = (
                self._admit_shared(
                    cfg.max_waiting_requests,
                    depth,
                    num_requests,
                    slo_class,
                    self.class_queue_depth,
                )
                if qos_on
                else depth + num_requests <= cfg.max_waiting_requests
            )
            if not admit:
                return self._overloaded(
                    "queue_full",
                    f"admission queue holds {depth} request(s), cap is "
                    f"{cfg.max_waiting_requests}",
                )
        if cfg.max_queued_tokens > 0:
            queued = self.queued_tokens()
            admit = (
                self._admit_shared(
                    cfg.max_queued_tokens,
                    queued,
                    est_tokens,
                    slo_class,
                    self.class_queued_tokens,
                )
                if qos_on
                else queued + est_tokens <= cfg.max_queued_tokens
            )
            if not admit:
                return self._overloaded(
                    "queued_tokens",
                    f"{queued} prompt token(s) queued, cap is "
                    f"{cfg.max_queued_tokens}",
                )
        if cfg.kv_admission_watermark > 0.0:
            err = self._check_kv(
                num_requests, est_tokens, prompt_token_ids
            )
            if err is not None:
                return err
        return None

    def _check_kv(
        self,
        num_requests: int,
        est_tokens: int,
        prompt_token_ids: list[int] | None,
    ) -> EngineOverloadedError | None:
        """Free-page watermark: would admitting this work leave less
        than the watermark fraction of usable pages free?  The estimate
        is prefix-cache-aware — tokens already resident as cached pages
        cost nothing to admit.  ``est_tokens`` is the TOTAL over
        ``num_requests`` sequences (n>1 choices each allocate their own
        pages, sharing nothing but a possible cached prefix)."""
        sched = self._scheduler
        if sched is None:
            return None
        alloc = sched.allocator
        usable = alloc.num_pages - 1  # page 0 reserved
        if usable <= 0:
            return None
        n = max(num_requests, 1)
        per_req = est_tokens // n
        cached = alloc.estimate_cached_tokens(prompt_token_ids)
        if cached:
            per_req = max(per_req - cached, 0)
        # +1 page per sequence: the first sampled token needs a slot.
        est_pages = n * (cdiv(per_req, alloc.page_size) + 1)
        floor = int(self.config.kv_admission_watermark * usable)
        if alloc.num_free_pages - est_pages < floor:
            return self._overloaded(
                "kv_pressure",
                f"{n} sequence(s) need ~{est_pages} KV page(s) but "
                f"only {alloc.num_free_pages}/{usable} are free "
                f"(watermark keeps {floor} free)",
            )
        return None

    def check(
        self,
        num_requests: int = 1,
        est_tokens: int = 0,
        prompt_token_ids: list[int] | None = None,
        slo_class: str | None = None,
    ) -> None:
        """Pure check (no reservation) — the HTTP layer calls this
        before opening an SSE stream so rejects become proper 429
        responses, not in-stream error frames."""
        err = self._check(
            num_requests, est_tokens, prompt_token_ids, slo_class
        )
        if err is not None:
            raise err

    def reserve(
        self,
        est_tokens: int,
        prompt_token_ids: list[int] | None = None,
        slo_class: str | None = None,
    ) -> None:
        """Authoritative admit for ONE request: re-checks the caps and
        reserves intake-pending capacity.  The reservation is released
        by ``consumed`` (engine thread drained the add) or ``release``
        (the add never reached the intake)."""
        err = self._check(1, est_tokens, prompt_token_ids, slo_class)
        if err is not None:
            raise err
        with self._lock:
            self._pending_requests += 1
            self._pending_tokens += est_tokens
            if self.qos.enabled:
                name = self.qos.resolve(slo_class).name
                self._pending_by_class[name] = (
                    self._pending_by_class.get(name, 0) + 1
                )
                self._pending_tokens_by_class[name] = (
                    self._pending_tokens_by_class.get(name, 0) + est_tokens
                )

    def consumed(self, est_tokens: int, slo_class: str | None = None) -> None:
        """Engine thread: one reserved add left the intake (it is now
        scheduler state, counted there)."""
        self.release(est_tokens, slo_class)

    def release(self, est_tokens: int, slo_class: str | None = None) -> None:
        with self._lock:
            self._pending_requests = max(self._pending_requests - 1, 0)
            self._pending_tokens = max(self._pending_tokens - est_tokens, 0)
            if self.qos.enabled:
                name = self.qos.resolve(slo_class).name
                self._pending_by_class[name] = max(
                    self._pending_by_class.get(name, 0) - 1, 0
                )
                self._pending_tokens_by_class[name] = max(
                    self._pending_tokens_by_class.get(name, 0) - est_tokens,
                    0,
                )


def estimate_prompt_tokens(
    prompt: str | None, prompt_token_ids: list[int] | None
) -> int:
    """Admission-time token estimate.  Exact when ids are in hand (the
    API layer tokenizes first); a ~4-chars-per-token heuristic for raw
    text (only the offline/programmatic path) — caps are load-shedding
    guardrails, not billing, so an estimate is fine."""
    if prompt_token_ids is not None:
        return len(prompt_token_ids)
    if prompt:
        return len(prompt) // 4 + 1
    return 1
