"""Paged KV-cache page allocator.

The TPU-native analog of vLLM's KV block manager (the engine capability the
reference delegates to the vllm package — SURVEY.md §2.3, "KV block
manager").  Pages are fixed-size chunks of `page_size` token slots in a
flat HBM pool; a request owns an ordered list of page ids (its block
table).  Allocation is O(1) from a free list; freeing returns pages LIFO so
recently-touched HBM is reused first.

Slot addressing: token `t` of a request lives at flat slot
``page_ids[t // page_size] * page_size + t % page_size`` — the layout the
attention kernels and the KV scatter in the model runner share.

``PrefixCachingAllocator`` extends this with automatic prefix caching
(vLLM's ``--enable-prefix-caching`` from Kwon et al. 2023; the hash-chain
cousin of SGLang's RadixAttention, Zheng et al. 2024 — see PAPERS.md):
full pages are content-addressed, freed pages park in an LRU queue
instead of becoming garbage, and later requests re-attach them
ref-counted, skipping the prefill of the shared prefix.

``RadixPrefixCachingAllocator`` (ISSUE 14 tentpole) upgrades the index
from the flat hash map to a real radix tree keyed by token sequences —
one node per full page, children keyed by the next page's exact token
tuple, ref-counted interior nodes, and **leaf-first cache-aware LRU
eviction** (a hot chain's interior pages can never be stranded by the
eviction of an unrelated leaf, and matching a prefix refreshes the whole
chain).  It also owns the second tier: evicted-but-indexed pages spill
to a bounded host-DRAM pool instead of evaporating, and stream back into
freshly allocated HBM pages ahead of a prefill resume (the scheduler
treats restored pages as computed).  The allocator is pure bookkeeping —
the actual KV bytes move worker-side (``model_runner._apply_kv_tier_ops``)
driven by the (page, slot) spans this class queues onto each
``SchedulerOutput``.
"""

from __future__ import annotations

import hashlib
import heapq
from collections import OrderedDict
from dataclasses import dataclass, field

from vllm_distributed_tpu.engine.request import Request
from vllm_distributed_tpu.utils import cdiv


class NoFreePagesError(RuntimeError):
    pass


class PageAllocator:
    def __init__(self, num_pages: int, page_size: int) -> None:
        self.num_pages = num_pages
        self.page_size = page_size
        # Free list as a stack; page 0 is reserved as the null/padding page
        # so block tables can be padded with 0 safely.
        self._free: list[int] = list(range(num_pages - 1, 0, -1))
        # req_id -> page ids
        self._allocated: dict[str, list[int]] = {}

    @property
    def num_free_pages(self) -> int:
        return len(self._free)

    def num_pages_needed(self, num_tokens: int) -> int:
        return cdiv(num_tokens, self.page_size)

    def can_allocate(self, req: Request, num_new_tokens: int) -> bool:
        have = len(self._allocated.get(req.request_id, ()))
        need = self.num_pages_needed(req.num_computed_tokens + num_new_tokens)
        return need - have <= self.num_free_pages

    def allocate(self, req: Request, num_new_tokens: int) -> list[int]:
        """Ensure req owns enough pages to cover `num_computed_tokens +
        num_new_tokens` tokens. Returns the newly granted page ids."""
        pages = self._allocated.setdefault(req.request_id, [])
        need = self.num_pages_needed(req.num_computed_tokens + num_new_tokens)
        new_pages: list[int] = []
        while len(pages) < need:
            if not self._free:
                # Roll back: caller decides to preempt.
                for p in new_pages:
                    pages.remove(p)
                    self._free.append(p)
                raise NoFreePagesError(
                    f"out of KV pages ({self.num_pages} total)"
                )
            p = self._free.pop()
            pages.append(p)
            new_pages.append(p)
        req.page_ids = pages
        return new_pages

    def free(self, req: Request) -> None:
        pages = self._allocated.pop(req.request_id, [])
        # LIFO reuse.
        self._free.extend(reversed(pages))
        req.page_ids = []

    def get_page_ids(self, req_id: str) -> list[int]:
        return self._allocated.get(req_id, [])

    def estimate_cached_tokens(
        self, token_ids: list[int] | None
    ) -> int:
        """Admission-time estimate of how many of ``token_ids`` are
        already resident as cached KV (ISSUE 8 KV backpressure).  The
        base allocator caches nothing."""
        return 0

    def slot_for_token(self, req: Request, token_idx: int) -> int:
        page = req.page_ids[token_idx // self.page_size]
        return page * self.page_size + token_idx % self.page_size


def hash_page_tokens(parent_key: bytes, token_ids: list[int]) -> bytes:
    """Content address of one FULL page: sha256 over the parent page's
    key followed by this page's token ids.  Chaining the parent key means
    identical page content under different prefixes gets different keys —
    a page's KV depends on every token before it, not just its own."""
    h = hashlib.sha256(parent_key)
    for t in token_ids:
        h.update(int(t).to_bytes(8, "little", signed=True))
    return h.digest()


class PrefixCachingAllocator(PageAllocator):
    """PageAllocator with content-addressed KV page reuse.

    Every full page whose KV has actually been computed is registered
    under ``hash_page_tokens(parent_key, page_tokens)``.  Pages released
    by finished/preempted requests keep their registration and move to an
    LRU queue (still counted free) instead of the plain free list; a new
    request whose prompt walks the same hash chain re-attaches them with
    a ref-count bump and starts prefill after the cached prefix.
    Allocation draws from the free list first and evicts the
    least-recently-freed cached page only when it must.

    Shared pages need no copy-on-write: only full computed pages are ever
    shared, hits stop at a page boundary strictly inside the prompt, and
    every token from the hit onward is written into freshly allocated
    pages — a shared page is never written.

    Evicting a page whose descendants are still registered strands them
    (lookups walk the chain from page 0 and stop at the gap); stranded
    entries stay harmlessly registered until their own eviction.
    """

    def __init__(self, num_pages: int, page_size: int) -> None:
        super().__init__(num_pages, page_size)
        # page -> live owner count (pages in the free list / LRU: absent).
        self._refs: dict[int, int] = {}
        # Content registry (invariant: page_key[p] == k  <=>
        # hash_to_page[k] == p; duplicate content never re-registers).
        self._hash_to_page: dict[bytes, int] = {}
        self._page_key: dict[int, bytes] = {}
        # Cached-free pages, least recently freed first (eviction order).
        self._lru: OrderedDict[int, None] = OrderedDict()
        # req_id -> number of pages registered so far.
        self._reg: dict[str, int] = {}
        # req_id -> memoized page hash chain.  A request's token prefix
        # never changes while it is alive (outputs only append; the
        # stop-string truncation happens as the request finishes), so
        # repeated queries of a waiting request and the later
        # registration pass reuse these instead of re-hashing.
        self._chains: dict[str, list[bytes]] = {}

    @property
    def num_free_pages(self) -> int:
        # Cached-free pages are reusable on demand: count them free.
        return len(self._free) + len(self._lru)

    def can_allocate_with_prefix(
        self, hit_pages: list[int], num_tokens_total: int
    ) -> bool:
        """Admission check for a request about to attach `hit_pages` and
        then prefill up to `num_tokens_total` tokens: attaching removes
        the cached-free hit pages from the free count, but also shrinks
        what remains to allocate."""
        need_new = self.num_pages_needed(num_tokens_total) - len(hit_pages)
        free = self.num_free_pages - sum(
            1 for p in hit_pages if p in self._lru
        )
        return need_new <= free

    def _pop_free_page(self) -> int:
        if self._free:
            return self._free.pop()
        if self._lru:
            # Evict the least-recently-freed cached page.
            page, _ = self._lru.popitem(last=False)
            key = self._page_key.pop(page)
            del self._hash_to_page[key]
            return page
        raise NoFreePagesError(f"out of KV pages ({self.num_pages} total)")

    def allocate(self, req: Request, num_new_tokens: int) -> list[int]:
        pages = self._allocated.setdefault(req.request_id, [])
        need = self.num_pages_needed(
            req.num_computed_tokens + num_new_tokens
        )
        new_pages: list[int] = []
        while len(pages) < need:
            try:
                p = self._pop_free_page()
            except NoFreePagesError:
                # Roll back: caller decides to preempt.  Evicted pages
                # lost their registration — a sliver of cache, never
                # correctness.
                for q in new_pages:
                    pages.remove(q)
                    self._refs.pop(q, None)
                    self._free.append(q)
                raise
            self._refs[p] = 1
            pages.append(p)
            new_pages.append(p)
        req.page_ids = pages
        return new_pages

    def free(self, req: Request) -> None:
        pages = self._allocated.pop(req.request_id, [])
        self._reg.pop(req.request_id, None)
        self._chains.pop(req.request_id, None)
        # Reverse order: plain pages reuse LIFO (like the base class) and
        # cached pages enter the LRU leaf-first, so eviction consumes the
        # chain tail before the (more shareable) root.
        for p in reversed(pages):
            refs = self._refs.get(p, 1) - 1
            if refs > 0:
                self._refs[p] = refs
                continue
            self._refs.pop(p, None)
            if p in self._page_key:
                self._lru[p] = None  # ref was live, so p cannot be in _lru
            else:
                self._free.append(p)
        req.page_ids = []

    # ---- prefix-cache surface (scheduler-facing) ----
    @staticmethod
    def registrable_tokens(req: Request) -> int:
        """Tokens whose KV rows are VALID and whose token ids exist on
        the host — the registration horizon.  This is where discarded
        KV rows are fenced off the cache:

        - fused-decode early stop: ``num_computed_tokens`` advanced by
          the full scan window but the host token list was truncated at
          the stop — rows past ``num_tokens`` are dead;
        - speculative decoding (ISSUE 11): the verify pass WRITES rows
          for every drafted position but the scheduler advances
          ``num_computed_tokens`` only by the accepted prefix, so
          rejected-draft rows sit past ``num_computed_tokens`` and are
          overwritten in place by the next window — never registered,
          never attachable by another request.

        Both clamps matter: registering a page containing a dead or
        rejected row would serve another request garbage KV under a
        hash computed from tokens that were never (validly) written.
        """
        return min(req.num_computed_tokens, req.num_tokens)

    def _chain(self, req: Request, upto_pages: int) -> list[bytes]:
        """The request's page hash chain, memoized and extended on
        demand (each page hashed at most once per request lifetime)."""
        keys = self._chains.setdefault(req.request_id, [])
        if len(keys) < upto_pages:
            ids = req.all_token_ids
            ps = self.page_size
            parent = keys[-1] if keys else b""
            for i in range(len(keys), upto_pages):
                parent = hash_page_tokens(parent, ids[i * ps : (i + 1) * ps])
                keys.append(parent)
        return keys

    def query_prefix(self, req: Request) -> tuple[int, list[int]]:
        """Longest registered page chain matching the request's tokens.
        Returns (num_cached_tokens, pages) without changing ownership.
        The hit always stops strictly below prefill_target at a page
        boundary: at least one token must be recomputed (the final step
        has to produce logits to sample from), and capping at the page
        boundary keeps every write of that recompute inside freshly
        allocated pages — shared pages are NEVER written, so a sharer's
        attention can't be perturbed by another request's prefill (XLA
        does not promise bit-identical KV across chunk shapes).  Partial
        pages never match: only full pages are ever registered."""
        prefill_target = req.prefill_target
        max_pages = min(req.num_tokens, prefill_target) // self.page_size
        keys = self._chain(req, max_pages)
        pages: list[int] = []
        for i in range(max_pages):
            page = self._hash_to_page.get(keys[i])
            if page is None:
                break
            pages.append(page)
        if pages and len(pages) * self.page_size >= prefill_target:
            pages.pop()  # fully cached prompt: recompute the whole tail page
        if not pages:
            return 0, []
        return len(pages) * self.page_size, pages

    def attach_prefix(self, req: Request, hit_pages: list[int]) -> None:
        """Adopt a queried page chain as the request's first pages
        (ref-counted; cached-free pages leave the LRU).  Must be the
        request's first allocation."""
        owned = self._allocated.setdefault(req.request_id, [])
        assert not owned, "attach_prefix after allocate"
        for p in hit_pages:
            self._lru.pop(p, None)
            self._refs[p] = self._refs.get(p, 0) + 1
        owned.extend(hit_pages)
        req.page_ids = owned
        # Registration resumes after the attached chain.
        self._reg[req.request_id] = len(hit_pages)

    def estimate_cached_tokens(
        self, token_ids: list[int] | None
    ) -> int:
        """Hash-walk the prompt's full pages against the content
        registry WITHOUT touching ownership — the prefix-cache-aware
        page estimate the admission watermark consults (ISSUE 8).

        Called from the event loop while the engine thread mutates the
        allocator: every access is a dict ``get`` (GIL-atomic, no
        iteration), so the worst outcome of a race is a slightly stale
        estimate — admission is a guardrail, not an allocation."""
        if not token_ids:
            return 0
        ps = self.page_size
        parent = b""
        hit_pages = 0
        for i in range(len(token_ids) // ps):
            parent = hash_page_tokens(parent, token_ids[i * ps : (i + 1) * ps])
            if self._hash_to_page.get(parent) is None:
                break
            hit_pages += 1
        return hit_pages * ps

    def register_computed(self, req: Request) -> None:
        """Register every newly FULL page whose tokens are now computed
        (call after num_computed_tokens advances).  Content that is
        already registered under another page is skipped — first writer
        wins, the duplicate page stays plain."""
        rid = req.request_id
        n_reg = self._reg.get(rid, 0)
        ps = self.page_size
        full = self.registrable_tokens(req) // ps
        if full <= n_reg:
            return
        pages = self._allocated.get(rid, [])
        keys = self._chain(req, full)
        while n_reg < full and n_reg < len(pages):
            key, page = keys[n_reg], pages[n_reg]
            if key not in self._hash_to_page and page not in self._page_key:
                self._hash_to_page[key] = page
                self._page_key[page] = key
            n_reg += 1
        self._reg[rid] = n_reg


class _RadixNode:
    """One full KV page in the radix tree.  ``key`` is the page's exact
    token tuple (the edge label from the parent — no hashing, so false
    positives are structurally impossible).  Exactly one of two
    residencies at a time: ``page`` set (HBM tier) or ``host_slot`` set
    (host-DRAM tier); a node with neither is detached from the tree."""

    __slots__ = (
        "key",
        "parent",
        "children",
        "page",
        "host_slot",
        "refs",
        "resident_children",
        "last_use",
        "stamp",
    )

    def __init__(self, key, parent, page=None) -> None:
        self.key = key
        self.parent = parent
        self.children: dict[tuple, _RadixNode] = {}
        self.page: int | None = page
        self.host_slot: int | None = None
        # Live request attachments.  Every request refs a contiguous
        # root-anchored path, so refs never increase with depth — the
        # leaf-first eviction order can rely on a refs==0 node having
        # only refs==0 resident descendants (modulo the duplicate-
        # content corner, which the resident-children gate still
        # protects).
        self.refs = 0
        # HBM-resident children (maintained incrementally): a node may
        # be evicted from HBM only once this drops to zero, which is
        # what makes eviction leaf-first.
        self.resident_children = 0
        self.last_use = 0
        # Lazy-heap validity stamp: heap entries carry the stamp they
        # were pushed with; any candidacy/recency change bumps it, so
        # stale entries are skipped at pop time.
        self.stamp = 0


@dataclass
class PrefixPlan:
    """Pure query result of one radix walk: the longest indexed chain
    matching a prompt, split by tier.  ``resident`` pages attach as-is;
    ``host`` nodes can be streamed back from the host tier into fresh
    HBM pages (the scheduler decides restore-vs-recompute against the
    ``restore_min_tokens`` crossover)."""

    resident: list[_RadixNode] = field(default_factory=list)
    host: list[_RadixNode] = field(default_factory=list)
    page_size: int = 0

    @property
    def resident_tokens(self) -> int:
        return len(self.resident) * self.page_size

    @property
    def host_tokens(self) -> int:
        return len(self.host) * self.page_size


class RadixPrefixCachingAllocator(PageAllocator):
    """Radix-tree prefix index + host-DRAM spill tier (ISSUE 14).

    Tree semantics: every FULL computed page is a node keyed by its
    exact token tuple under its parent page's node, so longest-prefix
    match is a root walk with no hash collisions.  Freed pages keep
    their node (cached-free, counted free); allocation evicts only
    **resident leaves of the resident subtree** (refs==0, no
    HBM-resident children), least-recently-used first, where "use"
    includes query matches — a chain a router keeps steering at stays
    warm end to end while cold chains are consumed tail-first.

    Spill tier: with ``host_pages > 0``, an evicted node's KV moves to a
    bounded host-DRAM slot instead of being discarded (the worker copies
    the page out before any step may overwrite it — the (page, slot)
    span rides the next dispatched SchedulerOutput ahead of the step's
    writes).  A later prompt whose chain walks into host-resident nodes
    restores them into freshly allocated HBM pages (slot→page spans,
    applied worker-side before the step that reads them) when the
    restorable run is at least ``restore_min_tokens``; below the
    crossover the tokens are recomputed and the host copies stay put.
    The host tier evicts leaf-first LRU like the HBM tier; pruning an
    unreachable subtree releases its slots.

    Shared pages still need no copy-on-write: only full computed pages
    are indexed, hits stop at a page boundary strictly inside the
    prompt, and restores write into freshly allocated pages before the
    step that reads them — an attached node's page is never written.
    """

    supports_tiered = True

    def __init__(
        self,
        num_pages: int,
        page_size: int,
        host_pages: int = 0,
        restore_min_tokens: int = 0,
    ) -> None:
        super().__init__(num_pages, page_size)
        self.host_pages = max(int(host_pages), 0)
        self.restore_min_tokens = max(int(restore_min_tokens), 0)
        self._root = _RadixNode(key=None, parent=None)
        # page id -> node whose KV lives in that page.
        self._page_node: dict[int, _RadixNode] = {}
        # req_id -> root-anchored node path the request holds refs on.
        self._req_nodes: dict[str, list[_RadixNode]] = {}
        # req_id -> pages registered so far / deepest chain node.
        self._reg: dict[str, int] = {}
        self._reg_node: dict[str, _RadixNode] = {}
        # Nodes with a page and refs==0 (evictable capacity).
        self._cached_free = 0
        # Lazy eviction heaps: (last_use, stamp, node); entries are
        # validated (stamp + candidacy) at pop time.
        self._hbm_heap: list[tuple[int, int, _RadixNode]] = []
        self._host_heap: list[tuple[int, int, _RadixNode]] = []
        self._tick = 0
        self._stamp = 0
        # Host tier state.
        self._host_free: list[int] = list(range(self.host_pages - 1, -1, -1))
        self._host_used = 0
        # Pending KV-tier spans for the next dispatched step, and slots
        # whose reuse must wait until the restore op that read them has
        # shipped (a spill into a just-restored slot inside ONE op batch
        # would be applied before the restore reads it).
        self._pending_spills: list[tuple[int, int]] = []
        self._pending_restores: list[tuple[int, int]] = []
        self._slots_freeing: list[int] = []
        # Pages whose restore span has been QUEUED but not SHIPPED: the
        # device copy does not exist yet, so evicting (and re-spilling)
        # such a page before its restore lands would capture garbage
        # into the host tier.  Cleared when the batch ships — later
        # spills ride later frames, which the worker applies after this
        # batch's restores.
        self._restoring_pages: set[int] = set()

    # ---- bookkeeping primitives ----
    def _touch(self, node: _RadixNode) -> None:
        self._tick += 1
        node.last_use = self._tick
        self._push_if_candidate(node)

    def _hbm_candidate(self, node: _RadixNode) -> bool:
        return (
            node.page is not None
            and node.refs == 0
            and node.resident_children == 0
            and node.parent is not None
            # A queued-but-unshipped restore target holds no real KV
            # yet (rollback can orphan one with refs==0): never spill
            # it before the restore lands.
            and node.page not in self._restoring_pages
        )

    def _host_candidate(self, node: _RadixNode) -> bool:
        return (
            node.host_slot is not None
            and node.refs == 0
            and not node.children
            and node.parent is not None
        )

    def _push_if_candidate(self, node: _RadixNode) -> None:
        self._stamp += 1
        node.stamp = self._stamp
        if self._hbm_candidate(node):
            heapq.heappush(
                self._hbm_heap, (node.last_use, node.stamp, node)
            )
            if len(self._hbm_heap) > 4 * len(self._page_node) + 64:
                self._compact(self._hbm_heap, self._hbm_candidate)
        elif self._host_candidate(node):
            heapq.heappush(
                self._host_heap, (node.last_use, node.stamp, node)
            )
            if len(self._host_heap) > 4 * self._host_used + 64:
                self._compact(self._host_heap, self._host_candidate)

    @staticmethod
    def _compact(heap, candidate) -> None:
        """Drop stale lazy-heap entries in place (touch-heavy,
        eviction-light workloads would otherwise grow the heap by one
        entry per chain touch, unbounded)."""
        live = [
            e for e in heap if e[2].stamp == e[1] and candidate(e[2])
        ]
        heap[:] = live
        heapq.heapify(heap)

    def _ref(self, node: _RadixNode) -> None:
        node.refs += 1
        if node.refs == 1 and node.page is not None:
            self._cached_free -= 1

    def _unref(self, node: _RadixNode) -> None:
        node.refs -= 1
        assert node.refs >= 0, "radix node ref underflow"
        if node.refs == 0:
            if node.page is not None:
                self._cached_free += 1
            self._push_if_candidate(node)

    @property
    def num_free_pages(self) -> int:
        # Cached-free node pages are reclaimable on demand (leaf-first).
        return len(self._free) + self._cached_free

    @property
    def host_slots_used(self) -> int:
        return self._host_used

    # ---- eviction ----
    def _take_host_slot(self) -> int | None:
        """A free host slot, evicting the LRU host leaf if the pool is
        full.  None when the host tier is disabled or unreclaimable."""
        if self.host_pages <= 0:
            return None
        if self._host_free:
            self._host_used += 1
            return self._host_free.pop()
        while self._host_heap:
            _, stamp, node = heapq.heappop(self._host_heap)
            if node.stamp != stamp or not self._host_candidate(node):
                continue
            slot = node.host_slot
            node.host_slot = None
            self._detach(node)
            # Slot handed straight to the caller: _host_used is
            # unchanged (one leaves the tier, one enters).
            return slot
        return None

    def _detach(self, node: _RadixNode) -> None:
        """Remove a pageless, slotless, childless node from the tree."""
        assert node.page is None and node.host_slot is None
        assert not node.children and node.refs == 0
        parent = node.parent
        del parent.children[node.key]
        node.parent = None
        self._stamp += 1
        node.stamp = self._stamp  # invalidate heap entries
        self._push_if_candidate(parent)

    def _prune_host_subtree(self, node: _RadixNode) -> None:
        """Release the (all-host) subtree under a node being evicted to
        nothing: its chains are unreachable once the parent's KV is
        gone."""
        for child in list(node.children.values()):
            self._prune_host_subtree(child)
            if child.host_slot is not None:
                self._host_free.append(child.host_slot)
                self._host_used -= 1
                child.host_slot = None
            self._detach(child)

    def _evict_one(self, allow_spill: bool = True) -> int:
        """Reclaim one HBM page: pop the least-recently-used resident
        leaf, spilling its KV to the host tier when there is (or can be
        made) room, discarding it otherwise.  ``allow_spill=False``
        forces the discard path — used when the reclaimed page will be
        written OUTSIDE the step stream (KV-import scatter over the aux
        path, ISSUE 15): a queued spill span would capture the imported
        content instead of the evicted page's, because the aux write
        lands before the next dispatched step applies the span."""
        while self._hbm_heap:
            _, stamp, node = heapq.heappop(self._hbm_heap)
            if node.stamp != stamp or not self._hbm_candidate(node):
                continue
            page = node.page
            node.page = None
            del self._page_node[page]
            self._cached_free -= 1
            parent = node.parent
            parent.resident_children -= 1
            slot = self._take_host_slot() if allow_spill else None
            if slot is not None:
                self._pending_spills.append((page, slot))
                node.host_slot = slot
                self._push_if_candidate(node)
            else:
                self._prune_host_subtree(node)
                self._detach(node)
            self._push_if_candidate(parent)
            return page
        raise NoFreePagesError(f"out of KV pages ({self.num_pages} total)")

    def _pop_free_page(self) -> int:
        if self._free:
            return self._free.pop()
        return self._evict_one()

    # ---- allocation / release ----
    def allocate(self, req: Request, num_new_tokens: int) -> list[int]:
        pages = self._allocated.setdefault(req.request_id, [])
        need = self.num_pages_needed(
            req.num_computed_tokens + num_new_tokens
        )
        new_pages: list[int] = []
        while len(pages) < need:
            try:
                p = self._pop_free_page()
            except NoFreePagesError:
                # Roll back: caller decides to preempt.  Evicted pages
                # lost their index entry (or moved to host) — a sliver
                # of cache, never correctness.
                for q in new_pages:
                    pages.remove(q)
                    self._free.append(q)
                raise
            pages.append(p)
            new_pages.append(p)
        req.page_ids = pages
        return new_pages

    def free(self, req: Request) -> None:
        rid = req.request_id
        pages = self._allocated.pop(rid, [])
        nodes = self._req_nodes.pop(rid, [])
        self._reg.pop(rid, None)
        self._reg_node.pop(rid, None)
        # Leaf-first unref so the chain tail enters evictability before
        # the (more shareable) root.
        for node in reversed(nodes):
            self._unref(node)
        # Plain pages (never registered, or duplicate content) return to
        # the free list; node pages stay with their node (cached-free).
        for p in reversed(pages):
            if p not in self._page_node:
                self._free.append(p)
        req.page_ids = []

    # ---- the radix walk (scheduler-facing) ----
    registrable_tokens = staticmethod(
        PrefixCachingAllocator.registrable_tokens
    )

    def _walk(
        self, token_ids: list[int], max_pages: int
    ) -> tuple[list[_RadixNode], list[_RadixNode]]:
        """Longest indexed chain matching ``token_ids``: the HBM-resident
        prefix, then the host-resident run behind it.  Stops at the
        first detached gap — and at a resident node BEHIND a host run
        (unreachable until its ancestors are restored)."""
        ps = self.page_size
        resident: list[_RadixNode] = []
        host: list[_RadixNode] = []
        node = self._root
        for i in range(max_pages):
            child = node.children.get(tuple(token_ids[i * ps : (i + 1) * ps]))
            if child is None:
                break
            if child.page is not None and not host:
                resident.append(child)
            elif child.host_slot is not None:
                host.append(child)
            else:
                break
            node = child
        return resident, host

    def plan_prefix(self, req: Request) -> PrefixPlan:
        """Pure tiered query (the radix analog of ``query_prefix``).
        The combined hit stops strictly below prefill_target at a page
        boundary — at least one token is always recomputed, and the
        fully-cached tail page is dropped so a shared page is never
        written (same contract as the hash-chain allocator)."""
        prefill_target = req.prefill_target
        max_pages = min(req.num_tokens, prefill_target) // self.page_size
        resident, host = self._walk(req.all_token_ids, max_pages)
        if (
            (resident or host)
            and (len(resident) + len(host)) * self.page_size
            >= prefill_target
        ):
            if host:
                host.pop()
            else:
                resident.pop()
        # Matching refreshes the WHOLE chain (cache-aware LRU): a chain
        # traffic keeps walking stays warm even while its tail is free.
        for node in resident:
            self._touch(node)
        for node in host:
            self._touch(node)
        return PrefixPlan(
            resident=resident, host=host, page_size=self.page_size
        )

    def query_prefix(self, req: Request) -> tuple[int, list[int]]:
        """Hash-chain-compatible view of ``plan_prefix``: the resident
        hit only (oracle tests and the flat-index scheduler path)."""
        plan = self.plan_prefix(req)
        return plan.resident_tokens, [n.page for n in plan.resident]

    def can_admit_plan(
        self, plan: PrefixPlan, num_new_tokens: int, restore: bool
    ) -> bool:
        """Admission check for attaching this plan and then prefilling
        ``num_new_tokens`` more: attaching removes the plan's
        cached-free resident pages from the free count; everything else
        (the prefill remainder AND, when restoring, the host run's
        target pages) must come out of what is left."""
        resident = plan.resident
        total = plan.resident_tokens + num_new_tokens
        if restore:
            total += plan.host_tokens
        need_new = self.num_pages_needed(total) - len(resident)
        free = self.num_free_pages - sum(
            1 for n in resident if n.refs == 0
        )
        return need_new <= free

    def attach_plan(
        self, req: Request, plan: PrefixPlan, restore: bool
    ) -> int:
        """Adopt a planned chain as the request's first pages: resident
        nodes attach ref-counted; with ``restore`` the host run is
        streamed back into freshly allocated pages (slot→page spans
        queued for the next dispatched step).  Atomic: on page
        exhaustion mid-restore everything is rolled back and
        NoFreePagesError propagates.  Returns the restored page count.
        Must be the request's first allocation."""
        rid = req.request_id
        owned = self._allocated.setdefault(rid, [])
        assert not owned, "attach_plan after allocate"
        nodes = list(plan.resident) + (list(plan.host) if restore else [])
        for node in nodes:
            self._ref(node)
        restored: list[int] = []
        if restore and plan.host:
            try:
                for _ in plan.host:
                    restored.append(self._pop_free_page())
            except NoFreePagesError:
                self._free.extend(reversed(restored))
                for node in reversed(nodes):
                    self._unref(node)
                raise
            for node, page in zip(plan.host, restored):
                self._pending_restores.append((node.host_slot, page))
                # The slot becomes reusable only after this op batch
                # ships (release_shipped_slots) — a spill reusing it in
                # the SAME batch would be applied before the restore.
                self._slots_freeing.append(node.host_slot)
                # ...and the target page is not evictable until then
                # either: its device copy does not exist yet.
                self._restoring_pages.add(page)
                node.host_slot = None
                node.page = page
                self._page_node[page] = node
                node.parent.resident_children += 1
        owned.extend(n.page for n in nodes)
        req.page_ids = owned
        self._req_nodes[rid] = nodes
        self._reg[rid] = len(nodes)
        self._reg_node[rid] = nodes[-1] if nodes else self._root
        return len(restored)

    # Hash-chain-compatible attach (flat callers and tests).
    def attach_prefix(self, req: Request, hit_pages: list[int]) -> None:
        plan = PrefixPlan(
            resident=[self._page_node[p] for p in hit_pages],
            page_size=self.page_size,
        )
        self.attach_plan(req, plan, restore=False)

    def can_allocate_with_prefix(
        self, hit_pages: list[int], num_tokens_total: int
    ) -> bool:
        plan = PrefixPlan(
            resident=[self._page_node[p] for p in hit_pages],
            page_size=self.page_size,
        )
        return self.can_admit_plan(
            plan, num_tokens_total - plan.resident_tokens, restore=False
        )

    def estimate_cached_tokens(
        self, token_ids: list[int] | None
    ) -> int:
        """Admission-watermark estimate (ISSUE 8): tokens the prompt
        would NOT need pages-to-prefill for.  Host-tier pages count as
        cached when their run would actually be restored (at/above the
        crossover) — a restore still needs target pages, but admission
        over-rejecting a hit that restores from DRAM is exactly the
        failure this estimate exists to avoid; the watermark keeps the
        slack.  Runs on the event loop against a tree the engine thread
        mutates: dict gets and attribute reads only, worst case a
        slightly stale estimate."""
        if not token_ids:
            return 0
        ps = self.page_size
        node = self._root
        resident = 0
        host = 0
        for i in range(len(token_ids) // ps):
            child = node.children.get(tuple(token_ids[i * ps : (i + 1) * ps]))
            if child is None:
                break
            if child.page is not None and host == 0:
                resident += 1
            elif child.host_slot is not None:
                host += 1
            else:
                break
            node = child
        tokens = resident * ps
        if host and host * ps >= self.restore_min_tokens:
            tokens += host * ps
        return tokens

    def register_computed(self, req: Request) -> None:
        """Index every newly FULL computed page (call after
        num_computed_tokens advances).  Content already indexed under
        another page is skipped — first writer wins, the duplicate page
        stays plain — except a host-resident duplicate, which is
        PROMOTED: the request's freshly computed resident page becomes
        the node's page and the stale host copy is released (keeps the
        resident-prefix/host-suffix chain invariant intact)."""
        rid = req.request_id
        n_reg = self._reg.get(rid, 0)
        ps = self.page_size
        full = self.registrable_tokens(req) // ps
        if full <= n_reg:
            return
        pages = self._allocated.get(rid, [])
        cursor = self._reg_node.get(rid, self._root)
        if cursor is None:
            return  # chain broken earlier (see below); stop registering
        if cursor is not self._root and (
            cursor.parent is None or cursor.page is None
        ):
            # The saved cursor was evicted or spilled between steps —
            # possible when it was a duplicate-content node this
            # request never reffed.  Registering under it would hang a
            # resident child off a host/detached node and corrupt the
            # residency invariant; the rest of this chain is a cache
            # sliver, so tombstone and skip (never correctness).
            self._reg_node[rid] = None
            return
        ids = req.all_token_ids
        nodes = self._req_nodes.setdefault(rid, [])
        while n_reg < full and n_reg < len(pages):
            key = tuple(ids[n_reg * ps : (n_reg + 1) * ps])
            page = pages[n_reg]
            child = cursor.children.get(key)
            if child is None:
                child = _RadixNode(key=key, parent=cursor, page=page)
                # Born owned: refs set directly (a page held by a live
                # request was never counted cached-free, so _ref's
                # accounting does not apply).
                child.refs = 1
                cursor.children[key] = child
                self._page_node[page] = child
                cursor.resident_children += 1
                nodes.append(child)
            elif child.page is None and child.host_slot is not None:
                # Promote: adopt the recomputed resident copy.
                assert child.refs == 0, "host-resident node with refs"
                self._host_free.append(child.host_slot)
                self._host_used -= 1
                child.host_slot = None
                child.page = page
                child.refs = 1
                self._page_node[page] = child
                child.parent.resident_children += 1
                nodes.append(child)
            # else: resident duplicate — first writer wins, our page
            # stays plain (freed to the plain list with the request).
            self._touch(child)
            cursor = child
            n_reg += 1
        self._reg[rid] = n_reg
        self._reg_node[rid] = cursor

    # ---- KV-page import (disaggregated prefill hand-off, ISSUE 15) ----
    def take_pages(self, n: int) -> list[int]:
        """Reserve ``n`` pages for an out-of-band KV import.  The pages
        leave every index (free list, radix nodes, request ownership)
        until ``adopt_chain`` registers or ``return_pages`` releases
        them — invisible to eviction, so the import scatter (which runs
        on the aux path, not the step stream) can never race a spill or
        a reuse.  Eviction to make room never spills (see _evict_one).
        Atomic: on exhaustion everything is rolled back."""
        pages: list[int] = []
        try:
            for _ in range(n):
                if self._free:
                    pages.append(self._free.pop())
                else:
                    pages.append(self._evict_one(allow_spill=False))
        except NoFreePagesError:
            self._free.extend(reversed(pages))
            raise
        return pages

    def return_pages(self, pages: list[int]) -> None:
        """Release pages reserved by ``take_pages`` (aborted/expired
        import) back to the plain free list."""
        self._free.extend(reversed(pages))

    def adopt_chain(
        self, token_ids: list[int], pages: list[int]
    ) -> tuple[int, list[int]]:
        """Index imported KV pages as a cached chain over ``token_ids``
        (one FULL page per entry, root-anchored) so the next prompt that
        walks the same tokens attaches them as computed — the decode
        side of the prefill/decode hand-off is exactly a prefix-cache
        warm-up with remote content.  Returns (adopted_pages,
        leftover_pages): a node that already exists resident keeps its
        page (first writer wins; ours is surplus), and the walk stops at
        a host-resident node (its DRAM copy is authoritative and a
        resident child under a host node would corrupt the residency
        invariant).  Leftover pages are returned to the free list here;
        callers must have scattered page CONTENT before adopting."""
        ps = self.page_size
        adopted = 0
        leftovers: list[int] = []
        cursor = self._root
        for i, page in enumerate(pages):
            key = tuple(token_ids[i * ps : (i + 1) * ps])
            if len(key) < ps:
                leftovers.append(page)
                continue
            child = cursor.children.get(key)
            if child is None:
                child = _RadixNode(key=key, parent=cursor, page=page)
                cursor.children[key] = child
                self._page_node[page] = child
                cursor.resident_children += 1
                # Born cached-free: no live request refs the import.
                self._cached_free += 1
                self._touch(child)
                adopted += 1
            elif child.page is not None:
                # Resident duplicate: keep the existing page, ours is
                # surplus (content identical by the checksummed wire
                # contract).
                self._touch(child)
                leftovers.append(page)
            else:
                # Host-resident (or detached-mid-walk) node: stop —
                # hanging a resident child under it would strand the
                # chain contract; the rest of the import is a cache
                # sliver, never correctness.
                leftovers.extend(pages[i:])
                break
            cursor = child
        if leftovers:
            self.return_pages(leftovers)
        return adopted, leftovers

    # ---- KV-tier op spans (drained by the scheduler per step) ----
    def take_tier_ops(
        self,
    ) -> tuple[list[tuple[int, int]], list[tuple[int, int]]]:
        """Drain the pending (page→slot) spill and (slot→page) restore
        spans for the next dispatched step.  Workers apply all spills,
        then all restores, then run the step — the order every span
        above was queued to be correct under."""
        spills, self._pending_spills = self._pending_spills, []
        restores, self._pending_restores = self._pending_restores, []
        return spills, restores

    def release_shipped_slots(self) -> None:
        """Call once the drained op batch is actually attached to a
        dispatched step: slots consumed by its restores become reusable
        for FUTURE spills (never for a spill in the same batch), and
        the restored pages become evictable again (a later spill rides
        a later frame, applied after this batch's restores)."""
        if self._slots_freeing:
            self._host_free.extend(self._slots_freeing)
            self._host_used -= len(self._slots_freeing)
            self._slots_freeing.clear()
        if self._restoring_pages:
            # Every queued restore is in the batch that just shipped
            # (take_tier_ops drains fully each schedule; holds merge).
            pages = self._restoring_pages
            self._restoring_pages = set()
            for page in pages:
                node = self._page_node.get(page)
                if node is not None:
                    self._push_if_candidate(node)
