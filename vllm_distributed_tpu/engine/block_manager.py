"""Paged KV-cache page allocator.

The TPU-native analog of vLLM's KV block manager (the engine capability the
reference delegates to the vllm package — SURVEY.md §2.3, "KV block
manager").  Pages are fixed-size chunks of `page_size` token slots in a
flat HBM pool; a request owns an ordered list of page ids (its block
table).  Allocation is O(1) from a free list; freeing returns pages LIFO so
recently-touched HBM is reused first.

Slot addressing: token `t` of a request lives at flat slot
``page_ids[t // page_size] * page_size + t % page_size`` — the layout the
attention kernels and the KV scatter in the model runner share.
"""

from __future__ import annotations

from vllm_distributed_tpu.engine.request import Request
from vllm_distributed_tpu.utils import cdiv


class NoFreePagesError(RuntimeError):
    pass


class PageAllocator:
    def __init__(self, num_pages: int, page_size: int) -> None:
        self.num_pages = num_pages
        self.page_size = page_size
        # Free list as a stack; page 0 is reserved as the null/padding page
        # so block tables can be padded with 0 safely.
        self._free: list[int] = list(range(num_pages - 1, 0, -1))
        # req_id -> page ids
        self._allocated: dict[str, list[int]] = {}

    @property
    def num_free_pages(self) -> int:
        return len(self._free)

    def num_pages_needed(self, num_tokens: int) -> int:
        return cdiv(num_tokens, self.page_size)

    def can_allocate(self, req: Request, num_new_tokens: int) -> bool:
        have = len(self._allocated.get(req.request_id, ()))
        need = self.num_pages_needed(req.num_computed_tokens + num_new_tokens)
        return need - have <= len(self._free)

    def allocate(self, req: Request, num_new_tokens: int) -> list[int]:
        """Ensure req owns enough pages to cover `num_computed_tokens +
        num_new_tokens` tokens. Returns the newly granted page ids."""
        pages = self._allocated.setdefault(req.request_id, [])
        need = self.num_pages_needed(req.num_computed_tokens + num_new_tokens)
        new_pages: list[int] = []
        while len(pages) < need:
            if not self._free:
                # Roll back: caller decides to preempt.
                for p in new_pages:
                    pages.remove(p)
                    self._free.append(p)
                raise NoFreePagesError(
                    f"out of KV pages ({self.num_pages} total)"
                )
            p = self._free.pop()
            pages.append(p)
            new_pages.append(p)
        req.page_ids = pages
        return new_pages

    def free(self, req: Request) -> None:
        pages = self._allocated.pop(req.request_id, [])
        # LIFO reuse.
        self._free.extend(reversed(pages))
        req.page_ids = []

    def get_page_ids(self, req_id: str) -> list[int]:
        return self._allocated.get(req_id, [])

    def slot_for_token(self, req: Request, token_idx: int) -> int:
        page = req.page_ids[token_idx // self.page_size]
        return page * self.page_size + token_idx % self.page_size
