"""Paged KV-cache page allocator.

The TPU-native analog of vLLM's KV block manager (the engine capability the
reference delegates to the vllm package — SURVEY.md §2.3, "KV block
manager").  Pages are fixed-size chunks of `page_size` token slots in a
flat HBM pool; a request owns an ordered list of page ids (its block
table).  Allocation is O(1) from a free list; freeing returns pages LIFO so
recently-touched HBM is reused first.

Slot addressing: token `t` of a request lives at flat slot
``page_ids[t // page_size] * page_size + t % page_size`` — the layout the
attention kernels and the KV scatter in the model runner share.

``PrefixCachingAllocator`` extends this with automatic prefix caching
(vLLM's ``--enable-prefix-caching`` from Kwon et al. 2023; the hash-chain
cousin of SGLang's RadixAttention, Zheng et al. 2024 — see PAPERS.md):
full pages are content-addressed, freed pages park in an LRU queue
instead of becoming garbage, and later requests re-attach them
ref-counted, skipping the prefill of the shared prefix.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict

from vllm_distributed_tpu.engine.request import Request
from vllm_distributed_tpu.utils import cdiv


class NoFreePagesError(RuntimeError):
    pass


class PageAllocator:
    def __init__(self, num_pages: int, page_size: int) -> None:
        self.num_pages = num_pages
        self.page_size = page_size
        # Free list as a stack; page 0 is reserved as the null/padding page
        # so block tables can be padded with 0 safely.
        self._free: list[int] = list(range(num_pages - 1, 0, -1))
        # req_id -> page ids
        self._allocated: dict[str, list[int]] = {}

    @property
    def num_free_pages(self) -> int:
        return len(self._free)

    def num_pages_needed(self, num_tokens: int) -> int:
        return cdiv(num_tokens, self.page_size)

    def can_allocate(self, req: Request, num_new_tokens: int) -> bool:
        have = len(self._allocated.get(req.request_id, ()))
        need = self.num_pages_needed(req.num_computed_tokens + num_new_tokens)
        return need - have <= self.num_free_pages

    def allocate(self, req: Request, num_new_tokens: int) -> list[int]:
        """Ensure req owns enough pages to cover `num_computed_tokens +
        num_new_tokens` tokens. Returns the newly granted page ids."""
        pages = self._allocated.setdefault(req.request_id, [])
        need = self.num_pages_needed(req.num_computed_tokens + num_new_tokens)
        new_pages: list[int] = []
        while len(pages) < need:
            if not self._free:
                # Roll back: caller decides to preempt.
                for p in new_pages:
                    pages.remove(p)
                    self._free.append(p)
                raise NoFreePagesError(
                    f"out of KV pages ({self.num_pages} total)"
                )
            p = self._free.pop()
            pages.append(p)
            new_pages.append(p)
        req.page_ids = pages
        return new_pages

    def free(self, req: Request) -> None:
        pages = self._allocated.pop(req.request_id, [])
        # LIFO reuse.
        self._free.extend(reversed(pages))
        req.page_ids = []

    def get_page_ids(self, req_id: str) -> list[int]:
        return self._allocated.get(req_id, [])

    def estimate_cached_tokens(
        self, token_ids: list[int] | None
    ) -> int:
        """Admission-time estimate of how many of ``token_ids`` are
        already resident as cached KV (ISSUE 8 KV backpressure).  The
        base allocator caches nothing."""
        return 0

    def slot_for_token(self, req: Request, token_idx: int) -> int:
        page = req.page_ids[token_idx // self.page_size]
        return page * self.page_size + token_idx % self.page_size


def hash_page_tokens(parent_key: bytes, token_ids: list[int]) -> bytes:
    """Content address of one FULL page: sha256 over the parent page's
    key followed by this page's token ids.  Chaining the parent key means
    identical page content under different prefixes gets different keys —
    a page's KV depends on every token before it, not just its own."""
    h = hashlib.sha256(parent_key)
    for t in token_ids:
        h.update(int(t).to_bytes(8, "little", signed=True))
    return h.digest()


class PrefixCachingAllocator(PageAllocator):
    """PageAllocator with content-addressed KV page reuse.

    Every full page whose KV has actually been computed is registered
    under ``hash_page_tokens(parent_key, page_tokens)``.  Pages released
    by finished/preempted requests keep their registration and move to an
    LRU queue (still counted free) instead of the plain free list; a new
    request whose prompt walks the same hash chain re-attaches them with
    a ref-count bump and starts prefill after the cached prefix.
    Allocation draws from the free list first and evicts the
    least-recently-freed cached page only when it must.

    Shared pages need no copy-on-write: only full computed pages are ever
    shared, hits stop at a page boundary strictly inside the prompt, and
    every token from the hit onward is written into freshly allocated
    pages — a shared page is never written.

    Evicting a page whose descendants are still registered strands them
    (lookups walk the chain from page 0 and stop at the gap); stranded
    entries stay harmlessly registered until their own eviction.
    """

    def __init__(self, num_pages: int, page_size: int) -> None:
        super().__init__(num_pages, page_size)
        # page -> live owner count (pages in the free list / LRU: absent).
        self._refs: dict[int, int] = {}
        # Content registry (invariant: page_key[p] == k  <=>
        # hash_to_page[k] == p; duplicate content never re-registers).
        self._hash_to_page: dict[bytes, int] = {}
        self._page_key: dict[int, bytes] = {}
        # Cached-free pages, least recently freed first (eviction order).
        self._lru: OrderedDict[int, None] = OrderedDict()
        # req_id -> number of pages registered so far.
        self._reg: dict[str, int] = {}
        # req_id -> memoized page hash chain.  A request's token prefix
        # never changes while it is alive (outputs only append; the
        # stop-string truncation happens as the request finishes), so
        # repeated queries of a waiting request and the later
        # registration pass reuse these instead of re-hashing.
        self._chains: dict[str, list[bytes]] = {}

    @property
    def num_free_pages(self) -> int:
        # Cached-free pages are reusable on demand: count them free.
        return len(self._free) + len(self._lru)

    def can_allocate_with_prefix(
        self, hit_pages: list[int], num_tokens_total: int
    ) -> bool:
        """Admission check for a request about to attach `hit_pages` and
        then prefill up to `num_tokens_total` tokens: attaching removes
        the cached-free hit pages from the free count, but also shrinks
        what remains to allocate."""
        need_new = self.num_pages_needed(num_tokens_total) - len(hit_pages)
        free = self.num_free_pages - sum(
            1 for p in hit_pages if p in self._lru
        )
        return need_new <= free

    def _pop_free_page(self) -> int:
        if self._free:
            return self._free.pop()
        if self._lru:
            # Evict the least-recently-freed cached page.
            page, _ = self._lru.popitem(last=False)
            key = self._page_key.pop(page)
            del self._hash_to_page[key]
            return page
        raise NoFreePagesError(f"out of KV pages ({self.num_pages} total)")

    def allocate(self, req: Request, num_new_tokens: int) -> list[int]:
        pages = self._allocated.setdefault(req.request_id, [])
        need = self.num_pages_needed(
            req.num_computed_tokens + num_new_tokens
        )
        new_pages: list[int] = []
        while len(pages) < need:
            try:
                p = self._pop_free_page()
            except NoFreePagesError:
                # Roll back: caller decides to preempt.  Evicted pages
                # lost their registration — a sliver of cache, never
                # correctness.
                for q in new_pages:
                    pages.remove(q)
                    self._refs.pop(q, None)
                    self._free.append(q)
                raise
            self._refs[p] = 1
            pages.append(p)
            new_pages.append(p)
        req.page_ids = pages
        return new_pages

    def free(self, req: Request) -> None:
        pages = self._allocated.pop(req.request_id, [])
        self._reg.pop(req.request_id, None)
        self._chains.pop(req.request_id, None)
        # Reverse order: plain pages reuse LIFO (like the base class) and
        # cached pages enter the LRU leaf-first, so eviction consumes the
        # chain tail before the (more shareable) root.
        for p in reversed(pages):
            refs = self._refs.get(p, 1) - 1
            if refs > 0:
                self._refs[p] = refs
                continue
            self._refs.pop(p, None)
            if p in self._page_key:
                self._lru[p] = None  # ref was live, so p cannot be in _lru
            else:
                self._free.append(p)
        req.page_ids = []

    # ---- prefix-cache surface (scheduler-facing) ----
    @staticmethod
    def registrable_tokens(req: Request) -> int:
        """Tokens whose KV rows are VALID and whose token ids exist on
        the host — the registration horizon.  This is where discarded
        KV rows are fenced off the cache:

        - fused-decode early stop: ``num_computed_tokens`` advanced by
          the full scan window but the host token list was truncated at
          the stop — rows past ``num_tokens`` are dead;
        - speculative decoding (ISSUE 11): the verify pass WRITES rows
          for every drafted position but the scheduler advances
          ``num_computed_tokens`` only by the accepted prefix, so
          rejected-draft rows sit past ``num_computed_tokens`` and are
          overwritten in place by the next window — never registered,
          never attachable by another request.

        Both clamps matter: registering a page containing a dead or
        rejected row would serve another request garbage KV under a
        hash computed from tokens that were never (validly) written.
        """
        return min(req.num_computed_tokens, req.num_tokens)

    def _chain(self, req: Request, upto_pages: int) -> list[bytes]:
        """The request's page hash chain, memoized and extended on
        demand (each page hashed at most once per request lifetime)."""
        keys = self._chains.setdefault(req.request_id, [])
        if len(keys) < upto_pages:
            ids = req.all_token_ids
            ps = self.page_size
            parent = keys[-1] if keys else b""
            for i in range(len(keys), upto_pages):
                parent = hash_page_tokens(parent, ids[i * ps : (i + 1) * ps])
                keys.append(parent)
        return keys

    def query_prefix(self, req: Request) -> tuple[int, list[int]]:
        """Longest registered page chain matching the request's tokens.
        Returns (num_cached_tokens, pages) without changing ownership.
        The hit always stops strictly below prefill_target at a page
        boundary: at least one token must be recomputed (the final step
        has to produce logits to sample from), and capping at the page
        boundary keeps every write of that recompute inside freshly
        allocated pages — shared pages are NEVER written, so a sharer's
        attention can't be perturbed by another request's prefill (XLA
        does not promise bit-identical KV across chunk shapes).  Partial
        pages never match: only full pages are ever registered."""
        prefill_target = req.prefill_target
        max_pages = min(req.num_tokens, prefill_target) // self.page_size
        keys = self._chain(req, max_pages)
        pages: list[int] = []
        for i in range(max_pages):
            page = self._hash_to_page.get(keys[i])
            if page is None:
                break
            pages.append(page)
        if pages and len(pages) * self.page_size >= prefill_target:
            pages.pop()  # fully cached prompt: recompute the whole tail page
        if not pages:
            return 0, []
        return len(pages) * self.page_size, pages

    def attach_prefix(self, req: Request, hit_pages: list[int]) -> None:
        """Adopt a queried page chain as the request's first pages
        (ref-counted; cached-free pages leave the LRU).  Must be the
        request's first allocation."""
        owned = self._allocated.setdefault(req.request_id, [])
        assert not owned, "attach_prefix after allocate"
        for p in hit_pages:
            self._lru.pop(p, None)
            self._refs[p] = self._refs.get(p, 0) + 1
        owned.extend(hit_pages)
        req.page_ids = owned
        # Registration resumes after the attached chain.
        self._reg[req.request_id] = len(hit_pages)

    def estimate_cached_tokens(
        self, token_ids: list[int] | None
    ) -> int:
        """Hash-walk the prompt's full pages against the content
        registry WITHOUT touching ownership — the prefix-cache-aware
        page estimate the admission watermark consults (ISSUE 8).

        Called from the event loop while the engine thread mutates the
        allocator: every access is a dict ``get`` (GIL-atomic, no
        iteration), so the worst outcome of a race is a slightly stale
        estimate — admission is a guardrail, not an allocation."""
        if not token_ids:
            return 0
        ps = self.page_size
        parent = b""
        hit_pages = 0
        for i in range(len(token_ids) // ps):
            parent = hash_page_tokens(parent, token_ids[i * ps : (i + 1) * ps])
            if self._hash_to_page.get(parent) is None:
                break
            hit_pages += 1
        return hit_pages * ps

    def register_computed(self, req: Request) -> None:
        """Register every newly FULL page whose tokens are now computed
        (call after num_computed_tokens advances).  Content that is
        already registered under another page is skipped — first writer
        wins, the duplicate page stays plain."""
        rid = req.request_id
        n_reg = self._reg.get(rid, 0)
        ps = self.page_size
        full = self.registrable_tokens(req) // ps
        if full <= n_reg:
            return
        pages = self._allocated.get(rid, [])
        keys = self._chain(req, full)
        while n_reg < full and n_reg < len(pages):
            key, page = keys[n_reg], pages[n_reg]
            if key not in self._hash_to_page and page not in self._page_key:
                self._hash_to_page[key] = page
                self._page_key[page] = key
            n_reg += 1
        self._reg[rid] = n_reg
