"""SLO/goodput accounting (ISSUE 12 tentpole, part 1).

Every bench so far judged the system on tokens/s and raw percentiles,
but the north-star workloads are judged on **SLO attainment**: DistServe
(Zhong et al., OSDI 2024, PAPERS.md) defines *goodput* — requests
completed within their TTFT/ITL SLO — as the metric that actually
matters for serving, and Llumnix (Sun et al. 2024) shows fleet
scheduling is only as good as the per-replica load/latency signals.
This module is the measurement substrate ROADMAP items 4 (SLO-class
scheduling) and 5 (autoscaler) consume.

Three pieces:

- ``LogBucketHistogram``: an HDR-style log-bucket histogram over
  milliseconds whose state is a sparse ``{bucket_index: count}`` map of
  integers.  Merging is integer addition, so it is **associative and
  order-independent by construction** — the router can fold N replicas'
  histograms into a fleet view that is bit-equal to recomputing from
  the union of raw observations (tests/test_slo.py pins this with a
  property test).  Bucket geometry is fixed (8 sub-buckets per octave,
  ~9% relative resolution from 1 µs to ~12 days), so indices mean the
  same thing on every replica.
- ``SloAccounting``: per-request timeline records (admit → first token
  → per-token ITL, all monotonic-anchored via RequestMetrics'
  ``*_mono`` stamps) folded into per-class histograms and attainment
  tallies against configurable targets (``VDT_SLO_TTFT_MS`` /
  ``VDT_SLO_ITL_MS``).  A bounded ring of raw per-request timelines is
  kept for the bit-equality contract and ``tools/slo_report.py``.
- Class-name hygiene: the SLO class is a **label** on Prometheus
  families, so its cardinality must be bounded no matter what clients
  send (vdt-lint VDT009 enforces the same rule statically): names are
  sanitized to a small charset and the number of distinct classes is
  capped, with overflow folded into ``"other"``.

Target syntax (both env vars): ``"500"`` sets the ``default`` class;
``"default:500,interactive:200,batch:5000"`` sets per-class targets in
milliseconds.  A class without a target attains trivially (goodput
degenerates to completed throughput), so the accounting is always on
and costs two dict updates per request milestone.
"""

from __future__ import annotations

import math
import threading
from collections import deque
from dataclasses import dataclass, field

# Fixed bucket geometry: index 0 holds non-positive values; index i>0
# covers milliseconds in [2^((i-1)/8 - 10), 2^(i/8 - 10)) — 8 buckets
# per octave starting at ~1 µs.  _MAX_BUCKET caps the range at ~2^30 ms.
_SUB = 8
_OFFSET_OCTAVES = 10
_MAX_BUCKET = 1 + (_OFFSET_OCTAVES + 30) * _SUB

DEFAULT_CLASS = "default"
OVERFLOW_CLASS = "other"
# Distinct classes one replica tracks before folding into "other" —
# the bound that keeps slo_class a legal Prometheus label (VDT009).
MAX_CLASSES = 32
_CLASS_CHARS = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_.-"
)
_CLASS_MAX_LEN = 48


def bucket_index(ms: float) -> int:
    """Bucket index for a millisecond value (fixed geometry, above)."""
    if ms <= 0 or ms != ms:  # non-positive or NaN
        return 0
    idx = 1 + math.floor((math.log2(ms) + _OFFSET_OCTAVES) * _SUB)
    return min(max(idx, 1), _MAX_BUCKET)

def bucket_value_ms(idx: int) -> float:
    """Representative (geometric-mid) millisecond value of a bucket."""
    if idx <= 0:
        return 0.0
    return 2.0 ** ((idx - 0.5) / _SUB - _OFFSET_OCTAVES)


class LogBucketHistogram:
    """Sparse integer log-bucket histogram; merge = per-bucket addition
    (associative, commutative, idempotent on the empty histogram)."""

    __slots__ = ("counts", "total")

    def __init__(self, counts: dict[int, int] | None = None) -> None:
        self.counts: dict[int, int] = {}
        self.total = 0
        if counts:
            for idx, n in counts.items():
                idx, n = int(idx), int(n)
                if n > 0:
                    self.counts[idx] = self.counts.get(idx, 0) + n
                    self.total += n

    def observe_ms(self, ms: float, n: int = 1) -> int:
        idx = bucket_index(ms)
        self.counts[idx] = self.counts.get(idx, 0) + n
        self.total += n
        return idx

    def observe_bucket(self, idx: int, n: int = 1) -> None:
        idx = int(idx)
        self.counts[idx] = self.counts.get(idx, 0) + n
        self.total += n

    def merge(self, other: "LogBucketHistogram") -> "LogBucketHistogram":
        """Return a NEW histogram = self + other (inputs untouched)."""
        out = LogBucketHistogram(self.counts)
        for idx, n in other.counts.items():
            out.counts[idx] = out.counts.get(idx, 0) + n
            out.total += n
        return out

    def percentile_ms(self, q: float) -> float | None:
        """Representative value at quantile ``q`` in [0, 1]."""
        if self.total == 0:
            return None
        rank = max(1, math.ceil(q * self.total))
        seen = 0
        for idx in sorted(self.counts):
            seen += self.counts[idx]
            if seen >= rank:
                return bucket_value_ms(idx)
        return bucket_value_ms(max(self.counts))  # pragma: no cover

    def to_dict(self) -> dict:
        """Wire form: string keys (JSON object keys are strings)."""
        return {
            "counts": {str(i): n for i, n in sorted(self.counts.items())},
            "total": self.total,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "LogBucketHistogram":
        return cls(
            {int(i): int(n) for i, n in (d.get("counts") or {}).items()}
        )

    def __eq__(self, other) -> bool:
        if not isinstance(other, LogBucketHistogram):
            return NotImplemented
        a = {i: n for i, n in self.counts.items() if n}
        b = {i: n for i, n in other.counts.items() if n}
        return a == b


def sanitize_class(name: str | None) -> str:
    """Bound the label space: empty/None → default; hostile names are
    filtered to the legal charset and truncated, never passed through."""
    if not name:
        return DEFAULT_CLASS
    cleaned = "".join(c for c in str(name)[:_CLASS_MAX_LEN] if c in _CLASS_CHARS)
    return cleaned or DEFAULT_CLASS


def parse_class_targets(raw: str) -> dict[str, float]:
    """Parse ``VDT_SLO_TTFT_MS``/``VDT_SLO_ITL_MS``: a bare number sets
    the default class; ``class:ms`` entries (comma-separated) set
    per-class targets.  Unparseable entries are ignored (telemetry
    must not take the server down); 0/negative disables the target."""
    targets: dict[str, float] = {}
    for piece in (raw or "").split(","):
        piece = piece.strip()
        if not piece:
            continue
        cls, sep, value = piece.rpartition(":")
        cls = sanitize_class(cls) if sep else DEFAULT_CLASS
        try:
            ms = float(value)
        except ValueError:
            continue
        if ms > 0:
            targets[cls] = ms
    return targets


@dataclass
class _ClassState:
    """Per-SLO-class accumulators (one replica's view)."""

    ttft_hist: LogBucketHistogram = field(default_factory=LogBucketHistogram)
    itl_hist: LogBucketHistogram = field(default_factory=LogBucketHistogram)
    requests: int = 0
    ttft_attained: int = 0
    itl_attained: int = 0
    goodput: int = 0


# Finish reasons that can count toward goodput: the request delivered
# its complete answer.  Sheds/timeouts/aborts are outcomes, not goodput.
_COMPLETED_REASONS = frozenset(("stop", "length"))


class SloAccounting:
    """Per-class SLO attainment and goodput for ONE replica.

    Mutated from the engine thread (via EngineMetrics); ``snapshot`` is
    read from the event loop (``/slo``), so state is guarded by a small
    lock — every record path is O(1) dict work under it.
    """

    def __init__(
        self,
        ttft_targets: dict[str, float] | None = None,
        itl_targets: dict[str, float] | None = None,
        max_classes: int = MAX_CLASSES,
        timeline_ring: int = 1024,
    ) -> None:
        if ttft_targets is None or itl_targets is None:
            from vllm_distributed_tpu import envs

            if ttft_targets is None:
                ttft_targets = parse_class_targets(envs.VDT_SLO_TTFT_MS)
            if itl_targets is None:
                itl_targets = parse_class_targets(envs.VDT_SLO_ITL_MS)
        self.ttft_targets = dict(ttft_targets)
        self.itl_targets = dict(itl_targets)
        self.max_classes = max_classes
        self._lock = threading.Lock()
        self.classes: dict[str, _ClassState] = {}
        # Raw per-request timelines (bounded): what the bit-equality
        # contract recomputes histograms from, and what slo_report.py
        # renders when pointed at a raw dump.
        self.timelines: deque[dict] = deque(maxlen=max(timeline_ring, 1))

    # ---- class resolution (bounded label space) ----
    def resolve(self, name: str | None) -> str:
        cls = sanitize_class(name)
        with self._lock:
            if cls in self.classes:
                return cls
            if len(self.classes) >= self.max_classes:
                return OVERFLOW_CLASS
            self.classes[cls] = _ClassState()
            return cls

    def _state(self, cls: str) -> _ClassState:
        # Lock held.  resolve() caps growth; OVERFLOW_CLASS always fits.
        st = self.classes.get(cls)
        if st is None:
            st = self.classes[cls] = _ClassState()
        return st

    # ---- observation (engine thread) ----
    def record_ttft(self, cls: str, seconds: float) -> None:
        with self._lock:
            self._state(cls).ttft_hist.observe_ms(seconds * 1000.0)

    def record_itl(self, cls: str, seconds: float, n: int = 1) -> int:
        """Record ``n`` inter-token intervals of ``seconds`` each;
        returns the bucket index so the caller can keep the request's
        own per-bucket tally (timeline recompute contract)."""
        with self._lock:
            return self._state(cls).itl_hist.observe_ms(
                seconds * 1000.0, n
            )

    def record_finished(
        self,
        cls: str,
        ttft_s: float | None,
        itl_max_s: float | None,
        itl_buckets: dict[int, int] | None,
        finish_reason: str | None,
    ) -> tuple[bool, bool, bool]:
        """One finished request: attainment against the class targets.
        Returns (ttft_attained, itl_attained, goodput) so EngineMetrics
        can mirror them into the Prometheus counters."""
        ttft_target = self.ttft_targets.get(cls)
        itl_target = self.itl_targets.get(cls)
        # No target ⇒ trivially attained (goodput == completed): the
        # accounting is always on, the SLO is opt-in per class.
        ttft_ok = (
            ttft_target is None
            or (ttft_s is not None and ttft_s * 1000.0 <= ttft_target)
        )
        # A request with ≤1 token has no inter-token intervals: its ITL
        # SLO is vacuously attained.
        itl_ok = (
            itl_target is None
            or itl_max_s is None
            or itl_max_s * 1000.0 <= itl_target
        )
        good = (
            ttft_ok and itl_ok and finish_reason in _COMPLETED_REASONS
        )
        with self._lock:
            st = self._state(cls)
            st.requests += 1
            if ttft_ok:
                st.ttft_attained += 1
            if itl_ok:
                st.itl_attained += 1
            if good:
                st.goodput += 1
            self.timelines.append(
                {
                    "slo_class": cls,
                    "ttft_ms": (
                        None if ttft_s is None else ttft_s * 1000.0
                    ),
                    "itl_max_ms": (
                        None if itl_max_s is None else itl_max_s * 1000.0
                    ),
                    "itl_buckets": {
                        str(i): n for i, n in (itl_buckets or {}).items()
                    },
                    "finish_reason": finish_reason,
                    "ttft_attained": ttft_ok,
                    "itl_attained": itl_ok,
                    "goodput": good,
                }
            )
        return ttft_ok, itl_ok, good

    def class_counts(self, cls: str) -> tuple[int, int]:
        """Cumulative (requests, goodput) for one class — the burn
        tracker's input (ISSUE 20)."""
        with self._lock:
            st = self.classes.get(cls)
            if st is None:
                return 0, 0
            return st.requests, st.goodput

    def itl_p99_ms(self) -> float | None:
        """p99 ITL across every class (merged log-bucket histograms):
        the ``vllm:itl_p99_ms`` gauge the router's anomaly scoring
        scrapes (ISSUE 20).  None until any interval is observed."""
        with self._lock:
            merged = None
            for st in self.classes.values():
                merged = (
                    st.itl_hist
                    if merged is None
                    else merged.merge(st.itl_hist)
                )
            if merged is None:
                return None
            return merged.percentile_ms(0.99)

    # ---- views (event loop) ----
    def snapshot(self, include_timelines: bool = True) -> dict:
        """JSON-ready replica view, served at ``/slo`` and merged by the
        router into the fleet view (``/router/slo``)."""
        with self._lock:
            classes = {
                cls: {
                    "requests": st.requests,
                    "ttft_attained": st.ttft_attained,
                    "itl_attained": st.itl_attained,
                    "goodput": st.goodput,
                    "ttft_hist": st.ttft_hist.to_dict(),
                    "itl_hist": st.itl_hist.to_dict(),
                    "ttft_target_ms": self.ttft_targets.get(cls),
                    "itl_target_ms": self.itl_targets.get(cls),
                }
                for cls, st in self.classes.items()
            }
            timelines = list(self.timelines) if include_timelines else None
        out = {"version": 1, "classes": classes}
        if timelines is not None:
            out["timelines"] = timelines
        return out


def merge_class_views(views: list[dict]) -> dict:
    """Fold N replica ``/slo`` class maps into one fleet view.  Pure
    integer addition + histogram merges, so the result is bit-equal no
    matter the merge order (the router's associativity contract).
    Targets are taken from the first replica that declares them (the
    fleet is expected to share one target config)."""
    fleet: dict[str, dict] = {}
    for view in views:
        for cls, d in (view.get("classes") or {}).items():
            agg = fleet.get(cls)
            if agg is None:
                agg = fleet[cls] = {
                    "requests": 0,
                    "ttft_attained": 0,
                    "itl_attained": 0,
                    "goodput": 0,
                    "ttft_hist": LogBucketHistogram(),
                    "itl_hist": LogBucketHistogram(),
                    "ttft_target_ms": d.get("ttft_target_ms"),
                    "itl_target_ms": d.get("itl_target_ms"),
                }
            for key in ("requests", "ttft_attained", "itl_attained", "goodput"):
                agg[key] += int(d.get(key, 0))
            agg["ttft_hist"] = agg["ttft_hist"].merge(
                LogBucketHistogram.from_dict(d.get("ttft_hist") or {})
            )
            agg["itl_hist"] = agg["itl_hist"].merge(
                LogBucketHistogram.from_dict(d.get("itl_hist") or {})
            )
            if agg["ttft_target_ms"] is None:
                agg["ttft_target_ms"] = d.get("ttft_target_ms")
            if agg["itl_target_ms"] is None:
                agg["itl_target_ms"] = d.get("itl_target_ms")
    out: dict[str, dict] = {}
    for cls, agg in fleet.items():
        requests = agg["requests"]
        ttft_hist: LogBucketHistogram = agg["ttft_hist"]
        itl_hist: LogBucketHistogram = agg["itl_hist"]
        out[cls] = {
            "requests": requests,
            "ttft_attained": agg["ttft_attained"],
            "itl_attained": agg["itl_attained"],
            "goodput": agg["goodput"],
            "goodput_ratio": (
                agg["goodput"] / requests if requests else None
            ),
            "ttft_target_ms": agg["ttft_target_ms"],
            "itl_target_ms": agg["itl_target_ms"],
            "ttft_p50_ms": ttft_hist.percentile_ms(0.5),
            "ttft_p99_ms": ttft_hist.percentile_ms(0.99),
            "itl_p50_ms": itl_hist.percentile_ms(0.5),
            "itl_p99_ms": itl_hist.percentile_ms(0.99),
            "ttft_hist": ttft_hist.to_dict(),
            "itl_hist": itl_hist.to_dict(),
        }
    return out
